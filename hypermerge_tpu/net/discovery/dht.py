"""Kademlia-lite DHT: announce/lookup by discovery id over UDP.

The reference treats discovery as a pluggable seam (src/SwarmInterface.ts
— any object with join/leave works; hyperswarm fills it in production).
This module is that filling: a 160-bit-keyspace DHT (Maymounkov &
Mazières 2002) sized for a fleet of repo daemons, not the open
internet — JSON datagrams on UDP, ed25519-signed announce records, and
the three primitives a swarm needs:

  find_node(target)   iterative routing-table walk toward `target`
  announce(key, addr) publish a signed+TTL'd {key -> dial address}
                      record on the k nodes closest to `key`
  lookup(key)         iterative walk that collects verified records

Routing state is the classic k-bucket array: one LRU-ordered bucket
per shared-prefix length, `HM_DHT_K` contacts each. A full bucket
NEVER evicts on sight — the long-lived node wins (Kademlia's uptime
heuristic): the newcomer parks in a bounded replacement cache while
the least-recently-seen contact is liveness-pinged; only an unanswered
ping evicts (and promotes the freshest replacement).

Announce records are self-certifying: the announcer signs
(key, host, port, ts, ttl) with its repo ed25519 identity (or an
ephemeral node key when anonymous), so a storing node — and every
looker-up — verifies without trusting the path the record traveled.
Expiry is the announcer's problem: records die at ts+ttl and the
owning swarm re-publishes every `HM_DHT_ANNOUNCE_S` (net/discovery/
swarm.py), so a crashed peer's stale address evaporates within a TTL.

Node ids are sha1(node public key): 160 bits, the keyspace of
`key_id(discovery_id)`. All RPCs ride one bound UDP socket per node, so
the datagram source address IS the node's reachable address (datacenter
/ loopback scope; NAT traversal is out of scope like the reference's).
"""

from __future__ import annotations

import base64
import hashlib
import itertools
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

from ...analysis.lockdep import make_lock
from ...utils import crypto
from ...utils.debug import log
from ... import telemetry

ID_BITS = 160
_MAX_DATAGRAM = 60 * 1024
_MAX_HOPS = 16  # iterative-walk backstop (log2 of any sane fleet)
_MAX_RECORDS_PER_REPLY = 32

# process-wide DHT counters (every node shares them, like net.tcp.*):
# the [dht] group in tools/top.py and the bench config_swarm block
_M_RPC_TX = telemetry.counter("dht.rpc_tx")
_M_RPC_RX = telemetry.counter("dht.rpc_rx")
_M_TIMEOUTS = telemetry.counter("dht.rpc_timeouts")
_M_LOOKUPS = telemetry.counter("dht.lookups")
_M_HOPS = telemetry.counter("dht.lookup_hops")
_M_FOUND = telemetry.counter("dht.records_found")
_M_ANNOUNCES = telemetry.counter("dht.announces")
_M_STORED = telemetry.counter("dht.records_stored")
_M_REJECTED = telemetry.counter("dht.records_rejected")
_M_EVICTIONS = telemetry.counter("dht.stale_evictions")
_M_SIGN_CACHE = telemetry.counter("dht.sign_cache_hits")
_M_SEEDS_TX = telemetry.counter("dht.seeds_tx")
_M_SEEDS_RX = telemetry.counter("dht.seeds_rx")


def _k() -> int:
    return int(os.environ.get("HM_DHT_K", "16"))


def _alpha() -> int:
    return int(os.environ.get("HM_DHT_ALPHA", "3"))


def _rpc_timeout_s() -> float:
    return float(os.environ.get("HM_DHT_RPC_TIMEOUT_S", "1"))


def _ttl_s() -> float:
    return float(os.environ.get("HM_DHT_TTL_S", "120"))


def bootstrap_from_env() -> List[Tuple[str, int]]:
    """Parse HM_DHT_BOOTSTRAP ("host:port,host:port") into addresses."""
    spec = os.environ.get("HM_DHT_BOOTSTRAP")
    if not spec:
        return []
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


def key_id(name: str) -> int:
    """A discovery id's position in the 160-bit keyspace."""
    return int.from_bytes(hashlib.sha1(name.encode("utf-8")).digest(), "big")


def _id_hex(i: int) -> str:
    return f"{i:040x}"


def _bucket_index(self_id: int, other: int) -> int:
    """0..159 by shared-prefix length; -1 for self (never bucketed)."""
    return (self_id ^ other).bit_length() - 1


class Contact(NamedTuple):
    id: int
    addr: Tuple[str, int]


class RoutingTable:
    """The k-bucket array. `observe` is the single ingest point: every
    datagram's sender lands here; a full bucket returns the LRU contact
    for the caller to liveness-probe (evict/refresh resolve the probe)
    while the newcomer waits in the bucket's replacement cache."""

    def __init__(self, self_id: int, k: Optional[int] = None) -> None:
        self.self_id = self_id
        self.k = _k() if k is None else k
        self._lock = make_lock("net.dht")
        # deque per bucket, LRU at the left / MRU at the right
        self._buckets: List[deque] = [deque() for _ in range(ID_BITS)]
        self._replacements: List[deque] = [deque() for _ in range(ID_BITS)]
        # buckets with a liveness probe in flight: at fleet scale every
        # datagram from a non-resident would otherwise fire a fresh
        # ping (the top bucket holds ~half the fleet) — one outstanding
        # probe per bucket bounds the storm
        self._probing: set = set()

    def observe(self, node_id: int, addr: Tuple[str, int]) -> Optional[Contact]:
        """Record a live sighting. Returns None when absorbed; returns
        the bucket's LRU contact when the bucket is full — the caller
        pings it and calls `refresh` (alive: newcomer stays parked) or
        `evict` (dead: freshest replacement promoted)."""
        i = _bucket_index(self.self_id, node_id)
        if i < 0:
            return None
        c = Contact(node_id, (addr[0], int(addr[1])))
        with self._lock:
            b = self._buckets[i]
            for existing in b:
                if existing.id == node_id:
                    b.remove(existing)
                    b.append(c)  # MRU + address refresh
                    return None
            if len(b) < self.k:
                b.append(c)
                return None
            r = self._replacements[i]
            for existing in list(r):
                if existing.id == node_id:
                    r.remove(existing)
            r.append(c)
            while len(r) > self.k:
                r.popleft()  # oldest parked newcomer sheds first
            if i in self._probing:
                return None  # a probe is already deciding this bucket
            self._probing.add(i)
            return b[0]

    def refresh(self, contact: Contact) -> None:
        """The probed LRU answered: it keeps its slot (moved to MRU)."""
        i = _bucket_index(self.self_id, contact.id)
        if i < 0:
            return
        with self._lock:
            self._probing.discard(i)
            b = self._buckets[i]
            for existing in list(b):
                if existing.id == contact.id:
                    b.remove(existing)
                    b.append(existing)
                    return

    def evict(self, contact: Contact) -> None:
        """The probed LRU never answered: drop it and promote the
        freshest parked replacement."""
        i = _bucket_index(self.self_id, contact.id)
        if i < 0:
            return
        with self._lock:
            self._probing.discard(i)
            b = self._buckets[i]
            for existing in list(b):
                if existing.id == contact.id:
                    b.remove(existing)
                    _M_EVICTIONS.add(1)
                    break
            r = self._replacements[i]
            while r and len(b) < self.k:
                cand = r.pop()  # freshest first
                if all(e.id != cand.id for e in b):
                    b.append(cand)

    def closest(self, target: int, n: Optional[int] = None) -> List[Contact]:
        with self._lock:
            all_c = [c for b in self._buckets for c in b]
        all_c.sort(key=lambda c: c.id ^ target)
        return all_c[: self.k if n is None else n]

    def size(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._buckets)

    def occupancy(self) -> Dict[int, int]:
        """Non-empty bucket index -> contact count (tools/meta.py)."""
        with self._lock:
            return {
                i: len(b) for i, b in enumerate(self._buckets) if b
            }


# ---------------------------------------------------------------------------
# signed announce records


def _record_bytes(rec: Dict[str, Any]) -> bytes:
    body = {
        k: rec[k] for k in ("key", "host", "port", "ts", "ttl", "pk")
    }
    return json.dumps(body, sort_keys=True, separators=(",", ":")).encode(
        "utf-8"
    )


def make_record(
    key_hex: str,
    host: str,
    port: int,
    seed: bytes,
    ttl: Optional[float] = None,
) -> Dict[str, Any]:
    """A signed announce record: `seed` (the repo's ed25519 identity,
    or the node's ephemeral key) certifies {key -> host:port} until
    ts+ttl."""
    pk = crypto.public_key(seed)
    rec = {
        "key": key_hex,
        "host": host,
        "port": int(port),
        "ts": round(time.time(), 3),
        "ttl": float(_ttl_s() if ttl is None else ttl),
        "pk": base64.b64encode(pk).decode("ascii"),
    }
    rec["sig"] = base64.b64encode(
        crypto.sign(_record_bytes(rec), seed)
    ).decode("ascii")
    return rec


def _seed_record_bytes(rec: Dict[str, Any]) -> bytes:
    body = {
        k: rec[k] for k in ("key", "doc", "ts", "ttl", "pk")
    }
    return json.dumps(body, sort_keys=True, separators=(",", ":")).encode(
        "utf-8"
    )


def make_seed_record(
    key_hex: str,
    doc_id: str,
    seed: bytes,
    ttl: Optional[float] = None,
) -> Dict[str, Any]:
    """A signed push-seed record (HM_DHT_PUSH_SEED): the announcer
    asks the k nodes closest to `key_hex` — the doc's own keyspace
    position — to OPEN `doc_id` and become replicas, so the creator
    stops serving the entire cold-join first wave alone."""
    pk = crypto.public_key(seed)
    rec = {
        "key": key_hex,
        "doc": str(doc_id),
        "ts": round(time.time(), 3),
        "ttl": float(_ttl_s() if ttl is None else ttl),
        "pk": base64.b64encode(pk).decode("ascii"),
    }
    rec["sig"] = base64.b64encode(
        crypto.sign(_seed_record_bytes(rec), seed)
    ).decode("ascii")
    return rec


def verify_seed_record(rec: Any, now: Optional[float] = None) -> bool:
    if not isinstance(rec, dict):
        return False
    try:
        pk = base64.b64decode(rec["pk"])
        sig = base64.b64decode(rec["sig"])
        ts = float(rec["ts"])
        ttl = float(rec["ttl"])
        payload = _seed_record_bytes(rec)
    except (KeyError, TypeError, ValueError):
        return False
    if not crypto.verify(payload, sig, pk):
        return False
    now = time.time() if now is None else now
    return ts + ttl > now and ts < now + 60


def verify_record(rec: Any, now: Optional[float] = None) -> bool:
    """Signature valid AND not expired AND not implausibly future-
    stamped (>60s of clock skew is a forged/replayed ts, not skew)."""
    if not isinstance(rec, dict):
        return False
    try:
        pk = base64.b64decode(rec["pk"])
        sig = base64.b64decode(rec["sig"])
        ts = float(rec["ts"])
        ttl = float(rec["ttl"])
        payload = _record_bytes(rec)
    except (KeyError, TypeError, ValueError):
        return False
    if not crypto.verify(payload, sig, pk):
        return False
    now = time.time() if now is None else now
    return ts + ttl > now and ts < now + 60


class RecordStore:
    """TTL'd announce records, one per (key, announcer pk), freshest
    ts wins. Expiry is lazy (reads prune) — announcers re-publish, so
    a key nobody reads or refreshes simply ages out on its next
    touch."""

    def __init__(self) -> None:
        self._lock = make_lock("net.dht.store")
        # key_hex -> {pk_b64 -> record}
        self._records: Dict[str, Dict[str, Dict[str, Any]]] = {}

    def put(self, rec: Any) -> bool:
        if not verify_record(rec):
            _M_REJECTED.add(1)
            return False
        with self._lock:
            by_pk = self._records.setdefault(rec["key"], {})
            old = by_pk.get(rec["pk"])
            if old is None or float(old["ts"]) <= float(rec["ts"]):
                by_pk[rec["pk"]] = rec
        _M_STORED.add(1)
        return True

    def get(self, key_hex: str) -> List[Dict[str, Any]]:
        now = time.time()
        with self._lock:
            by_pk = self._records.get(key_hex)
            if not by_pk:
                return []
            live = {
                pk: r
                for pk, r in by_pk.items()
                if float(r["ts"]) + float(r["ttl"]) > now
            }
            if live:
                self._records[key_hex] = live
            else:
                self._records.pop(key_hex, None)
            return list(live.values())

    def size(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._records.values())


# ---------------------------------------------------------------------------
# the node


class DhtNode:
    """One DHT participant: a bound UDP socket, a routing table, a
    record store, and the iterative find_node/announce/lookup walks.

    RPCs are fire-and-correlate: every request carries an `rpc` id; the
    reader thread resolves the pending entry (reply) or a timer fires
    it (timeout). The iterative walks batch `HM_DHT_ALPHA` in-flight
    probes per round and count rounds as hops (`dht.lookup_hops`)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        bootstrap: Optional[List[Tuple[str, int]]] = None,
        seed: Optional[bytes] = None,
        k: Optional[int] = None,
    ) -> None:
        self._seed = seed if seed is not None else os.urandom(32)
        self.public_key = crypto.public_key(self._seed)
        self.id = int.from_bytes(
            hashlib.sha1(self.public_key).digest(), "big"
        )
        # announce records sign with the OWNING repo's identity when the
        # swarm wires one (set_announce_seed); the ephemeral node key
        # covers anonymous nodes. Set before traffic flows.
        self._announce_seed = self._seed
        # announce-record signing cache: re-publishing an unchanged
        # {key,host,port,ttl} within the TTL window reuses the signed
        # record instead of paying an ed25519 sign per period per key
        self._sign_cache: Dict[Tuple, Dict[str, Any]] = {}
        # push-seed receiver state: hook fired once per doc id
        self._seed_hook: Optional[Callable[[str], None]] = None
        self._seeded: set = set()
        self.table = RoutingTable(self.id, k)
        self.records = RecordStore()
        self._plock = make_lock("net.dht.rpc")
        self._pending: Dict[int, Dict[str, Any]] = {}
        self._rpc_ids = itertools.count(1)
        self._closed = False
        self.bootstrap = list(
            bootstrap if bootstrap is not None else bootstrap_from_env()
        )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self.address: Tuple[str, int] = self._sock.getsockname()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"dht:{self.address[1]}",
        )
        self._reader.start()
        # ONE expiry sweeper per node, not a threading.Timer per RPC:
        # at fleet RPC rates a timer thread per probe piles into
        # thousands of live threads and the scheduler thrash makes its
        # own timeouts
        self._sweep_stop = threading.Event()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, daemon=True,
            name=f"dht-sweep:{self.address[1]}",
        )
        self._sweeper.start()

    @property
    def id_hex(self) -> str:
        return _id_hex(self.id)

    def set_announce_seed(self, seed: bytes) -> None:
        """Sign future announce records with the repo identity instead
        of the ephemeral node key (DhtSwarm.set_identity)."""
        self._announce_seed = seed
        self._sign_cache = {}  # cached records carry the old key

    def set_seed_hook(self, hook: Callable[[str], None]) -> None:
        """Push-seed receiver (HM_DHT_PUSH_SEED): `hook(doc_id)` fires
        once per doc named by a verified seed record addressed to this
        node (Network wires backend.open — the node becomes a replica)."""
        self._seed_hook = hook

    # -- inbound --------------------------------------------------------

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                data, addr = self._sock.recvfrom(_MAX_DATAGRAM + 4096)
            except OSError:
                return  # closed
            try:
                msg = json.loads(data.decode("utf-8"))
            except ValueError:
                continue  # corrupt datagram: skip
            if not isinstance(msg, dict):
                continue
            _M_RPC_RX.add(1)
            try:
                self._handle(msg, addr)
            except (KeyError, TypeError, ValueError) as e:
                # malformed frames from buggy peers must not kill the
                # reader (same contract as the TCP stack)
                log("net:dht", f"malformed dht msg from {addr}: {e}")

    def _handle(self, msg: Dict[str, Any], addr: Tuple[str, int]) -> None:
        sender = msg.get("from")
        if isinstance(sender, str):
            try:
                self._observe(int(sender, 16), addr)
            except ValueError:
                return
        t = msg.get("t")
        rid = msg.get("rpc")
        if t == "ping":
            self._send(addr, {"t": "pong", "rpc": rid})
        elif t == "find_node":
            target = int(msg["target"], 16)
            self._send(addr, {
                "t": "nodes",
                "rpc": rid,
                "nodes": self._node_triples(target),
            })
        elif t == "lookup":
            key = str(msg["key"])
            self._send(addr, {
                "t": "values",
                "rpc": rid,
                "records": self.records.get(key)[:_MAX_RECORDS_PER_REPLY],
                "nodes": self._node_triples(int(key, 16)),
            })
        elif t == "announce":
            ok = self.records.put(msg.get("record"))
            self._send(addr, {"t": "stored", "rpc": rid, "ok": ok})
        elif t == "seed":
            ok = self._handle_seed(msg.get("record"))
            self._send(addr, {"t": "stored", "rpc": rid, "ok": ok})
        elif t in ("pong", "nodes", "values", "stored"):
            self._resolve(rid, msg)

    def _handle_seed(self, rec: Any) -> bool:
        """A push-seed request landed (we are among the k closest to
        the doc's key). Verify the signature AND that the named doc
        really owns the record's keyspace position — a record may ask
        us to replicate only the doc whose key it is stored under."""
        if not verify_seed_record(rec):
            return False
        doc_id = str(rec["doc"])
        from ...utils import keys as keymod

        if rec["key"] != _id_hex(key_id(keymod.discovery_id(doc_id))):
            return False
        _M_SEEDS_RX.add(1)
        hook = self._seed_hook
        if hook is None or doc_id in self._seeded:
            return True
        self._seeded.add(doc_id)
        # off the reader thread: opening a doc does storage I/O and
        # may re-enter the network stack
        threading.Thread(
            target=lambda: hook(doc_id), daemon=True,
            name=f"dht-seed:{doc_id[:6]}",
        ).start()
        return True

    def _node_triples(self, target: int) -> List[List[Any]]:
        return [
            [_id_hex(c.id), c.addr[0], c.addr[1]]
            for c in self.table.closest(target)
        ]

    def _observe(self, node_id: int, addr: Tuple[str, int]) -> None:
        lru = self.table.observe(node_id, addr)
        if lru is not None:
            # full bucket: liveness-probe the LRU; the Kademlia uptime
            # rule — only an unanswered ping evicts
            self._send_rpc(
                lru.addr, {"t": "ping"},
                on_reply=lambda _m, c=lru: self.table.refresh(c),
                on_timeout=lambda c=lru: self.table.evict(c),
            )

    # -- outbound -------------------------------------------------------

    def _send(self, addr: Tuple[str, int], msg: Dict[str, Any]) -> None:
        msg.setdefault("from", self.id_hex)
        try:
            data = json.dumps(msg, separators=(",", ":")).encode("utf-8")
            if len(data) > _MAX_DATAGRAM:
                log("net:dht", f"oversized dht reply dropped ({len(data)}B)")
                return
            self._sock.sendto(data, addr)
            _M_RPC_TX.add(1)
        except OSError:
            pass  # closed socket / unreachable: timers handle the rest

    def _send_rpc(
        self,
        addr: Tuple[str, int],
        msg: Dict[str, Any],
        on_reply: Optional[Callable[[Dict[str, Any]], None]] = None,
        on_timeout: Optional[Callable[[], None]] = None,
        timeout: Optional[float] = None,
    ) -> None:
        if self._closed:
            # fail fast: an in-flight iterative walk on a closing node
            # must collapse instead of waiting out a timeout per round
            if on_timeout is not None:
                on_timeout()
            return
        rid = next(self._rpc_ids)
        timeout = _rpc_timeout_s() if timeout is None else timeout
        with self._plock:
            self._pending[rid] = {
                "on_reply": on_reply,
                "on_timeout": on_timeout,
                "deadline": time.monotonic() + timeout,
            }
        self._send(addr, {**msg, "rpc": rid})

    def _sweep_loop(self) -> None:
        """Expire pending RPCs past their deadline (the per-node
        timeout authority; replaces a thread per in-flight probe)."""
        while not self._sweep_stop.wait(0.05):
            now = time.monotonic()
            expired = []
            with self._plock:
                for rid, entry in list(self._pending.items()):
                    if entry["deadline"] <= now:
                        expired.append(self._pending.pop(rid))
            for entry in expired:
                _M_TIMEOUTS.add(1)
                cb = entry["on_timeout"]
                if cb is not None:
                    try:
                        cb()
                    except Exception as e:  # a probe hook must not
                        log("net:dht", f"timeout hook error: {e}")

    def _resolve(self, rid: Any, msg: Dict[str, Any]) -> None:
        with self._plock:
            entry = self._pending.pop(rid, None)
        if entry is None:
            return  # late reply after the sweep expired it
        cb = entry["on_reply"]
        if cb is not None:
            cb(msg)

    def _query_round(
        self,
        contacts: List[Contact],
        msg: Dict[str, Any],
        timeout: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """One alpha-wide probe round: send to every contact, wait the
        RPC timeout, return the replies that landed."""
        timeout = _rpc_timeout_s() if timeout is None else timeout
        done = threading.Event()
        replies: List[Dict[str, Any]] = []
        remaining = [len(contacts)]

        def _account() -> None:
            # reader thread (replies), sweeper thread (expiries) and
            # the caller (closed-node fast path) all decrement: the
            # RMW must serialize or a lost update waits out the full
            # round timeout instead of completing on the last reply
            with self._plock:
                remaining[0] -= 1
                settled = remaining[0] <= 0
            if settled:
                done.set()

        def on_reply(m: Dict[str, Any]) -> None:
            replies.append(m)  # GIL-atomic list append
            _account()

        for c in contacts:
            self._send_rpc(
                c.addr, dict(msg), on_reply=on_reply,
                on_timeout=_account, timeout=timeout,
            )
        done.wait(timeout + 0.5)
        return list(replies)

    def _iterative(
        self, target: int, msg: Dict[str, Any]
    ) -> Tuple[List[Contact], List[Dict[str, Any]], int]:
        """The Kademlia walk: probe the alpha closest unqueried
        contacts per round, absorb returned nodes, stop when the k
        closest known are all queried (or nothing new surfaces).
        Returns (k closest contacts, verified records seen, hops)."""
        alpha = _alpha()
        k = self.table.k
        shortlist: Dict[int, Contact] = {
            c.id: c for c in self.table.closest(target)
        }
        queried: set = set()
        records: Dict[str, Dict[str, Any]] = {}
        hops = 0
        while hops < _MAX_HOPS:
            candidates = sorted(
                (c for c in shortlist.values() if c.id not in queried),
                key=lambda c: c.id ^ target,
            )
            # termination: every one of the k closest known is queried
            frontier = sorted(
                shortlist.values(), key=lambda c: c.id ^ target
            )[:k]
            if all(c.id in queried for c in frontier) or not candidates:
                break
            batch = candidates[:alpha]
            hops += 1
            replies = self._query_round(batch, msg)
            for c in batch:
                queried.add(c.id)
            for rep in replies:
                for r in rep.get("records", ()):
                    if verify_record(r):
                        old = records.get(r["pk"])
                        if old is None or float(old["ts"]) <= float(r["ts"]):
                            records[r["pk"]] = r
                for triple in rep.get("nodes", ()):
                    nid_hex, host, port = triple
                    nid = int(nid_hex, 16)
                    if nid != self.id and nid not in shortlist:
                        shortlist[nid] = Contact(nid, (str(host), int(port)))
        closest = sorted(
            shortlist.values(), key=lambda c: c.id ^ target
        )[:k]
        return closest, list(records.values()), hops

    # -- the three primitives ------------------------------------------

    def find_node(self, target: int) -> List[Contact]:
        closest, _recs, _hops = self._iterative(
            target, {"t": "find_node", "target": _id_hex(target)}
        )
        return closest

    def lookup(self, key_hex: str) -> List[Dict[str, Any]]:
        """Verified, unexpired announce records for `key_hex` — from
        the iterative walk AND our own store (we may be one of the k
        closest)."""
        _M_LOOKUPS.add(1)
        _closest, recs, hops = self._iterative(
            int(key_hex, 16), {"t": "lookup", "key": key_hex}
        )
        _M_HOPS.add(hops)
        by_pk = {r["pk"]: r for r in self.records.get(key_hex)}
        for r in recs:
            old = by_pk.get(r["pk"])
            if old is None or float(old["ts"]) <= float(r["ts"]):
                by_pk[r["pk"]] = r
        _M_FOUND.add(len(by_pk))
        return list(by_pk.values())

    def announce(
        self,
        key_hex: str,
        host: str,
        port: int,
        ttl: Optional[float] = None,
        seed_doc: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Publish a signed record for `key_hex` on the k nodes closest
        to it (plus our own store — a two-node fleet has no third party
        to delegate to). An unchanged {key,host,port,ttl} re-publish
        within the first half of the record's TTL window reuses the
        cached signature (`dht.sign_cache_hits`) — the second half
        re-signs so the record never expires out from under its
        refresher. `seed_doc` push-seeds the doc to the same k-closest
        targets (HM_DHT_PUSH_SEED), reusing the one walk."""
        ck = (key_hex, host, int(port), ttl)
        rec = self._sign_cache.get(ck)
        if (
            rec is not None
            and time.time() < float(rec["ts"]) + float(rec["ttl"]) / 2
        ):
            _M_SIGN_CACHE.add(1)
        else:
            rec = make_record(
                key_hex, host, port, self._announce_seed, ttl
            )
            self._sign_cache[ck] = rec
        self.records.put(rec)
        targets = self.find_node(int(key_hex, 16))
        for c in targets:
            self._send_rpc(c.addr, {"t": "announce", "record": rec})
        _M_ANNOUNCES.add(1)
        if seed_doc is not None:
            sk = ("seed", key_hex, seed_doc)
            srec = self._sign_cache.get(sk)
            if (
                srec is None
                or time.time() >= float(srec["ts"]) + float(srec["ttl"]) / 2
            ):
                srec = make_seed_record(
                    key_hex, seed_doc, self._announce_seed, ttl
                )
                self._sign_cache[sk] = srec
            else:
                _M_SIGN_CACHE.add(1)
            for c in targets:
                self._send_rpc(c.addr, {"t": "seed", "record": srec})
                _M_SEEDS_TX.add(1)
        return rec

    def bootstrap_now(self, timeout: Optional[float] = None) -> int:
        """Ping the bootstrap list (a dead entry just times out), then
        walk toward our own id to populate the near buckets. Returns
        the routing-table size — callers retry while it stays 0 (a
        bootstrap node that was down comes back within a period)."""
        for addr in self.bootstrap:
            if tuple(addr) != tuple(self.address):
                self._send_rpc(tuple(addr), {"t": "ping"}, timeout=timeout)
        # give the pongs one RPC window to land before walking
        deadline = time.monotonic() + (
            _rpc_timeout_s() if timeout is None else timeout
        )
        while self.table.size() == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        if self.table.size():
            self.find_node(self.id)
        return self.table.size()

    def close(self) -> None:
        self._closed = True
        self._sweep_stop.set()
        with self._plock:
            self._pending.clear()  # waiters are deadline-bounded
        try:
            self._sock.close()
        except OSError:
            pass
