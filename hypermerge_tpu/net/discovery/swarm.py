"""DhtSwarm — the Swarm seam filled by the DHT.

`Network.set_swarm` + `join(discovery_id)` is all the repo knows about
discovery (net/swarm.py Swarm). `LoopbackSwarm` fills the seam
in-process and `TcpSwarm` with explicit `connect()` addresses; this
class fills it fleet-style, hyperswarm-shaped:

- join(id, announce=True)  publishes a signed record mapping the id's
  DHT key to OUR TCP listen address, re-published every
  `HM_DHT_ANNOUNCE_S` (records expire at `HM_DHT_TTL_S`);
- join(id, lookup=True)    walks the DHT for announcers every
  `HM_DHT_LOOKUP_S` and supervise-dials a bounded subset of them
  (`HM_DHT_TARGETS` — the HyParView-style active view);
- leave(id)                stops the re-announce/lookup; the published
  record evaporates at its TTL, and live connections stay up (other
  shared docs may ride them — the supervisor owns their lifecycle).

Dials go through the wrapped `TcpSwarm`'s `SessionSupervisor`
(net/resilience.py), so redial/backoff/ban apply to DHT-discovered
addresses exactly as to explicit ones. Bootstrap comes from the
constructor or `HM_DHT_BOOTSTRAP`; an empty routing table re-runs the
bootstrap every maintenance pass, so a bootstrap node that was down at
our start is adopted when it appears (and a restarted one re-learns us
from our next announce walk).

Four rules keep a FLEET (not a pair) healthy, each earned by the
50-daemon soak failing without it:

- the active view is STABLE and SHARED across ids: targets persist
  while announced, deficits fill from addresses other ids already
  dialed, and only uncovered ids dial fresh (per-id resampling
  accumulated sessions toward a full mesh — a fleet doc carries one
  placeholder actor feed per peer);
- lookups are DEMAND-driven (`set_need_hook`): an id some verified
  peer already replicates spends no walk/dial budget, with a
  slow-cadence shuffle every 10th period so mutually-satisfied
  data-less ISLANDS still merge;
- of any announcer pair exactly ONE side dials (the higher address) —
  mutual dialing was a dedup-close + supervised-redial churn loop;
- walk work per maintenance pass is budgeted (`_PASS_BUDGET`), so a
  cursor merge that joins O(peers) ids at once becomes a trickle, not
  a storm.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ...analysis.lockdep import make_lock
from ...utils.debug import log
from ..swarm import DEFAULT_JOIN, JoinOptions, Swarm
from ..tcp import TcpSwarm
from .dht import DhtNode, key_id, _id_hex


def _announce_s() -> float:
    return float(os.environ.get("HM_DHT_ANNOUNCE_S", "30"))


def _lookup_s() -> float:
    return float(os.environ.get("HM_DHT_LOOKUP_S", "10"))


def _targets_n() -> int:
    return int(os.environ.get("HM_DHT_TARGETS", "4"))


# max announce/lookup walks one maintenance pass performs; remaining
# due ids carry over to the next pass (0.05-1s later)
_PASS_BUDGET = 8


class DhtSwarm(Swarm):
    """Swarm whose dial targets come from DHT lookups instead of
    explicit addresses. Wraps a TcpSwarm (inbound accept + supervised
    outbound) and a DhtNode (UDP announce/lookup)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        bootstrap: Optional[List[Tuple[str, int]]] = None,
        dht_port: int = 0,
        tcp: Optional[TcpSwarm] = None,
    ) -> None:
        self.tcp = tcp if tcp is not None else TcpSwarm(host, port)
        self.node = DhtNode(host, dht_port, bootstrap=bootstrap)
        self._lock = make_lock("net.dht.swarm")
        self._joined: Dict[str, JoinOptions] = {}
        # id -> dial addresses of the current sampled active view
        self._targets: Dict[str, Tuple[Tuple[str, int], ...]] = {}
        self._rng = random.Random()
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._pass_waiters: List[threading.Event] = []
        # demand hook (Network.set_swarm wires it): lookup walks run
        # only for ids the repo still NEEDS peers for. Without it,
        # every placeholder actor feed a doc's cursor carries (one per
        # peer in a fleet) gets walked and its single announcer dialed
        # — O(peers^2) sessions that the active-view bound cannot see.
        self._need: Optional[callable] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"dht-swarm:{self.tcp.address[1]}",
        )
        self._thread.start()

    # -- Swarm interface ------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        """The TCP listen address announce records publish."""
        return self.tcp.address

    @property
    def dht_address(self) -> Tuple[str, int]:
        """The UDP address other nodes bootstrap from."""
        return self.node.address

    @property
    def supervisor(self):
        return self.tcp.supervisor

    def set_identity(self, seed: Optional[bytes]) -> None:
        self.tcp.set_identity(seed)
        if seed is not None:
            # announce records certify with the repo identity, not the
            # ephemeral node key (Network.set_swarm wires this before
            # any join)
            self.node.set_announce_seed(seed)

    def set_need_hook(self, fn) -> None:
        """`fn(discovery_id) -> bool`: True while the repo still needs
        peers for the id (Network wires `no verified peer replicates
        it yet`). Lookups for satisfied ids are skipped — one
        connection replicates every shared feed, so the walk + dial
        budget goes to genuinely uncovered ids. When a doc's peers all
        churn away the hook flips back and lookups resume."""
        self._need = fn

    def set_seed_hook(self, fn) -> None:
        """`fn(doc_id)`: a verified push-seed record landed on our DHT
        node (HM_DHT_PUSH_SEED — we are among the doc key's k closest;
        Network wires backend.open so this node becomes a replica)."""
        self.node.set_seed_hook(fn)

    def join(
        self, discovery_id: str, options: JoinOptions = DEFAULT_JOIN
    ) -> None:
        with self._lock:
            self._joined[discovery_id] = options
        self._kick.set()

    def leave(self, discovery_id: str) -> None:
        with self._lock:
            self._joined.pop(discovery_id, None)
            self._targets.pop(discovery_id, None)

    def connect(self, address: Tuple[str, int]):
        """Explicit supervised dial (bootstrap escape hatch — the DHT
        path never needs it)."""
        return self.tcp.connect(address)

    def on_connection(self, cb) -> None:
        self.tcp.on_connection(cb)

    def destroy(self) -> None:
        self._stop.set()
        # close the node FIRST: an in-flight maintenance walk fails
        # fast (DhtNode._send_rpc short-circuits on a closed node)
        # instead of waiting out an RPC timeout per round
        self.node.close()
        self._kick.set()
        self._thread.join(timeout=2.0)
        self.tcp.destroy()

    # -- maintenance loop -----------------------------------------------

    def poke(self, timeout: float = 0.0) -> None:
        """Wake the maintenance loop now (tests; churn hooks). With a
        timeout, block until the woken pass finished."""
        if timeout <= 0:
            self._kick.set()
            return
        done = threading.Event()
        with self._lock:
            self._pass_waiters.append(done)
        self._kick.set()
        done.wait(timeout)

    def _run(self) -> None:
        announce_s = _announce_s()
        lookup_s = _lookup_s()
        # per-id next-due stamps live on this thread only
        announced_at: Dict[str, float] = {}
        looked_at: Dict[str, float] = {}
        skipped: Dict[str, int] = {}
        while not self._stop.is_set():
            backlog = False
            try:
                backlog = self._pass(
                    announced_at, looked_at, skipped,
                    announce_s, lookup_s,
                )
            except Exception as e:  # a flaky pass must not kill the loop
                log("net:dht", f"maintenance pass failed: {e}")
            with self._lock:
                waiters = list(self._pass_waiters)
                self._pass_waiters[:] = []
            for w in waiters:
                w.set()
            # wake at the earliest due stamp (bounded so a kick or a
            # newly-due id is picked up promptly); budget-deferred
            # backlog continues on the short edge
            due = [
                t
                for t in list(announced_at.values())
                + list(looked_at.values())
            ]
            now = time.monotonic()
            delay = min((t - now for t in due), default=1.0)
            if backlog:
                delay = 0.0
            self._kick.wait(min(max(delay, 0.05), 1.0))
            self._kick.clear()

    def _pass(
        self,
        announced_at: Dict[str, float],
        looked_at: Dict[str, float],
        skipped: Dict[str, int],
        announce_s: float,
        lookup_s: float,
    ) -> bool:
        """One maintenance pass; True when budget-deferred work
        remains (the loop continues promptly instead of sleeping)."""
        if self.node.table.size() == 0 and self.node.bootstrap:
            # not bootstrapped (or every known node churned away):
            # retry every pass until the fleet answers
            self.node.bootstrap_now()
            if self.node.table.size():
                # fresh view of the fleet: publish immediately
                announced_at.clear()
                looked_at.clear()
        with self._lock:
            joined = dict(self._joined)
        # announce AGGREGATION (JoinOptions.via): every id joined via
        # the same doc key folds into ONE group — one signed announce
        # record and one lookup walk per doc per period, instead of
        # one of each per placeholder actor feed. Replication
        # negotiates the individual feeds over the connection the doc
        # key produced, so nothing is lost — only O(actors) walks. Ids
        # joined without a via keep their own key (legacy shape).
        groups: Dict[str, List[Tuple[str, JoinOptions]]] = {}
        for did, opts in joined.items():
            groups.setdefault(opts.via or did, []).append((did, opts))
        now = time.monotonic()
        host, port = self.tcp.address
        # bounded work per pass: a doc whose cursor carries one
        # placeholder actor per peer joins O(peers) ids at once, and
        # walking them all back-to-back every pass is the fleet's CPU
        # gone (each walk is ~alpha*hops RPCs, signed records, k
        # verifies per store). Oldest-due first, the rest next pass —
        # the FIRST joined id (the doc being opened) always leads.
        due = []
        for gkey, members in groups.items():
            if (
                any(o.announce for _d, o in members)
                and now >= announced_at.get(gkey, 0.0)
            ):
                seed_doc = next(
                    (o.seed for _d, o in members if o.seed is not None),
                    None,
                )
                due.append(
                    (announced_at.get(gkey, 0.0), "a", gkey, seed_doc)
                )
            lookers = [d for d, o in members if o.lookup]
            if lookers and now >= looked_at.get(gkey, 0.0):
                if self._need is not None and not any(
                    self._need(d) for d in lookers
                ):
                    # already replicating with someone: usually no
                    # walk, no dial — but every 10th period walk
                    # anyway. Two data-less peers that found only
                    # each other are mutually "satisfied" yet an
                    # ISLAND (with one-side dialing the lower-address
                    # data holder can never dial out); the slow-
                    # cadence shuffle is what merges islands.
                    n_skip = skipped.get(gkey, 0) + 1
                    if n_skip < 10:
                        skipped[gkey] = n_skip
                        looked_at[gkey] = now + lookup_s
                        continue
                    # do NOT reset the counter here: the budget below
                    # may defer this entry, and a reset-on-schedule
                    # would restart the 10-period clock without the
                    # walk ever running (the executed branch clears it)
                due.append((looked_at.get(gkey, 0.0), "l", gkey, lookers))
        due.sort(key=lambda e: e[0])
        for _t, kind, gkey, extra in due[:_PASS_BUDGET]:
            key = _id_hex(key_id(gkey))
            if kind == "a":
                self.node.announce(key, host, port, seed_doc=extra)
                announced_at[gkey] = time.monotonic() + announce_s
            else:
                self._lookup_and_dial(gkey, key, extra)
                looked_at[gkey] = time.monotonic() + lookup_s
                skipped.pop(gkey, None)  # the walk ran: island-shuffle
                # clock restarts only on an EXECUTED lookup
        # group keys whose members all left drop their stamps + view
        for table in (announced_at, looked_at, skipped):
            for gkey in list(table):
                if gkey not in groups:
                    table.pop(gkey, None)
        with self._lock:
            for gkey in list(self._targets):
                if gkey not in groups:
                    self._targets.pop(gkey, None)
        return len(due) > _PASS_BUDGET

    def _lookup_and_dial(
        self, gkey: str, key: str, members: List[str]
    ) -> None:
        records = self.node.lookup(key)
        own_addr = tuple(self.tcp.address)
        addrs = []
        seen = set()
        for r in records:
            addr = (str(r["host"]), int(r["port"]))
            if addr == own_addr or addr in seen:
                continue  # our own record / duplicate announcer
            seen.add(addr)
            # deterministic dial direction: of any announcer pair,
            # exactly ONE side dials (the higher address) — both
            # dialing each other would make every pair a dedup close
            # + supervised-redial churn loop. The lower side gets the
            # edge inbound; the union graph is identical.
            if addr < own_addr:
                addrs.append(addr)
        if not addrs:
            return
        n = _targets_n()
        with self._lock:
            current = self._targets.get(gkey, ())
            active = {a for t in self._targets.values() for a in t}
        # the bounded active view is STABLE and SHARED: keep targets
        # still being announced, and cover any deficit FIRST from
        # addresses some other id already dialed — a connection
        # replicates every feed the pair shares, so one well-connected
        # peer covers all of a doc's per-actor ids. Only a genuinely
        # uncovered id dials fresh addresses. (Wholesale resampling
        # per refresh, or per-id-independent dialing, both accumulate
        # supervised sessions until the fleet is a full mesh — the
        # opposite of the bound.) A target whose record expired (peer
        # gone, TTL elapsed) drops out here and its slot is refilled.
        keep = [a for a in current if a in seen]
        deficit = max(0, n - len(keep)) if n > 0 else len(addrs)
        reuse = [a for a in addrs if a in active and a not in keep]
        take = reuse[:deficit]
        deficit -= len(take)
        pool = [a for a in addrs if a not in active and a not in keep]
        if n > 0 and len(pool) > deficit:
            pool = self._rng.sample(pool, deficit)
        view = keep + take + pool
        with self._lock:
            if not any(d in self._joined for d in members):
                return  # leave() raced the lookup: no dials
            self._targets[gkey] = tuple(view)
        for addr in pool:
            try:
                self.tcp.connect(addr)
            except RuntimeError:
                return  # supervisor stopped: we are being destroyed

    # -- introspection --------------------------------------------------

    def discovery_report(self) -> Dict:
        """The `dht` block of the Telemetry payload (tools/meta.py
        --dht, tools/ls.py header, bench config_swarm)."""
        with self._lock:
            joined = {
                did: {
                    "announce": o.announce,
                    "lookup": o.lookup,
                    **({"via": o.via} if o.via else {}),
                }
                for did, o in self._joined.items()
            }
            targets = {did: len(t) for did, t in self._targets.items()}
        return {
            "node_id": self.node.id_hex,
            "dht_address": list(self.node.address),
            "tcp_address": list(self.tcp.address),
            "nodes": self.node.table.size(),
            "buckets": self.node.table.occupancy(),
            "records": self.node.records.size(),
            "joined": joined,
            "targets": targets,
        }
