"""Fleet-scale peer discovery: the pluggable seam, filled.

- `dht.py`     Kademlia-lite UDP DHT — k-buckets with LRU-plus-liveness
               eviction, iterative find_node/announce/lookup, signed+
               TTL'd announce records, HM_DHT_BOOTSTRAP.
- `swarm.py`   DhtSwarm: Swarm.join/leave backed by DHT announce/
               lookup; dial targets (a bounded random active view)
               flow into the TcpSwarm's SessionSupervisor.
- `gossip.py`  GossipSampler: per-doc bounded fanout for the hot
               broadcast paths; anti-entropy covers the rest.
"""

from .dht import (
    DhtNode,
    RecordStore,
    RoutingTable,
    bootstrap_from_env,
    key_id,
    make_record,
    verify_record,
)
from .gossip import GossipSampler
from .swarm import DhtSwarm

__all__ = [
    "DhtNode",
    "DhtSwarm",
    "GossipSampler",
    "RecordStore",
    "RoutingTable",
    "bootstrap_from_env",
    "key_id",
    "make_record",
    "verify_record",
]
