"""Bounded gossip relay: per-doc peer sampling caps active fanout.

Without a bound, every hot-path broadcast — replication live tails
(net/replication.py `_flush_feed`) and cursor gossip
(net/network.py) — costs O(connected peers) frames per event:
a 100-peer fleet amplifies every keystroke a hundredfold. HyParView's
insight is that an epidemic only needs a SMALL active view per node as
long as the union graph stays connected and the views reshuffle: this
sampler is that active view, per doc/feed key.

`sample(key, peers)` returns at most `HM_GOSSIP_FANOUT` of the given
peers (0 = unbounded). The subset is STABLE for `HM_GOSSIP_RESHUFFLE_S`
seconds per key — a stable subset lets the ack-paced replication
streams make progress instead of re-negotiating every frame — then
reshuffles to a fresh random subset, so over a few periods every edge
of the full peer graph gets exercised. A sampled peer that disconnects
triggers an immediate resample (the fanout budget must buy live edges).

Convergence across the sampled graph is guaranteed two ways:

- RELAY: a peer that receives replicated blocks extends its own feed,
  which marks its own flusher, which broadcasts to ITS sample — the
  epidemic hop. Fanout >= 2 with reshuffle floods any connected fleet
  in O(log N) rounds.
- ANTI-ENTROPY: the periodic FeedLength re-announce + cursor resend
  (`HM_ANTIENTROPY_S`, net/replication.py sweep) goes to EVERY
  verified peer, unsampled — a straggler the epidemic missed is
  bounded by one sweep period, and the sweep is O(peers) only once
  per interval, not per edit.

Only paths with a repair story are sampled: ephemeral doc messages
(Network.broadcast_doc_message) stay UNSAMPLED because they have no
relay hop and no sweep — a sampled-away peer would lose them forever.
"""

from __future__ import annotations

import os
import random
import time
from typing import Dict, List, Sequence, Tuple

from ...analysis.lockdep import make_lock
from ... import telemetry

# process-wide counters (tools/top.py [gossip] group): sent = peers
# actually targeted, suppressed = peers the fanout bound skipped
_M_SENT = telemetry.counter("gossip.sent")
_M_SUPPRESSED = telemetry.counter("gossip.suppressed")
_M_RESHUFFLES = telemetry.counter("gossip.reshuffles")

_MAX_KEYS = 4096  # sample-table bound: prune expired past this


def _fanout() -> int:
    return int(os.environ.get("HM_GOSSIP_FANOUT", "8"))


def _reshuffle_s() -> float:
    return float(os.environ.get("HM_GOSSIP_RESHUFFLE_S", "5"))


class GossipSampler:
    """Per-key bounded random peer sampling with periodic reshuffle.

    Peers are any objects with a stable `id` attribute (NetworkPeer).
    Thread-safe; called from emission/flusher threads on the hot path,
    so the critical section is dict bookkeeping only."""

    def __init__(
        self,
        fanout: int = None,
        reshuffle_s: float = None,
        seed: int = None,
    ) -> None:
        self.fanout = _fanout() if fanout is None else int(fanout)
        self.reshuffle_s = (
            _reshuffle_s() if reshuffle_s is None else float(reshuffle_s)
        )
        self._rng = random.Random(seed)
        self._lock = make_lock("net.gossip")
        # key -> (expiry monotonic, chosen peer-id tuple)
        self._samples: Dict[str, Tuple[float, Tuple[str, ...]]] = {}
        # service-plane hook (set once by Network wiring before
        # traffic): an OverloadController whose BROWNOUT+ states thin
        # the relay fanout so foreground reads keep the cores
        self.overload_ctl = None

    def sample(self, key: str, peers: Sequence) -> List:
        """At most `fanout` of `peers` for this key — the same subset
        until the reshuffle deadline, provided every chosen peer is
        still present."""
        fanout = self.fanout
        ctl = self.overload_ctl
        if ctl is not None and fanout > 1 and ctl.deprioritize():
            # brownout: the epidemic yields to foreground traffic —
            # half the fanout (never below 1: relay still converges,
            # and the anti-entropy sweep bounds any straggler)
            fanout = max(1, fanout // 2)
            ctl.note_thinned_gossip()
        if fanout <= 0 or len(peers) <= fanout:
            if peers:
                _M_SENT.add(len(peers))
            return list(peers)
        by_id = {getattr(p, "id", str(p)): p for p in peers}
        now = time.monotonic()
        with self._lock:
            ent = self._samples.get(key)
            chosen: Tuple[str, ...] = ()
            if ent is not None and ent[0] > now:
                alive = tuple(i for i in ent[1] if i in by_id)
                if len(alive) == fanout:
                    chosen = alive
            if not chosen:
                chosen = tuple(
                    self._rng.sample(sorted(by_id), fanout)
                )
                self._samples[key] = (now + self.reshuffle_s, chosen)
                _M_RESHUFFLES.add(1)
                if len(self._samples) > _MAX_KEYS:
                    self._samples = {
                        k: v
                        for k, v in self._samples.items()
                        if v[0] > now
                    }
        out = [by_id[i] for i in chosen]
        _M_SENT.add(len(out))
        _M_SUPPRESSED.add(len(peers) - len(out))
        return out

    def invalidate(self, key: str = None) -> None:
        """Force the next `sample` to reshuffle (tests; churn hooks)."""
        with self._lock:
            if key is None:
                self._samples.clear()
            else:
                self._samples.pop(key, None)
