"""Network — swarm lifecycle, peer handshake, message routing.

Parity: reference src/Network.ts:7-112 (join/leave sets, connection
handshake with Info exchange + self-connect rejection) +
src/MessageRouter.ts (typed channels per peer) wired into the repo hub:
cursor/clock gossip and ephemeral doc messages ride the "Msgs" channel
(reference channel 'HypermergeMessages', src/RepoBackend.ts:113), feed
sync rides "Replication" (net/replication.py).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, Optional, Set

from ..analysis.lockdep import make_rlock
from .. import msgs, telemetry
from ..crdt import clock as clockmod
from ..utils.debug import log
from .connection import PeerConnection
from .duplex import Duplex
from .peer import NetworkPeer
from .replication import ReplicationManager
from .swarm import DEFAULT_JOIN, ConnectionDetails, JoinOptions, Swarm

MSGS_CHANNEL = "Msgs"

# delta cursor gossip (HM_CURSOR_DELTA): steady-state frame sizes.
# full_tx counts whole-map frames (first frame per connection+doc and
# every repair-path send), delta_tx counts advanced-actors-only frames,
# suppressed counts gossip rounds skipped entirely because nothing
# advanced since the last frame this connection acked into the ledger.
_M_CUR_FULL = telemetry.counter("net.cursor.full_tx")
_M_CUR_DELTA = telemetry.counter("net.cursor.delta_tx")
_M_CUR_SUPPRESSED = telemetry.counter("net.cursor.suppressed")


def _cursor_delta_on() -> bool:
    """Delta cursor frames: steady-state gossip sends only the actors
    whose clock advanced since the last frame sent on this connection
    (full frame on (re)connect). Receiver-safe by construction — the
    receive path merges max-wins/union, so a partial map is just a
    small merge. =0 keeps the full-frame twin bit-compatible."""
    return os.environ.get("HM_CURSOR_DELTA", "1") == "1"


class Network:
    def __init__(self, backend) -> None:
        self.backend = backend
        self.self_id: str = backend.id
        self.swarm: Optional[Swarm] = None
        self.join_options: JoinOptions = DEFAULT_JOIN
        self.joined: Set[str] = set()
        self.pending_joins: Set[str] = set()
        self.peers: Dict[str, NetworkPeer] = {}
        self.closed_connection_count = 0
        self._lock = make_rlock("net.network")
        # bounded gossip relay (net/discovery/gossip.py): the
        # REPAIRABLE broadcast paths — replication live tails, cursor
        # gossip — target at most HM_GOSSIP_FANOUT peers per doc;
        # anti-entropy sweeps (and ephemeral doc messages, which have
        # no repair path) stay unsampled so convergence is bounded
        from .discovery.gossip import GossipSampler

        self.gossip = GossipSampler()
        self.replication = ReplicationManager(
            backend.feeds, self._on_feed_discovery, sampler=self.gossip
        )
        # sweep-time cursor repair: the anti-entropy pass re-sends doc
        # cursors a sampled gossip may have skipped (None for minimal
        # test backends that carry no cursor store)
        self.replication.on_sweep = getattr(
            backend, "send_sweep_cursors", None
        )
        # service plane (serve/overload.py): under BROWNOUT+ the
        # anti-entropy sweep skips its period and the gossip relay
        # thins its fanout — background repair yields to foreground
        # reads, bounded by the next healthy sweep
        ctl = getattr(backend, "overload", None)
        if ctl is not None:
            self.replication.overload_ctl = ctl
            self.gossip.overload_ctl = ctl

    # ------------------------------------------------------------------
    # swarm lifecycle

    def set_swarm(
        self, swarm: Swarm, join_options: Optional[JoinOptions] = None
    ) -> None:
        if self.swarm is not None:
            raise RuntimeError("swarm already set")
        fault_spec = os.environ.get("HM_FAULT")
        if fault_spec:
            # fault-injection soak mode: every connection of every
            # swarm rides a seeded FaultDuplex (net/faults.py), ticks
            # advanced on a wall-clock timer
            from .faults import FaultSwarm, parse_fault_spec

            swarm = FaultSwarm(swarm, parse_fault_spec(fault_spec))
            swarm.start_ticker()
            log("network", f"HM_FAULT active: {fault_spec}")
        self.swarm = swarm
        # the repo's swarm posture (reference Network.ts:22 — every
        # join uses it; server-ish repos announce, clients look up)
        self.join_options = join_options or DEFAULT_JOIN
        # authenticated transport: hand the repo's static ed25519 seed to
        # the swarm so every connection's handshake signs the ephemeral
        # transcript (net/secure.py auth; reference noise-peer static
        # keys, src/PeerConnection.ts:36). Readonly repos (no secret) and
        # swarms without identity support stay anonymous.
        set_id = getattr(swarm, "set_identity", None)
        if set_id is not None:
            set_id(self.backend.identity_seed())
        # demand-driven discovery (DhtSwarm): a lookup walk + dial only
        # while NO verified peer replicates the id — one connection
        # replicates every shared feed, so satisfied ids spend no
        # walk/dial budget, and a doc whose peers all churned away
        # flips back to needing one
        set_need = getattr(swarm, "set_need_hook", None)
        if set_need is not None:
            set_need(
                lambda did: not self.replication.peers_with_feed(did)
            )
        # push-seed receiver (HM_DHT_PUSH_SEED): a verified seed record
        # from the DHT names a doc this node is among the k-closest
        # for — open it so the creator stops serving the entire
        # cold-join first wave alone
        set_seed = getattr(swarm, "set_seed_hook", None)
        opener = getattr(self.backend, "open", None)
        if set_seed is not None and opener is not None:
            set_seed(opener)
        swarm.on_connection(self._on_connection)
        for did in self.backend.feeds.known_discovery_ids():
            self.join(did)
        for did in list(self.pending_joins):
            self.join(did)

    def join(
        self, discovery_id: str,
        options: Optional[JoinOptions] = None,
    ) -> None:
        if self.swarm is None:
            self.pending_joins.add(discovery_id)
            return
        with self._lock:
            if discovery_id in self.joined:
                return
            self.joined.add(discovery_id)
        self.swarm.join(discovery_id, options or self.join_options)

    def leave(self, discovery_id: str) -> None:
        with self._lock:
            self.joined.discard(discovery_id)
        if self.swarm is not None:
            self.swarm.leave(discovery_id)

    # ------------------------------------------------------------------
    # connections

    def _on_connection(
        self, duplex: Duplex, details: ConnectionDetails
    ) -> None:
        conn = PeerConnection(duplex, is_client=details.client)
        state = {"done": False}

        def on_info(msg: Any) -> None:
            if state["done"] or not isinstance(msg, dict):
                return
            if msg.get("type") != "Info":
                return
            state["done"] = True
            timer = state.pop("timer", None)
            if timer is not None:  # reaper thread retires on success
                timer.cancel()
            # hand the bus off to the NetworkPeer (single-subscriber
            # queue); anything arriving in between buffers
            conn.network_bus.receive_q.unsubscribe()
            peer_id = msg.get("peerId")
            if peer_id == self.self_id:
                log("network", "rejecting self-connection")
                details.reconnect(False)
                conn.close()
                return
            # identity pinning: when the transport authenticated the
            # peer (net/secure.py auth frames), the repo id it CLAIMS
            # must be the identity it PROVED — otherwise any
            # authenticated peer could impersonate another repo
            proven = conn.peer_identity
            if proven is not None and peer_id != proven:
                log(
                    "network",
                    f"rejecting peer: claimed id {str(peer_id)[:6]} != "
                    f"authenticated identity {proven[:6]}",
                )
                conn.close()
                return
            self._add_peer_connection(peer_id, conn)

        conn.network_bus.subscribe(on_info)
        conn.network_bus.send(msgs.info_msg(self.self_id))
        conn.on_close(self._count_close)
        # half-wired reaper: a connection whose Info exchange never
        # completes (the peer's frame lost to a faulty middlebox or
        # injected fault) must not idle forever behind healthy
        # keepalives — close it so the supervised redial renegotiates
        # from scratch
        timeout = float(os.environ.get("HM_INFO_TIMEOUT_S", "20"))
        if timeout > 0:
            def reap() -> None:
                if not state["done"] and conn.is_open:
                    log(
                        "network",
                        "Info exchange timed out: closing "
                        "half-wired connection",
                    )
                    conn.close()

            timer = threading.Timer(timeout, reap)
            timer.daemon = True
            state["timer"] = timer
            timer.start()
            conn.on_close(timer.cancel)
            if state["done"]:  # Info landed before the timer stored
                timer.cancel()

    def _count_close(self) -> None:
        self.closed_connection_count += 1

    def _add_peer_connection(
        self, peer_id: str, conn: PeerConnection
    ) -> None:
        with self._lock:
            peer = self.peers.get(peer_id)
            if peer is None:
                peer = NetworkPeer(
                    self.self_id,
                    peer_id,
                    self._on_peer_active,
                    self._on_peer_inactive,
                )
                self.peers[peer_id] = peer
        peer.add_connection(conn)

    def _on_peer_active(self, peer: NetworkPeer) -> None:
        """Fires for EVERY connection that becomes active (including
        replacements after churn): wire channels on the new connection."""
        log("network", f"peer active {peer.id[:6]}")
        conn = peer.connection
        if conn is None or not conn.is_open:
            # lost the race to a concurrent close: raising here would
            # kill the transport reader that delivered the activation;
            # the close path fires on_inactive and the next connection
            # re-wires cleanly
            return
        # wire each CONNECTION exactly once: a stale activation (its
        # own connection already replaced) reads the newer connection
        # here, and without the latch the real activation's duplicate
        # channel subscribe would raise mid-wiring, leaving
        # replication unnegotiated on the surviving connection
        with self._lock:
            if getattr(conn, "_hm_wired", False):
                return
            conn._hm_wired = True
        ch = conn.open_channel(MSGS_CHANNEL)
        ch.subscribe(lambda msg: self._on_peer_msg(peer, msg))
        self.replication.on_peer(peer)

    def _on_peer_inactive(self, peer: NetworkPeer) -> None:
        """Active connection lost without replacement: reset replication
        associations so a reconnect renegotiates from scratch."""
        log("network", f"peer inactive {peer.id[:6]}")
        self.replication.on_peer_closed(peer)

    # ------------------------------------------------------------------
    # message routing

    def _on_peer_msg(self, peer: NetworkPeer, msg: Any) -> None:
        if not isinstance(msg, dict):
            return
        try:
            t = msg.get("type")
            if t == "CursorMessage":
                self.backend.on_cursor_message(
                    peer,
                    msg["id"],
                    clockmod.strs_to_clock(msg["cursors"]),
                    clockmod.strs_to_clock(msg["clocks"]),
                )
            elif t == "DocumentMessage":
                self.backend.deliver_doc_message(msg["id"], msg["contents"])
        except (KeyError, TypeError, ValueError) as e:
            # malformed frames from buggy/hostile peers must not kill the
            # transport's reader
            log("network", f"malformed peer msg from {peer.id[:6]}: {e}")

    def _on_feed_discovery(self, public_id: str, peer: NetworkPeer) -> None:
        self.backend.on_discovery(public_id, peer)

    # ------------------------------------------------------------------
    # outbound (called by RepoBackend)

    def announce_feed(self, feed) -> None:
        self.join(feed.discovery_id, self._feed_join_options(feed))
        self.replication.announce(feed)

    def _feed_join_options(self, feed) -> Optional[JoinOptions]:
        """Announce aggregation: a feed that belongs to a known doc
        joins the DHT VIA the doc's discovery id — one signed record
        per doc key instead of one per placeholder actor feed (the
        O(actors) announce walks PR 15 measured). Push-seeding
        (HM_DHT_PUSH_SEED) rides the same options. None = no doc
        association known here; the feed announces under its own key."""
        cursors = getattr(self.backend, "cursors", None)
        if cursors is None:
            return None
        from ..utils import keys as keymod

        docs = sorted(
            cursors.docs_with_actor(self.backend.id, feed.public_key)
        )
        if not docs:
            return None
        doc_id = docs[0]  # deterministic pick for multi-doc actors
        opts = dataclasses.replace(
            self.join_options, via=keymod.discovery_id(doc_id)
        )
        if os.environ.get("HM_DHT_PUSH_SEED", "0") == "1":
            opts = dataclasses.replace(opts, seed=doc_id)
        return opts

    def _peers_for_doc(self, doc_id: str) -> Set[NetworkPeer]:
        from ..utils import keys as keymod

        peers: Set[NetworkPeer] = set()
        for actor_id in self.backend.cursors.actors_for(
            self.backend.id, doc_id
        ):
            did = keymod.discovery_id(actor_id)
            peers.update(self.replication.peers_with_feed(did))
        return peers

    def send_cursor_to(self, peer: NetworkPeer, doc_id: str,
                       cursor: clockmod.Clock, clock: clockmod.Clock,
                       full: bool = True) -> None:
        """Send a cursor frame to one peer. `full=True` (the repair
        paths: discovery replies, anti-entropy sweeps) always carries
        the whole maps; `full=False` (steady-state gossip) sends a
        delta against this connection's send ledger when
        HM_CURSOR_DELTA is on — or nothing at all when no actor
        advanced since the last frame."""
        conn = peer.connection  # snapshot: ledger rides the connection
        # (a replacement connection starts with no ledger, so the
        # first frame after churn is full — the resync guarantee)
        use_delta = not full and _cursor_delta_on() and conn is not None
        msg_cursor, msg_clock = cursor, clock
        if use_delta:
            with self._lock:
                ledger = getattr(conn, "_hm_cursor_sent", None)
                sent = None if ledger is None else ledger.get(doc_id)
                if sent is not None:
                    s_cur, s_clk = sent
                    msg_cursor = {
                        k: v for k, v in cursor.items()
                        if s_cur.get(k, -1) < v
                    }
                    msg_clock = {
                        k: v for k, v in clock.items()
                        if s_clk.get(k, -1) < v
                    }
            if sent is None:
                msg_cursor, msg_clock = cursor, clock
                use_delta = False  # first frame per conn+doc is full
            elif not msg_cursor and not msg_clock:
                _M_CUR_SUPPRESSED.add(1)
                return
        ok = peer.try_send(
            MSGS_CHANNEL,
            msgs.cursor_message(
                doc_id,
                clockmod.clock_to_strs(msg_cursor),
                clockmod.clock_to_strs(msg_clock),
            ),
        )
        if not ok:
            return  # dropped to churn; the replacement resyncs full
        (_M_CUR_DELTA if use_delta else _M_CUR_FULL).add(1)
        if not _cursor_delta_on() or conn is None:
            return
        # ledger merge (max-wins, like the receiver): record the FULL
        # new maps — the peer now knows at least this much, whether
        # the frame carried all of it or just the advancing slice
        with self._lock:
            ledger = getattr(conn, "_hm_cursor_sent", None)
            if ledger is None:
                ledger = {}
                conn._hm_cursor_sent = ledger
            s_cur, s_clk = ledger.get(doc_id, ({}, {}))
            ns_cur, ns_clk = dict(s_cur), dict(s_clk)
            for k, v in cursor.items():
                if ns_cur.get(k, -1) < v:
                    ns_cur[k] = v
            for k, v in clock.items():
                if ns_clk.get(k, -1) < v:
                    ns_clk[k] = v
            ledger[doc_id] = (ns_cur, ns_clk)

    def gossip_cursor(
        self, doc_id: str, cursor: clockmod.Clock, clock: clockmod.Clock
    ) -> None:
        peers = self.gossip.sample(doc_id, list(self._peers_for_doc(doc_id)))
        for peer in peers:
            self.send_cursor_to(peer, doc_id, cursor, clock, full=False)

    def broadcast_doc_message(self, doc_id: str, contents: Any) -> None:
        # deliberately UNSAMPLED: ephemeral doc messages are one-shot
        # with no relay hop (receivers only deliver to their frontend)
        # and no anti-entropy repair — a sampled-away peer would lose
        # the message forever, not late. The bounded-fanout claim
        # covers the repairable paths (live tails, cursor gossip).
        for peer in self._peers_for_doc(doc_id):
            peer.try_send(
                MSGS_CHANNEL, msgs.document_message(doc_id, contents)
            )

    def discovery_report(self) -> Optional[Dict[str, Any]]:
        """The attached swarm's DHT introspection block, when it has
        one (DhtSwarm.discovery_report; FaultSwarm passes through)."""
        fn = getattr(self.swarm, "discovery_report", None)
        return fn() if fn is not None else None

    # ------------------------------------------------------------------

    def close(self) -> None:
        self.replication.close()
        for peer in list(self.peers.values()):
            peer.close()
        self.peers.clear()
        if self.swarm is not None:
            self.swarm.destroy()
