"""Swarm interface + in-process loopback implementation.

Parity: the reference never hard-depends on a discovery mechanism — any
object with join/leave/on-connection/destroy works (reference
src/SwarmInterface.ts:6-58, README.md:26-34). `LoopbackSwarm` is the
in-process implementation (the testSwarm/testDuplexPair role from the
reference's tests, tests/misc.ts:34-36, :70-112); net/tcp.py provides a
socket-based swarm for real inter-process networking.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..analysis.lockdep import make_rlock
from .duplex import Duplex, duplex_pair


@dataclass(frozen=True)
class JoinOptions:
    """Discovery asymmetry (reference src/SwarmInterface.ts:22-25 +
    Network.ts:22 — the repo's swarm posture): `announce` makes a
    joined id discoverable by peers looking it up; `lookup` actively
    seeks announcers. Server-ish peers announce, clients look up;
    default is both.

    `via` is the announce-aggregation key (HM discovery ids only): a
    feed id joined with via=<doc discovery id> is announced and looked
    up under ONE signed DHT record per doc key instead of one per
    placeholder actor feed — peers of the doc find each other through
    the doc key, and replication negotiates the individual feeds over
    the connection. `seed` optionally names the doc id to push-seed to
    the DHT's k-closest at announce time (HM_DHT_PUSH_SEED)."""

    announce: bool = True
    lookup: bool = True
    via: Optional[str] = None
    seed: Optional[str] = None


DEFAULT_JOIN = JoinOptions()


class ConnectionDetails:
    """Per-connection policy record. `reconnect(False)` and `ban()` are
    CONSULTED now, not merely recorded: the redial supervisor
    (net/resilience.py) stops a session whose details carry either, and
    a transport may attach `_on_ban` to learn of bans as they happen
    (net/tcp.py records the peer's identity/address and refuses it at
    both dial and accept time)."""

    def __init__(self, client: bool, peer_info=None) -> None:
        self.client = client
        self.peer = peer_info
        self._reconnect_allowed = True
        self.banned = False
        self._on_ban: Optional[Callable[[], None]] = None

    def reconnect(self, allowed: bool) -> None:
        self._reconnect_allowed = allowed

    def ban(self) -> None:
        self.banned = True
        if self._on_ban is not None:
            self._on_ban()


class Swarm:
    """Structural base: join/leave by discovery id; emits connections."""

    def set_identity(self, seed: bytes) -> None:
        """Static ed25519 seed for transports that authenticate peers
        (net/tcp.py). Default: ignored — in-process loopback pairs have
        no wire to protect."""

    def join(
        self, discovery_id: str, options: JoinOptions = DEFAULT_JOIN
    ) -> None:
        raise NotImplementedError

    def leave(self, discovery_id: str) -> None:
        raise NotImplementedError

    def on_connection(
        self, cb: Callable[[Duplex, ConnectionDetails], None]
    ) -> None:
        raise NotImplementedError

    def destroy(self) -> None:
        raise NotImplementedError


class LoopbackHub:
    """Shared rendezvous for LoopbackSwarms in one process: when one
    swarm LOOKS UP a discovery id another swarm ANNOUNCES, a duplex
    pair connects them (the looker-up is the client). Two lookup-only
    members never pair — a lookup-only join is invisible to inbound
    discovery (reference JoinOptions asymmetry)."""

    def __init__(self) -> None:
        self._lock = make_rlock("net.swarm")
        self._members: Dict[
            str, List[Tuple["LoopbackSwarm", JoinOptions]]
        ] = {}

    def join(
        self,
        swarm: "LoopbackSwarm",
        discovery_id: str,
        options: JoinOptions = DEFAULT_JOIN,
    ) -> None:
        with self._lock:
            if discovery_id not in swarm.joined:
                # a leave raced this join (the swarm records intent
                # BEFORE calling the hub, in both directions): the
                # leave already ran its hub.leave, so registering now
                # would strand a member entry that keeps pairing the
                # departed swarm forever
                return
            members = self._members.setdefault(discovery_id, [])
            members[:] = [(s, o) for s, o in members if s is not swarm]
            members.append((swarm, options))
            others = [(s, o) for s, o in members if s is not swarm]
        for other, other_opts in others:
            if options.lookup and other_opts.announce:
                client, server = swarm, other
            elif options.announce and other_opts.lookup:
                client, server = other, swarm
            else:
                continue  # lookup/lookup or announce/announce: no pair
            if (client, server) not in _connected_pairs(client, server):
                _connect(client, server)

    def leave(self, swarm: "LoopbackSwarm", discovery_id: str) -> None:
        with self._lock:
            members = self._members.get(discovery_id, [])
            members[:] = [(s, o) for s, o in members if s is not swarm]


def _connected_pairs(a: "LoopbackSwarm", b: "LoopbackSwarm") -> Set:
    return a.connected & {(a, b), (b, a)}


def _connect(client: "LoopbackSwarm", server: "LoopbackSwarm") -> None:
    if (client, server) in client.connected:
        return
    client.connected.add((client, server))
    server.connected.add((client, server))
    d1, d2 = duplex_pair()
    client.emit(d1, ConnectionDetails(client=True))
    server.emit(d2, ConnectionDetails(client=False))


class LoopbackSwarm(Swarm):
    def __init__(self, hub: LoopbackHub) -> None:
        self.hub = hub
        self.joined: Set[str] = set()
        self.connected: Set = set()
        self._cb: Optional[Callable] = None

    def join(
        self, discovery_id: str, options: JoinOptions = DEFAULT_JOIN
    ) -> None:
        self.joined.add(discovery_id)
        self.hub.join(self, discovery_id, options)

    def leave(self, discovery_id: str) -> None:
        # intent first: a join racing this leave re-checks `joined`
        # inside the hub lock and cancels itself (LoopbackHub.join), so
        # a leave also cancels the PENDING join it interleaved with
        self.joined.discard(discovery_id)
        self.hub.leave(self, discovery_id)

    def on_connection(self, cb) -> None:
        self._cb = cb

    def emit(self, duplex: Duplex, details: ConnectionDetails) -> None:
        if self._cb is not None:
            self._cb(duplex, details)

    def destroy(self) -> None:
        for d in list(self.joined):
            self.leave(d)
