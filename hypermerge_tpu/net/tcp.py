"""TCP transport: socket-backed Duplex + a dial/accept swarm.

Carries the same object-message Duplex interface as the in-memory pair
(net/duplex.py) over real sockets with length-prefixed JSON frames, so the
whole connection/peer/replication stack is transport-agnostic — exactly
the reference's layering (sockets at the bottom, reference
src/PeerConnection.ts; discovery injected from outside,
src/SwarmInterface.ts).

`TcpSwarm` accepts inbound connections and dials explicit addresses
(`connect`). DHT-style peer discovery stays pluggable/external like the
reference's hyperswarm; `connect` is the bootstrap primitive a discovery
implementation would call.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

from ..analysis import lockdep
from ..analysis.lockdep import make_condition, make_lock, make_rlock
from ..utils.debug import log
from .. import telemetry
from .resilience import SessionSupervisor, dial_timeout_s
from .swarm import ConnectionDetails, Swarm

_HDR = struct.Struct("<I")
_MAX_FRAME = 64 * 1024 * 1024

# process-wide transport counters (every duplex shares them): frame +
# byte rates are the wire-level truth tools/top.py graphs under the
# per-channel replication counters. Counter.add is per-thread-sharded
# (one dict hit + one float add) — noise on a path that JSON-encodes
# and encrypts every frame.
_M_FRAMES_TX = telemetry.counter("net.tcp.frames_tx")
_M_FRAMES_RX = telemetry.counter("net.tcp.frames_rx")
_M_BYTES_TX = telemetry.counter("net.tcp.bytes_tx")
_M_BYTES_RX = telemetry.counter("net.tcp.bytes_rx")
_M_PINGS = telemetry.counter("net.tcp.pings_tx")
_M_SHEDS = telemetry.counter("net.tcp.sheds")

# keepalive frames: duplex-level, never delivered to subscribers. A
# pre-keepalive peer drops them as malformed channel frames
# (net/connection.py _on_raw) and never pongs — so a fully IDLE
# connection to such a peer is eventually shed and redialed (it is
# indistinguishable from half-open by design; any real frame from the
# peer counts as liveness). Every in-tree transport pongs.
_PING = "__hm_ping"
_PONG = "__hm_pong"


def _outbox_cap() -> int:
    """Max bytes queued behind a non-draining peer before the
    connection sheds (closes). The writer thread removed the old
    blocking-send backpressure; this cap bounds what replaces it."""
    return int(
        float(os.environ.get("HM_TCP_OUTBOX_MB", "64")) * (1 << 20)
    )


def _ping_s() -> float:
    """Keepalive period; 0 disables. A half-open socket (peer machine
    gone, NAT timeout, stalled reader) is detected within
    2 * HM_NET_PING_S * HM_NET_PING_MISSES seconds instead of at the
    64MB outbox bound."""
    return float(os.environ.get("HM_NET_PING_S", "15"))


def _ping_misses() -> int:
    return int(os.environ.get("HM_NET_PING_MISSES", "3"))


def _accept_pool_n() -> int:
    """Cap on concurrent inbound-handshake workers (legacy stack): an
    accept storm parks behind this pool instead of spawning a thread
    per accepted socket. Each slot is held at most the 10s handshake
    deadline."""
    return int(os.environ.get("HM_TCP_ACCEPT_POOL", "8"))


class TcpDuplex:
    """Object-message duplex over one socket (JSON frames, encrypted by
    default — sodium kx handshake + per-frame ChaCha20-Poly1305 with
    counter nonces, net/secure.py; the reference's noise wrapping,
    src/PeerConnection.ts:36). Inbound buffering rides utils.queue.Queue
    (same never-concurrent / never-reordered guarantees as the rest of
    the stack). HM_TCP_PLAINTEXT=1 disables encryption (both ends must
    agree)."""

    def __init__(
        self,
        sock: socket.socket,
        is_client: bool = False,
        identity: Optional[bytes] = None,
    ) -> None:
        from ..utils.queue import Queue

        self._sock = sock
        # Outbound frames go through a dedicated writer thread, never
        # straight to sendall: inbound dispatch runs synchronously on
        # the reader thread, and a reader that blocks on a full socket
        # buffer while the peer's reader does the same is a distributed
        # send deadlock (both sides wedge mid-burst, replication
        # freezes while the connection still reports open).
        self._outbox: deque = deque()
        self._out_cv = make_condition("net.tcp.outbox")
        self._out_inflight = False  # frame popped but not yet sent
        self._out_bytes = 0
        self._out_cap = _outbox_cap()  # read once: send() is hot
        self._stall_s = float(os.environ.get("HM_TCP_STALL_S", "10"))
        self._last_progress = time.monotonic()  # writer's last sendall
        self._shed = False  # over-cap close: skip the drain wait
        self._writer_dead = False  # writer hit a send error: no drain
        self._rx_eof = False  # peer closed/died: draining is pointless
        self._inbox: "Queue" = Queue("tcp:inbox")
        self._close_cbs: List[Callable[[], None]] = []
        self._lock = make_rlock("net.tcp")
        self.closed = False
        # keepalive: any complete inbound frame is liveness
        self._last_rx = time.monotonic()
        self._ka_stop = threading.Event()
        self._session = None
        self._identity = identity
        if os.environ.get("HM_TCP_PLAINTEXT") != "1":
            from .secure import SecureSession

            self._session = SecureSession(is_client)
            try:
                self._handshake()
            except (OSError, ValueError) as e:
                log("net:tcp", f"handshake failed: {e}")
                self.close()
                return
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self._writer = threading.Thread(
            target=self._write_loop, daemon=True
        )
        self._writer.start()
        ping = _ping_s()
        if ping > 0:
            threading.Thread(
                target=self._keepalive_loop, args=(ping, _ping_misses()),
                daemon=True,
            ).start()

    @property
    def channel_binding(self) -> Optional[bytes]:
        return self._session.channel_binding if self._session else None

    @property
    def peer_identity(self) -> Optional[str]:
        return self._session.peer_identity if self._session else None

    def _handshake(self) -> None:
        """Exchange ephemeral public keys (the only plaintext frames:
        one flags byte + 32-byte key), then — when BOTH sides offered
        auth — one encrypted ed25519 auth frame each way over the
        transcript (net/secure.py). A peer that cannot sign the
        transcript (MITM key substitution) fails closed.

        Negotiation: the flags byte advertises whether this side will
        send an auth frame (bit 0). Auth runs only when both offer it;
        a mixed pair (identity-less peer, HM_NET_AUTH=0, legacy 32-byte
        handshake) falls back to the anonymous session — unless
        HM_NET_AUTH=require, which drops unauthenticated peers."""
        mode = os.environ.get("HM_NET_AUTH", "1")
        offer = self._identity is not None and mode != "0"
        if mode == "require" and self._identity is None:
            raise ValueError("HM_NET_AUTH=require but no identity set")
        self._sock.settimeout(10)
        pk = self._session.handshake_bytes
        frame = bytes([1 if offer else 0]) + pk
        with lockdep.blocking("socket_send", "handshake"):
            self._sock.sendall(_HDR.pack(len(frame)) + frame)
        hdr = self._read_exact(_HDR.size)
        if hdr is None:
            raise OSError("peer closed during handshake")
        (size,) = _HDR.unpack(hdr)
        if size == 33:
            flags = self._read_exact(1)
            if flags is None:
                raise OSError("peer closed during handshake")
            peer_offers = bool(flags[0] & 1)
        elif size == 32:
            peer_offers = False  # legacy anonymous endpoint
        else:
            raise ValueError(f"bad handshake frame size {size}")
        peer_pk = self._read_exact(32)
        if peer_pk is None:
            raise OSError("peer closed during handshake")
        self._session.complete(peer_pk)
        if offer and peer_offers:
            auth = self._session.encrypt(
                self._session.auth_frame(self._identity)
            )
            with lockdep.blocking("socket_send", "auth"):
                self._sock.sendall(_HDR.pack(len(auth)) + auth)
            hdr = self._read_exact(_HDR.size)
            if hdr is None:
                raise OSError("peer closed during auth")
            (size,) = _HDR.unpack(hdr)
            if size > 1024:
                raise ValueError(f"bad auth frame size {size}")
            wire = self._read_exact(size)
            if wire is None:
                raise OSError("peer closed during auth")
            frame = self._session.decrypt(wire)
            if frame is None or not self._session.verify_auth(frame):
                raise ValueError(
                    "peer identity authentication FAILED "
                    "(MITM key substitution or signature over a "
                    "different transcript)"
                )
        elif mode == "require":
            raise ValueError(
                "peer did not offer identity auth (HM_NET_AUTH=require)"
            )
        self._sock.settimeout(None)

    def on_message(self, cb: Callable[[Any], None]) -> None:
        self._inbox.subscribe(cb)

    def on_close(self, cb: Callable[[], None]) -> None:
        """Register a close listener. Multiple listeners are supported
        (the connection stack AND the redial supervisor both watch);
        a listener registered after close fires immediately."""
        fire_now = False
        with self._lock:
            if self.closed:
                fire_now = True  # closed before anyone registered
            else:
                self._close_cbs.append(cb)
        if fire_now:
            cb()

    def _keepalive_loop(self, period: float, miss_budget: int) -> None:
        """Ping when the inbound side goes quiet; shed after the miss
        budget. A half-open connection (peer machine gone, NAT timeout,
        reader stalled with the socket open) looks healthy to the
        writer until the outbox cap — this closes it in seconds: no
        inbound frame for `period` sends a ping, `miss_budget`
        consecutive quiet periods close the connection (and the redial
        supervisor, if any, dials a fresh one)."""
        misses = 0
        last_probe = float("-inf")
        while not self._ka_stop.wait(period):
            if self.closed:
                return
            now = time.monotonic()
            # a miss is "nothing arrived since my last probe" — NOT
            # "idle at check time": a pong that lands just after a
            # check must reset the budget even though the link is idle
            if self._last_rx >= last_probe:
                misses = 0
            else:
                misses += 1
                # shed ON the Nth unanswered probe (>=, not >): with
                # probes at period P the shed lands by (M+1)*P, inside
                # the documented 2*P*M bound for every M >= 1
                if misses >= miss_budget:
                    log(
                        "net:tcp",
                        f"keepalive: {misses} unanswered probes "
                        f"({period}s apart): half-open, shedding",
                    )
                    # a peer that answers no pings is by definition
                    # not draining: skip close()'s bounded drain wait
                    _M_SHEDS.add(1)
                    self._shed = True
                    self.close()
                    return
            if now - self._last_rx >= period:
                self.send({_PING: misses})
                _M_PINGS.add(1)
                last_probe = now

    def send(self, msg: Any) -> None:
        """Queue a frame for the writer thread. Never blocks on the
        socket — see _outbox above. The protocol's ack-paced block
        streams bound most of what piles up here, but patch/gossip
        frames are not ack-paced: a peer that stops reading while its
        socket stays open would otherwise grow the queue without limit.
        Past HM_TCP_OUTBOX_MB *with the writer stalled* (no completed
        frame for HM_TCP_STALL_S — a healthy peer absorbing a large
        burst keeps making progress and is never shed), or past 4x the
        cap regardless of progress (the hard memory bound: a slow-drip
        peer must not grow the queue forever), the connection sheds
        (closes); the peer redials and resyncs from its cursor."""
        if self.closed:
            return
        data = json.dumps(msg, separators=(",", ":")).encode("utf-8")
        with self._out_cv:
            if not self._outbox and not self._out_inflight:
                # idle -> active: the stall clock must measure from the
                # start of THIS burst, not from the last pre-idle frame
                self._last_progress = time.monotonic()
            self._outbox.append(data)
            self._out_bytes += len(data)
            over = self._out_bytes > self._out_cap
            self._out_cv.notify()
        if over and (
            self._out_bytes > 4 * self._out_cap
            or time.monotonic() - self._last_progress > self._stall_s
        ):
            log(
                "net:tcp",
                f"outbox over cap ({self._out_bytes}B) with a stalled "
                "writer: peer not draining, shedding connection",
            )
            _M_SHEDS.add(1)
            self._shed = True
            self.close()

    def _write_loop(self) -> None:
        while True:
            with self._out_cv:
                # the previous frame (if any) is fully on the wire only
                # once we get back here: signal close()'s drain AFTER
                # sendall, not when the frame is merely popped
                self._out_inflight = False
                if not self._outbox:
                    self._out_cv.notify_all()  # close() may be draining
                while not self._outbox and not self.closed:
                    self._out_cv.wait()
                if not self._outbox:  # closed and drained
                    return
                data = self._outbox.popleft()
                self._out_bytes -= len(data)
                self._out_inflight = True
            try:
                # nonce counters are per-direction and strictly ordered:
                # the single writer thread orders encryption and writes
                if self._session is not None:
                    data = self._session.encrypt(data)
                with lockdep.blocking("socket_send", "frame"):
                    self._sock.sendall(_HDR.pack(len(data)) + data)
                _M_FRAMES_TX.add(1)
                _M_BYTES_TX.add(_HDR.size + len(data))
                self._last_progress = time.monotonic()
            except OSError:
                # signal BEFORE close(): a concurrent closer may be
                # waiting on the drain cv while holding self._lock —
                # the frame is lost and the outbox will never drain, so
                # wake it now instead of letting it burn its deadline
                with self._out_cv:
                    self._out_inflight = False
                    self._writer_dead = True
                    self._out_cv.notify_all()
                self.close()
                return

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = self._sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        while not self.closed:
            hdr = self._read_exact(_HDR.size)
            if hdr is None:
                break
            (size,) = _HDR.unpack(hdr)
            if size > _MAX_FRAME:
                log("net:tcp", f"oversized frame {size}, closing")
                break
            payload = self._read_exact(size)
            if payload is None:
                break
            _M_FRAMES_RX.add(1)
            _M_BYTES_RX.add(_HDR.size + size)
            self._last_rx = time.monotonic()  # any frame is liveness
            if self._session is not None:
                payload = self._session.decrypt(payload)
                if payload is None:
                    # authentication failure = tampering or desync:
                    # fatal, never skippable
                    log("net:tcp", "ciphertext auth failed, closing")
                    break
            try:
                msg = json.loads(payload.decode("utf-8"))
            except ValueError:
                continue  # corrupt frame: skip
            if isinstance(msg, dict):
                # keepalive frames stop here, never reach subscribers
                if _PING in msg:
                    self.send({_PONG: msg[_PING]})
                    continue
                if _PONG in msg:
                    continue
            try:
                self._inbox.push(msg)
            except Exception as e:  # subscriber bug must not kill reader
                log("net:tcp", f"inbound handler error: {e}")
                break
        self._rx_eof = True
        self.close()

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            # orderly close loses nothing: give the writer a bounded
            # window to drain queued frames. Skip when draining cannot
            # succeed or has no point: close() running ON the writer
            # after a send error (socket dead), an over-cap shed (peer
            # by definition not draining), a writer that already died
            # in sendall, or a reader EOF (the peer is gone and will
            # never read queued frames)
            if (
                not self._shed
                and not self._rx_eof
                and threading.current_thread()
                is not getattr(self, "_writer", None)
            ):
                deadline = 5.0
                with self._out_cv:
                    while (
                        (self._outbox or self._out_inflight)
                        and not self._writer_dead
                        and not self._rx_eof  # peer died mid-drain
                        and deadline > 0
                    ):
                        t0 = time.monotonic()
                        self._out_cv.wait(min(deadline, 0.2))
                        deadline -= time.monotonic() - t0
            self.closed = True
            listeners = list(self._close_cbs)
        self._ka_stop.set()
        with self._out_cv:
            self._out_cv.notify_all()  # writer exits
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        for cb in listeners:
            cb()


class TcpSwarm(Swarm):
    """Accepts inbound connections; dials peers via `connect(addr)`.

    Outbound addresses are owned by a `SessionSupervisor`
    (net/resilience.py): `connect` registers the address and returns
    immediately; the dial + handshake run off-thread, a failed dial
    backs off and retries instead of raising, and a dropped connection
    redials until its ConnectionDetails recorded `reconnect(False)` or
    `ban()`. Banned peer identities are also refused at ACCEPT time —
    a banned peer's inbound redial used to be accepted unconditionally."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        identity: Optional[bytes] = None,
    ) -> None:
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(16)
        self.address: Tuple[str, int] = self._server.getsockname()
        self.join_options: dict = {}
        self._cb: Optional[Callable] = None
        self._duplexes: List[TcpDuplex] = []
        self._dlock = make_lock("net.tcp.server")
        self._destroyed = False
        self._identity: Optional[bytes] = identity
        self._banned_ids: set = set()  # proven peer identities
        self._banned_addrs: set = set()  # outbound dial addresses
        self._banned_hosts: set = set()  # anonymous-peer fallback
        # transport twin selector: =1 multiplexes every connection of
        # the process onto the shared net/aio.py loop (bit-compatible
        # on the wire with the =0 thread-per-connection stack)
        self._async = os.environ.get("HM_NET_ASYNC", "0") == "1"
        self._loop = None
        if self._async:
            from .aio import get_loop

            self._loop = get_loop()
            self.supervisor = SessionSupervisor(
                dial=self._dial_async,
                deliver=self._deliver_outbound,
                banned=lambda addr: (
                    addr in self._banned_addrs
                    or addr[0] in self._banned_hosts
                ),
                connector=self._loop,
            )
        else:
            self.supervisor = SessionSupervisor(
                dial=self._dial,
                deliver=self._deliver_outbound,
                banned=lambda addr: (
                    addr in self._banned_addrs
                    or addr[0] in self._banned_hosts
                ),
            )
            # bounded inbound-handshake pool (legacy stack): an accept
            # storm queues here instead of spawning a thread per accept
            self._accept_cv = make_condition("net.tcp.accept")
            self._accept_q: deque = deque()
            self._accept_idle = 0
            self._accept_workers = 0
        self._accepter = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accepter.start()

    def set_identity(self, seed: Optional[bytes]) -> None:
        """Static ed25519 identity for the authenticated handshake
        (Network.set_swarm passes the repo keypair's seed). The accept
        loop runs from construction, so an inbound connection can race
        this call and handshake anonymously; _handle_inbound re-checks
        after the handshake and drops such connections (the peer
        reconnects into the authenticated path). Passing the identity
        to the constructor avoids the window entirely."""
        self._identity = seed

    def _accept_loop(self) -> None:
        while not self._destroyed:
            try:
                sock, _addr = self._server.accept()
            except OSError:
                break
            if self._async:
                # the handshake runs as loop callbacks — nothing to
                # park a thread on; checks resume in _inbound_ready
                self._accept_async(sock)
                continue
            # handshake per connection off the listener thread, but
            # BOUNDED: an accept storm (or a dialer that stalls inside
            # the 10s handshake window) queues here instead of
            # spawning an unbounded thread per accept
            spawn = False
            with self._accept_cv:
                self._accept_q.append(sock)
                if self._accept_idle > 0:
                    self._accept_cv.notify()
                elif self._accept_workers < _accept_pool_n():
                    self._accept_workers += 1
                    spawn = True
            if spawn:
                threading.Thread(
                    target=self._accept_worker, daemon=True
                ).start()

    def _accept_worker(self) -> None:
        while True:
            with self._accept_cv:
                while not self._accept_q:
                    if self._destroyed:
                        return
                    self._accept_idle += 1
                    self._accept_cv.wait()
                    self._accept_idle -= 1
                sock = self._accept_q.popleft()
            try:
                self._handle_inbound(sock)
            except Exception as e:  # one bad peer must not kill a slot
                log("net:tcp", f"inbound handshake error: {e}")
                try:
                    sock.close()
                except OSError:
                    pass

    def _accept_async(self, sock: socket.socket) -> None:
        """Inbound path under HM_NET_ASYNC=1: the same checks as
        _handle_inbound, split around the loop-driven handshake."""
        from .aio import AioDuplex

        try:
            peer_host = sock.getpeername()[0]
        except OSError:
            peer_host = None
        if peer_host is not None and peer_host in self._banned_hosts:
            log("net:tcp", f"refusing inbound from banned host {peer_host}")
            sock.close()
            return
        ident = self._identity
        AioDuplex(
            sock,
            is_client=False,
            identity=ident,
            on_ready=lambda d, exc: self._inbound_ready(d, exc, ident),
        )

    def _inbound_ready(self, duplex, exc, ident) -> None:
        """Dispatch-worker continuation of _accept_async (fires once
        per accepted connection when its handshake settles)."""
        if exc is not None:
            return  # the duplex is already tearing itself down
        if ident is None and self._identity is not None:
            # set_identity landed mid-handshake: this connection went
            # through anonymously and would bypass identity pinning —
            # drop it; the dialer retries into the authenticated path
            log("net:tcp", "dropping pre-identity inbound connection")
            duplex.close()
            return
        if (
            duplex.peer_identity is not None
            and duplex.peer_identity in self._banned_ids
        ):
            log(
                "net:tcp",
                f"refusing inbound redial from banned peer "
                f"{duplex.peer_identity[:6]}",
            )
            duplex.close()
            return
        self._track(duplex)
        if not duplex.closed and self._cb is not None:
            details = ConnectionDetails(client=False)
            details._on_ban = lambda: self._record_ban(duplex)
            self._cb(duplex, details)

    def _track(self, duplex: TcpDuplex) -> None:
        """Track a live duplex; closed duplexes LEAVE the list (a
        long-lived swarm under churn must not grow without bound). A
        duplex tracked after destroy() began — an inbound redial can
        complete its handshake between destroy's flag and its duplex
        snapshot — is closed here instead of living as a zombie on a
        destroyed swarm."""
        with self._dlock:
            self._duplexes.append(duplex)
            dead = self._destroyed
        duplex.on_close(lambda: self._untrack(duplex))
        if dead:
            duplex.close()

    def _untrack(self, duplex: TcpDuplex) -> None:
        with self._dlock:
            try:
                self._duplexes.remove(duplex)
            except ValueError:
                pass

    def _record_ban(self, duplex: TcpDuplex, address=None) -> None:
        """ConnectionDetails.ban() fired: sever the live connection NOW
        and refuse this peer from then on — its proven identity at
        accept AND dial time; on anonymous transports (no identity
        auth) the peer HOST is the only stable key, so the whole host
        is refused (blunt by necessity — run identity auth for
        per-peer precision). Outbound dial addresses are banned too."""
        ident = duplex.peer_identity
        if ident is not None:
            self._banned_ids.add(ident)
        else:
            try:
                self._banned_hosts.add(duplex._sock.getpeername()[0])
            except OSError:
                pass  # already disconnected: nothing stable to record
        if address is not None:
            self._banned_addrs.add(tuple(address))
        log("net:tcp", f"banned peer id={str(ident)[:6]} addr={address}")
        duplex.close()  # a ban is effective immediately, not at the
        # next natural drop (keepalive would keep a healthy banned
        # link alive indefinitely)

    def _handle_inbound(self, sock: socket.socket) -> None:
        try:
            peer_host = sock.getpeername()[0]
        except OSError:
            peer_host = None
        if peer_host is not None and peer_host in self._banned_hosts:
            log("net:tcp", f"refusing inbound from banned host {peer_host}")
            sock.close()
            return
        ident = self._identity
        duplex = TcpDuplex(sock, is_client=False, identity=ident)
        if ident is None and self._identity is not None:
            # set_identity landed mid-handshake: this connection went
            # through anonymously and would bypass identity pinning —
            # drop it; the dialer retries into the authenticated path
            log("net:tcp", "dropping pre-identity inbound connection")
            duplex.close()
            return
        if (
            duplex.peer_identity is not None
            and duplex.peer_identity in self._banned_ids
        ):
            log(
                "net:tcp",
                f"refusing inbound redial from banned peer "
                f"{duplex.peer_identity[:6]}",
            )
            duplex.close()
            return
        self._track(duplex)
        if not duplex.closed and self._cb is not None:
            details = ConnectionDetails(client=False)
            details._on_ban = lambda: self._record_ban(duplex)
            self._cb(duplex, details)

    def _dial(self, address: Tuple[str, int]) -> TcpDuplex:
        """One dial + handshake (supervisor thread). Raises OSError on
        failure so the supervisor schedules a backoff retry."""
        sock = socket.create_connection(address, timeout=dial_timeout_s())
        sock.settimeout(None)
        duplex = TcpDuplex(sock, is_client=True, identity=self._identity)
        if duplex.closed:
            raise OSError("handshake failed")
        if (
            duplex.peer_identity is not None
            and duplex.peer_identity in self._banned_ids
        ):
            duplex.close()
            self._banned_addrs.add(address)  # stop the session too
            raise OSError("peer identity is banned")
        self._track(duplex)
        return duplex

    def _dial_async(self, address: Tuple[str, int], cb) -> None:
        """Async-mode dial primitive (supervisor connector mode): a
        non-blocking connect + loop-driven handshake; `cb(duplex, exc)`
        fires exactly once on a dispatch worker."""
        from .aio import AioDuplex

        address = tuple(address)

        def ready(duplex, exc) -> None:
            if exc is not None:
                duplex.close()
                cb(None, OSError(f"handshake failed: {exc}"))
                return
            if (
                duplex.peer_identity is not None
                and duplex.peer_identity in self._banned_ids
            ):
                duplex.close()
                self._banned_addrs.add(address)  # stop the session too
                cb(None, OSError("peer identity is banned"))
                return
            self._track(duplex)
            cb(duplex, None)

        def dialed(sock, exc) -> None:  # loop thread: keep it cheap
            if exc is not None:
                cb(None, exc)
                return
            AioDuplex(
                sock,
                is_client=True,
                identity=self._identity,
                on_ready=ready,
            )

        self._loop.dial(address, dial_timeout_s(), dialed)

    def _deliver_outbound(
        self, duplex: TcpDuplex, details: ConnectionDetails
    ) -> None:
        try:
            address = duplex._sock.getpeername()
        except OSError:  # died between dial and deliver
            address = None
        details._on_ban = lambda: self._record_ban(duplex, address)
        if not duplex.closed and self._cb is not None:
            self._cb(duplex, details)

    def connect(self, address: Tuple[str, int]):
        """Supervised dial: registers `address` with the session
        supervisor and returns its Session immediately. A failed dial
        enqueues a jittered retry and surfaces through the
        supervisor's status hook (`swarm.supervisor.on_status`)
        instead of raising into the caller; a dropped connection
        redials until `reconnect(False)`/`ban()`."""
        return self.supervisor.connect(tuple(address))

    # discovery is external (reference: hyperswarm); topics are no-ops here
    def join(self, discovery_id: str, options=None) -> None:
        # topology is explicit (connect()); per-id discovery — and so
        # the announce/lookup asymmetry — doesn't apply, matching
        # hyperswarm-with-direct-connections semantics. Options are
        # recorded for introspection.
        from .swarm import DEFAULT_JOIN

        self.join_options[discovery_id] = options or DEFAULT_JOIN

    def leave(self, discovery_id: str) -> None:
        self.join_options.pop(discovery_id, None)

    def on_connection(self, cb) -> None:
        self._cb = cb

    def destroy(self) -> None:
        with self._dlock:
            self._destroyed = True  # _track closes later arrivals
        self.supervisor.stop()  # no redial races the teardown below
        try:
            self._server.close()
        except OSError:
            pass
        if not self._async:
            # wake parked handshake workers (they see _destroyed and
            # exit) and refuse the sockets still queued behind them
            with self._accept_cv:
                pending = list(self._accept_q)
                self._accept_q.clear()
                self._accept_cv.notify_all()
            for sock in pending:
                try:
                    sock.close()
                except OSError:
                    pass
        with self._dlock:
            live = list(self._duplexes)
        for d in live:
            d.close()
