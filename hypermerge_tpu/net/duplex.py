"""Duplex message transports.

The connection stack is built over a minimal object-message Duplex (send /
on_message / close). `DuplexPair` is the in-memory cross-wired pair used by
loopback tests and the LoopbackSwarm — deliveries are deferred through a
trampoline scheduler rather than invoked re-entrantly, the same race-
avoidance the reference's test duplex gets from setImmediate writes
(reference tests/misc.ts:70-112). A TCP adapter (net/tcp.py) carries the
same interface over sockets with JSON framing.
"""

from __future__ import annotations

import threading
from collections import deque

from ..analysis.lockdep import make_rlock
from typing import Any, Callable, Optional


class Duplex:
    """One end of a bidirectional object-message pipe."""

    def __init__(self) -> None:
        self._on_message: Optional[Callable[[Any], None]] = None
        self._close_cbs: list = []
        self._inbox: deque = deque()
        self._peer: Optional["Duplex"] = None
        self._scheduler: Optional["_Trampoline"] = None
        self.closed = False

    def on_message(self, cb: Callable[[Any], None]) -> None:
        self._on_message = cb
        self._drain_inbox()

    def on_close(self, cb: Callable[[], None]) -> None:
        """Multi-listener, same contract as TcpDuplex.on_close: the
        connection stack AND wrappers (fault injection, supervisors)
        may both watch; registering after close fires immediately."""
        if self.closed:
            cb()
        else:
            self._close_cbs.append(cb)

    def send(self, msg: Any) -> None:
        if self.closed or self._peer is None:
            return
        peer = self._peer
        self._scheduler.defer(lambda: peer._deliver(msg))

    def _deliver(self, msg: Any) -> None:
        if self.closed:
            return
        if self._on_message is None:
            self._inbox.append(msg)
        else:
            self._on_message(msg)

    def _drain_inbox(self) -> None:
        while self._inbox and self._on_message is not None:
            self._on_message(self._inbox.popleft())

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for cb in list(self._close_cbs):
            cb()
        peer = self._peer
        if peer is not None and not peer.closed:
            self._scheduler.defer(peer.close)


class _Trampoline:
    """Defer callbacks without unbounded recursion: whoever starts the
    pump drains everything queued (including callbacks queued while
    pumping). Thread-safe; callbacks never run concurrently."""

    def __init__(self) -> None:
        self._queue: deque = deque()
        self._lock = make_rlock("net.duplex")
        self._pumping = False

    def defer(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._queue.append(fn)
        self._pump()

    def _pump(self) -> None:
        while True:
            with self._lock:
                if self._pumping or not self._queue:
                    return
                self._pumping = True
                fn = self._queue.popleft()
            try:
                fn()
            finally:
                with self._lock:
                    self._pumping = False


def duplex_pair() -> tuple:
    """Two cross-wired in-memory duplexes sharing one trampoline."""
    a, b = Duplex(), Duplex()
    tramp = _Trampoline()
    a._peer, b._peer = b, a
    a._scheduler = b._scheduler = tramp
    return a, b
