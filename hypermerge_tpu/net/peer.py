"""NetworkPeer — one logical peer; dedups simultaneous connections.

Parity: reference src/NetworkPeer.ts:8-106 — when both sides dial each
other, the side whose id sorts higher has *authority* (reference
weHaveAuthority, :41-43): with an already-confirmed connection it closes
the duplicate (reference :52-55); otherwise it picks the incoming one and
sends ConfirmConnection; the other side closes everything else.

Lifecycle callbacks fire per connection, not once per peer: every time a
new connection becomes active, `on_active(peer)` lets the network layer
re-wire channels on it (the reference's connectionQ re-subscription,
src/NetworkPeer.ts:83-85); `on_inactive(peer)` fires when the active
connection is lost without a replacement, so replication state can reset.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from ..analysis.lockdep import make_lock
from .. import msgs
from ..utils.debug import log
from .connection import PeerConnection


class NetworkPeer:
    def __init__(
        self,
        self_id: str,
        peer_id: str,
        on_active: Callable[["NetworkPeer"], None],
        on_inactive: Optional[Callable[["NetworkPeer"], None]] = None,
    ) -> None:
        self.self_id = self_id
        self.id = peer_id
        self._on_active = on_active
        self._on_inactive = on_inactive
        self.connection: Optional[PeerConnection] = None
        self._pending: List[PeerConnection] = []
        # guards _pending: mutated from accept/supervisor threads
        # (add_connection) AND reader threads (close-driven prune)
        self._plock = make_lock("net.peer")

    @property
    def we_have_authority(self) -> bool:
        return self.self_id > self.id

    @property
    def is_connected(self) -> bool:
        return self.connection is not None and self.connection.is_open

    def add_connection(self, conn: PeerConnection) -> None:
        conn.network_bus.subscribe(lambda msg: self._on_bus(conn, msg))
        if self.we_have_authority:
            if self.is_connected:
                # duplicate dial: keep the confirmed connection
                conn.close()
                return
            self._confirm(conn)
            conn.network_bus.send(msgs.confirm_connection_msg(conn.id))
        else:
            # churn hygiene: dead connections must LEAVE pending, or a
            # reconnect after a lost ConfirmConnection finds
            # len(pending) > 1 forever and never optimistically wires
            # the only live connection
            with self._plock:
                self._pending = [c for c in self._pending if c.is_open]
                self._pending.append(conn)
                use_now = (
                    self.connection is None and len(self._pending) == 1
                )
            conn.on_close(lambda: self._prune_pending(conn))
            if use_now:
                # optimistically use the first connection until (unless)
                # the authority confirms a different one
                self._use(conn)

    def _prune_pending(self, conn: PeerConnection) -> None:
        with self._plock:
            try:
                self._pending.remove(conn)
            except ValueError:
                pass

    def try_send(self, channel: str, msg: Any) -> bool:
        """Snapshot-send on the active connection. THE send idiom for
        churn safety: `peer.connection` can flip to None between an
        `is_connected` check and the send, so callers must not
        check-then-use it themselves. False when no live connection
        (the dropped frame is recovered by the replacement
        connection's resync)."""
        conn = self.connection
        if conn is not None and conn.is_open:
            conn.open_channel(channel).send(msg)
            return True
        return False

    def _on_bus(self, conn: PeerConnection, msg) -> None:
        if isinstance(msg, dict) and msg.get("type") == "ConfirmConnection":
            # connection ids are side-local; the authority sends the
            # confirmation ON the connection it chose, so the arrival
            # connection is the confirmed one
            self._confirm(conn)

    def _confirm(self, conn: PeerConnection) -> None:
        with self._plock:
            others = [c for c in self._pending if c is not conn]
            self._pending = []
        for other in others:
            if other.is_open:
                other.close()
        self._use(conn)

    def _use(self, conn: PeerConnection) -> None:
        if self.connection is conn:
            return
        old = self.connection
        self.connection = conn
        conn.on_close(lambda: self._on_conn_close(conn))
        if old is not None and old.is_open and old is not conn:
            old.close()
        if conn.is_open:
            self._on_active(self)

    def _on_conn_close(self, conn: PeerConnection) -> None:
        if self.connection is conn:
            self.connection = None
            log("network:peer", f"connection to {self.id[:6]} closed")
            if self._on_inactive is not None:
                self._on_inactive(self)

    def close(self) -> None:
        if self.connection is not None:
            self.connection.close()
        with self._plock:
            pending = list(self._pending)
            self._pending = []
        for c in pending:
            if c.is_open:
                c.close()
