"""Frontend/backend process split — the seam, realized across processes.

Parity: the reference's stated design goal is that RepoFrontend runs on
a UI thread/process while RepoBackend runs elsewhere, joined only by
JSON-serializable messages (reference README.md:160-184, one frontend
per backend). Every message in msgs.py is a plain dict, so the split is
a transport choice: this module pumps the two queues over a unix-domain
socket using the same framed duplex the TCP swarm uses.

Backend process:
    python -m hypermerge_tpu.net.ipc /path/to/repo /tmp/backend.sock

Frontend process:
    from hypermerge_tpu.net.ipc import connect_frontend
    front, close = connect_frontend("/tmp/backend.sock")
    url = front.create({"hello": "world"})
    ...
    close()

The XLA bulk path, storage, crypto, and networking all live with the
backend; the frontend process needs none of them loaded.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable, Optional, Tuple

from .tcp import TcpDuplex


class ReplyFence:
    """Fences one backend's query replies across frontend swaps.

    Persist mode reuses ONE live backend for successive frontends. The
    swap drains *buffered* messages, but a handler still in flight on
    another thread (a Materialize query walking a large history, a
    patch decode) pushes its Reply AFTER the drain — and the next
    frontend's queryId counter restarts at the same small integers, so
    a previous frontend's late reply would resolve the wrong promise.

    Inbound Query ids are tagged with the accepting connection's epoch;
    outbound Replies only pass a gate bound to the same epoch (and are
    untagged back to the frontend's raw id). A reply produced by an
    in-flight handler from a previous frontend therefore dies at the
    gate instead of being delivered cross-session.
    """

    def __init__(self) -> None:
        self.epoch = 0

    def advance(self) -> int:
        self.epoch += 1
        return self.epoch

    def inbound(self, msg, epoch: int):
        """Tag a frontend->backend Query with the accepting
        connection's epoch (the backend echoes queryId opaquely into
        its Reply). The epoch is bound at accept time, NOT read at
        dispatch time: a previous connection's reader thread that
        dispatches a decoded frame after the swap must tag with ITS
        epoch, so the resulting Reply still dies at the new gate."""
        if isinstance(msg, dict) and msg.get("type") == "Query":
            msg = dict(msg)
            msg["queryId"] = [epoch, msg["queryId"]]
        return msg

    def outbound(self, epoch: int, msg):
        """The backend->frontend message for a gate bound to `epoch`,
        with the raw queryId restored — or None when the Reply belongs
        to a different frontend session (dropped)."""
        if isinstance(msg, dict) and msg.get("type") == "Reply":
            qid = msg.get("queryId")
            if isinstance(qid, list) and len(qid) == 2:
                if qid[0] != epoch:
                    return None  # a previous frontend's late reply
                msg = dict(msg)
                msg["queryId"] = qid[1]
        return msg

    def gate(self, send):
        """A subscriber for backend.to_frontend bound to the CURRENT
        epoch: drops other epochs' replies, untags this one's."""
        epoch = self.epoch

        def fn(msg):
            out = self.outbound(epoch, msg)
            if out is not None:
                send(out)

        return fn


def serve_backend(
    sock_path: str,
    repo_path: Optional[str] = None,
    memory: bool = False,
    once: bool = True,
    tcp_listen: bool = False,
    tcp_connect: Optional[list] = None,
) -> None:
    """Host a RepoBackend behind a unix socket. `once` serves a single
    frontend connection then returns (the reference pairs exactly one
    frontend per backend). With `tcp_listen`/`tcp_connect` the backend
    process also joins the peer swarm over TCP (the daemon owns the
    networking; the frontend process needs none of it loaded)."""
    from ..backend.repo_backend import RepoBackend

    if os.path.exists(sock_path):
        os.remove(sock_path)
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    # backlog > 1: a probe burst (port scan, health check) must not make
    # a real frontend's connect fail with EAGAIN while the accept loop
    # is still tearing down the previous connection (AF_UNIX connect
    # does not wait for backlog space on Linux)
    server.listen(8)
    print(f"backend ready on {sock_path}", flush=True)

    def build_backend() -> "RepoBackend":
        # the daemon's repo + swarm come up BEFORE a frontend attaches:
        # it replicates with peers on its own; the frontend is a client
        back = RepoBackend(path=repo_path, memory=memory)
        if tcp_listen or tcp_connect:
            from .tcp import TcpSwarm

            swarm = TcpSwarm()
            back.set_swarm(swarm)
            host, port = swarm.address
            print(f"swarm listening on {host}:{port}", flush=True)
            for addr in tcp_connect or []:
                h, _, p = addr.rpartition(":")
                swarm.connect((h, int(p)))
        return back

    back = build_backend()
    idle_sink = False  # a discard sink is attached between frontends
    fence = ReplyFence()  # queryIds are epoch-tagged per frontend: a
    # previous frontend's in-flight handler cannot deliver its late
    # Reply to the next one (whose queryId counter restarts)
    try:
        while True:
            conn, _ = server.accept()
            duplex = TcpDuplex(conn, is_client=False)
            if duplex.closed:
                # failed handshake (probe, health check, misconfigured
                # client): this was not the frontend — the LIVE backend,
                # its swarm, and its replicated state stay untouched
                continue
            if idle_sink:
                # swap the discard sink for the real frontend; drop the
                # handful of messages a push could buffer in the swap
                # window (a PREVIOUS frontend's replies/patches must
                # never reach this one — its queryId counter restarts)
                back.to_frontend.unsubscribe()
                back.to_frontend.drain()
                idle_sink = False
            epoch = fence.advance()
            back.subscribe(fence.gate(duplex.send))
            duplex.on_message(
                lambda msg, _f=fence, _e=epoch: back.receive(
                    _f.inbound(msg, _e)
                )
            )
            gone = threading.Event()
            duplex.on_close(gone.set)
            gone.wait()
            if once:
                return
            # non-once: REUSE the live backend for the next frontend —
            # closing + rebuilding per cycle would rebind the advertised
            # swarm port (stranding --connect peers), drop a :memory:
            # repo's replicated state, and spin up a fresh set of
            # debouncer threads/device caches every cycle. While no
            # frontend is attached, a DISCARD sink consumes pushes
            # (swarm-replicated patches, gossip) so the queue cannot
            # grow without bound on an idle daemon; the next frontend
            # opens its docs fresh and gets its own Ready/patch stream.
            back.to_frontend.unsubscribe()
            back.to_frontend.drain()
            back.subscribe(lambda _msg: None)
            idle_sink = True
    finally:
        back.close()
        server.close()
        if os.path.exists(sock_path):
            os.remove(sock_path)


def connect_frontend(
    sock_path: str,
) -> Tuple["RepoFrontend", Callable[[], None]]:
    """A RepoFrontend wired to a remote backend. Returns (frontend,
    close)."""
    from ..frontend.repo_frontend import RepoFrontend

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    duplex = TcpDuplex(sock, is_client=True)
    if duplex.closed:
        raise ConnectionError(
            f"handshake with backend at {sock_path} failed"
        )
    front = RepoFrontend()
    front.subscribe(duplex.send)
    duplex.on_message(front.receive)
    return front, duplex.close


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hypermerge_tpu.net.ipc",
        description="Host a RepoBackend daemon behind a unix socket.",
    )
    ap.add_argument("repo_path", help="repo directory, or :memory:")
    ap.add_argument("sock_path", help="unix socket for the frontend")
    ap.add_argument(
        "--listen", action="store_true",
        help="join the peer swarm: listen on TCP (address printed)",
    )
    ap.add_argument(
        "--connect", action="append", default=[], metavar="HOST:PORT",
        help="join the peer swarm: dial another backend (repeatable)",
    )
    ap.add_argument(
        "--persist", action="store_true",
        help="keep serving after a frontend disconnects (ONE live "
        "backend is reused across frontend cycles: swarm port and "
        "replicated state persist)",
    )
    args = ap.parse_args()
    serve_backend(
        args.sock_path,
        repo_path=None if args.repo_path == ":memory:" else args.repo_path,
        memory=args.repo_path == ":memory:",
        once=not args.persist,
        tcp_listen=args.listen,
        tcp_connect=args.connect,
    )


if __name__ == "__main__":
    main()
