"""Frontend/backend process split — the seam, realized across processes.

Parity: the reference's stated design goal is that RepoFrontend runs on
a UI thread/process while RepoBackend runs elsewhere, joined only by
JSON-serializable messages (reference README.md:160-184, one frontend
per backend). Every message in msgs.py is a plain dict, so the split is
a transport choice: this module pumps the two queues over a unix-domain
socket using the same framed duplex the TCP swarm uses.

Backend process:
    python -m hypermerge_tpu.net.ipc /path/to/repo /tmp/backend.sock

Frontend process:
    from hypermerge_tpu.net.ipc import connect_frontend
    front, close = connect_frontend("/tmp/backend.sock")
    url = front.create({"hello": "world"})
    ...
    close()

The XLA bulk path, storage, crypto, and networking all live with the
backend; the frontend process needs none of them loaded.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable, Dict, Optional, Set, Tuple

from ..analysis.lockdep import make_lock
from .tcp import TcpDuplex


class ReplyFence:
    """Fences one backend's query replies across frontend swaps.

    Persist mode reuses ONE live backend for successive frontends. The
    swap drains *buffered* messages, but a handler still in flight on
    another thread (a Materialize query walking a large history, a
    patch decode) pushes its Reply AFTER the drain — and the next
    frontend's queryId counter restarts at the same small integers, so
    a previous frontend's late reply would resolve the wrong promise.

    Inbound Query ids are tagged with the accepting connection's epoch;
    outbound Replies only pass a gate bound to the same epoch (and are
    untagged back to the frontend's raw id). A reply produced by an
    in-flight handler from a previous frontend therefore dies at the
    gate instead of being delivered cross-session.
    """

    def __init__(self) -> None:
        self.epoch = 0

    def advance(self) -> int:
        self.epoch += 1
        return self.epoch

    def inbound(self, msg, epoch: int):
        """Tag a frontend->backend Query with the accepting
        connection's epoch (the backend echoes queryId opaquely into
        its Reply). The epoch is bound at accept time, NOT read at
        dispatch time: a previous connection's reader thread that
        dispatches a decoded frame after the swap must tag with ITS
        epoch, so the resulting Reply still dies at the new gate."""
        if isinstance(msg, dict) and msg.get("type") == "Query":
            msg = dict(msg)
            msg["queryId"] = [epoch, msg["queryId"]]
        return msg

    def outbound(self, epoch: int, msg):
        """The backend->frontend message for a gate bound to `epoch`,
        with the raw queryId restored — or None when the Reply belongs
        to a different frontend session (dropped)."""
        if isinstance(msg, dict) and msg.get("type") == "Reply":
            qid = msg.get("queryId")
            if isinstance(qid, list) and len(qid) == 2:
                if qid[0] != epoch:
                    return None  # a previous frontend's late reply
                msg = dict(msg)
                msg["queryId"] = qid[1]
        return msg

    def gate(self, send):
        """A subscriber for backend.to_frontend bound to the CURRENT
        epoch: drops other epochs' replies, untags this one's."""
        epoch = self.epoch

        def fn(msg):
            out = self.outbound(epoch, msg)
            if out is not None:
                send(out)

        return fn


class _FrontendHub:
    """Many frontends, ONE daemon backend — the connection/interest
    table behind `serve_backend(hub=True)` (`--hub`), and the process
    topology bench `config_writers` measures: N writer processes
    editing disjoint docs against one backend, whose per-doc emission
    domains (backend/emission.py) let their {patch -> feed append ->
    WAL commit -> push} pipelines run concurrently.

    Each accepted frontend gets a connection key. Its Query ids are
    tagged `[key, raw]` so Replies route back to the issuing frontend
    only (the ReplyFence trick, per connection instead of per epoch —
    every frontend's queryId counter starts at the same small
    integers). Doc-addressed pushes (Ready/Patch/ActorId/Download/...)
    route by INTEREST: a frontend that named a doc id in any message
    (Open/Create/Request/...) receives that doc's pushes, and
    disjoint-doc writers never see each other's patch traffic; Close/
    Destroy retires the interest. Un-addressed pushes broadcast.
    Supported write topology: ONE writing frontend per doc (any number
    of watchers) — the backend grants one writable actor per doc, so
    two connections editing the same doc would collide on its seq
    counter. Concurrent same-doc writers belong on separate daemons
    joined by replication (the reference design); hub mode's
    concurrency win is disjoint docs.
    Socket sends run OUTSIDE the hub lock (`net.ipc.hub`,
    analysis/hierarchy.py): a slow frontend must not stall accepts or
    another connection's teardown."""

    def __init__(self, back) -> None:
        self._back = back
        self._lock = make_lock("net.ipc.hub")
        self._conns: Dict[int, TcpDuplex] = {}
        self._interest: Dict[str, Set[int]] = {}  # doc id -> conn keys
        self._next_key = 0

    def attach(self, duplex: TcpDuplex) -> None:
        with self._lock:
            self._next_key += 1
            key = self._next_key
            self._conns[key] = duplex
        duplex.on_close(lambda _k=key: self._detach(_k))
        duplex.on_message(lambda msg, _k=key: self._inbound(_k, msg))

    def _detach(self, key: int) -> None:
        with self._lock:
            self._conns.pop(key, None)
            # drop doc entries whose last watcher left — a long-lived
            # daemon's interest table must track LIVE interest, not
            # every doc id ever named (it would grow monotonically
            # with lifetime doc count otherwise)
            emptied = []
            for doc_id, keys in self._interest.items():
                keys.discard(key)
                if not keys:
                    emptied.append(doc_id)
            for doc_id in emptied:
                del self._interest[doc_id]

    def _inbound(self, key: int, msg) -> None:
        if isinstance(msg, dict):
            t = msg.get("type")
            doc_id = (
                msg.get("publicKey") if t == "Create" else msg.get("id")
            )
            with self._lock:
                if doc_id is not None:
                    if t in ("Close", "Destroy"):
                        keys = self._interest.get(doc_id)
                        if keys is not None:
                            keys.discard(key)
                            if not keys:
                                del self._interest[doc_id]
                    else:
                        self._interest.setdefault(doc_id, set()).add(key)
                if t == "OpenBulk":
                    for i in msg.get("ids", ()):
                        self._interest.setdefault(i, set()).add(key)
            if t == "Query":
                msg = dict(msg)
                msg["queryId"] = [key, msg["queryId"]]
        self._back.receive(msg)

    def dispatch(self, msg) -> None:
        """The ONE to_frontend subscriber: Replies to their issuing
        connection, doc-addressed pushes to the interested
        connections, everything else to everyone."""
        if isinstance(msg, dict):
            if msg.get("type") == "Reply":
                qid = msg.get("queryId")
                if not (isinstance(qid, list) and len(qid) == 2):
                    return  # not hub-tagged: no route back
                with self._lock:
                    duplex = self._conns.get(qid[0])
                if duplex is not None:
                    out = dict(msg)
                    out["queryId"] = qid[1]
                    self._send(duplex, out)
                return
            doc_id = msg.get("id")
            if doc_id is not None:
                with self._lock:
                    targets = [
                        self._conns[k]
                        for k in self._interest.get(doc_id, ())
                        if k in self._conns
                    ]
                for duplex in targets:
                    self._send(duplex, msg)
                return
        with self._lock:
            targets = list(self._conns.values())
        for duplex in targets:
            self._send(duplex, msg)

    @staticmethod
    def _send(duplex: TcpDuplex, msg) -> None:
        try:
            duplex.send(msg)
        except OSError:
            pass  # the duplex's on_close detach reaps the connection


def serve_backend(
    sock_path: str,
    repo_path: Optional[str] = None,
    memory: bool = False,
    once: bool = True,
    tcp_listen: bool = False,
    tcp_connect: Optional[list] = None,
    hub: bool = False,
    dht: bool = False,
    dht_bootstrap: Optional[list] = None,
) -> None:
    """Host a RepoBackend behind a unix socket. `once` serves a single
    frontend connection then returns (the reference pairs exactly one
    frontend per backend). With `tcp_listen`/`tcp_connect` the backend
    process also joins the peer swarm over TCP (the daemon owns the
    networking; the frontend process needs none of it loaded). With
    `dht` it joins fleet-style instead (net/discovery/ DhtSwarm): dial
    targets come from DHT announce/lookup — no addresses to configure
    beyond `dht_bootstrap` ("host:port" strings; default
    HM_DHT_BOOTSTRAP)."""
    from ..backend.repo_backend import RepoBackend

    if os.path.exists(sock_path):
        os.remove(sock_path)
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    # backlog > 1: a probe burst (port scan, health check) must not make
    # a real frontend's connect fail with EAGAIN while the accept loop
    # is still tearing down the previous connection (AF_UNIX connect
    # does not wait for backlog space on Linux)
    server.listen(8)
    print(f"backend ready on {sock_path}", flush=True)

    def build_backend() -> "RepoBackend":
        # the daemon's repo + swarm come up BEFORE a frontend attaches:
        # it replicates with peers on its own; the frontend is a client
        back = RepoBackend(path=repo_path, memory=memory)
        if dht or dht_bootstrap:
            from .discovery import DhtSwarm

            bootstrap = None
            if dht_bootstrap:
                bootstrap = []
                for addr in dht_bootstrap:
                    h, _, p = addr.rpartition(":")
                    bootstrap.append((h, int(p)))
            swarm = DhtSwarm(bootstrap=bootstrap)
            # fleet posture: every feed on record joins discovery NOW
            # (announce + serve), not at first frontend/doc open
            back.hydrate_feeds()
            back.set_swarm(swarm)
            th, tp = swarm.address
            dh, dp = swarm.dht_address
            print(
                f"dht node {swarm.node.id_hex[:12]}… udp {dh}:{dp} "
                f"swarm listening on {th}:{tp}",
                flush=True,
            )
        elif tcp_listen or tcp_connect:
            from .tcp import TcpSwarm

            swarm = TcpSwarm()
            back.set_swarm(swarm)
            host, port = swarm.address
            print(f"swarm listening on {host}:{port}", flush=True)
            for addr in tcp_connect or []:
                h, _, p = addr.rpartition(":")
                swarm.connect((h, int(p)))
        return back

    back = build_backend()
    if hub:
        # many-frontend mode: every accepted connection joins the hub;
        # the backend's push stream routes by doc interest and Replies
        # by issuing connection. The daemon runs until killed.
        hub_obj = _FrontendHub(back)
        back.subscribe(hub_obj.dispatch)
        try:
            while True:
                conn, _ = server.accept()
                duplex = TcpDuplex(conn, is_client=False)
                if duplex.closed:
                    continue  # probe/failed handshake
                hub_obj.attach(duplex)
        finally:
            back.close()
            server.close()
            if os.path.exists(sock_path):
                os.remove(sock_path)
        return
    idle_sink = False  # a discard sink is attached between frontends
    fence = ReplyFence()  # queryIds are epoch-tagged per frontend: a
    # previous frontend's in-flight handler cannot deliver its late
    # Reply to the next one (whose queryId counter restarts)
    try:
        while True:
            conn, _ = server.accept()
            duplex = TcpDuplex(conn, is_client=False)
            if duplex.closed:
                # failed handshake (probe, health check, misconfigured
                # client): this was not the frontend — the LIVE backend,
                # its swarm, and its replicated state stay untouched
                continue
            if idle_sink:
                # swap the discard sink for the real frontend; drop the
                # handful of messages a push could buffer in the swap
                # window (a PREVIOUS frontend's replies/patches must
                # never reach this one — its queryId counter restarts)
                back.to_frontend.unsubscribe()
                back.to_frontend.drain()
                idle_sink = False
            epoch = fence.advance()
            back.subscribe(fence.gate(duplex.send))
            duplex.on_message(
                lambda msg, _f=fence, _e=epoch: back.receive(
                    _f.inbound(msg, _e)
                )
            )
            gone = threading.Event()
            duplex.on_close(gone.set)
            gone.wait()
            if once:
                return
            # non-once: REUSE the live backend for the next frontend —
            # closing + rebuilding per cycle would rebind the advertised
            # swarm port (stranding --connect peers), drop a :memory:
            # repo's replicated state, and spin up a fresh set of
            # debouncer threads/device caches every cycle. While no
            # frontend is attached, a DISCARD sink consumes pushes
            # (swarm-replicated patches, gossip) so the queue cannot
            # grow without bound on an idle daemon; the next frontend
            # opens its docs fresh and gets its own Ready/patch stream.
            back.to_frontend.unsubscribe()
            back.to_frontend.drain()
            back.subscribe(lambda _msg: None)
            idle_sink = True
    finally:
        back.close()
        server.close()
        if os.path.exists(sock_path):
            os.remove(sock_path)


def connect_frontend(
    sock_path: str,
) -> Tuple["RepoFrontend", Callable[[], None]]:
    """A RepoFrontend wired to a remote backend. Returns (frontend,
    close)."""
    from ..frontend.repo_frontend import RepoFrontend

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    duplex = TcpDuplex(sock, is_client=True)
    if duplex.closed:
        raise ConnectionError(
            f"handshake with backend at {sock_path} failed"
        )
    front = RepoFrontend()
    front.subscribe(duplex.send)
    duplex.on_message(front.receive)
    return front, duplex.close


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hypermerge_tpu.net.ipc",
        description="Host a RepoBackend daemon behind a unix socket.",
    )
    ap.add_argument("repo_path", help="repo directory, or :memory:")
    ap.add_argument("sock_path", help="unix socket for the frontend")
    ap.add_argument(
        "--listen", action="store_true",
        help="join the peer swarm: listen on TCP (address printed)",
    )
    ap.add_argument(
        "--connect", action="append", default=[], metavar="HOST:PORT",
        help="join the peer swarm: dial another backend (repeatable)",
    )
    ap.add_argument(
        "--dht", action="store_true",
        help="join the peer swarm fleet-style via the DHT "
        "(net/discovery/): announce/lookup by doc id, no explicit "
        "addresses; bootstrap from --dht-bootstrap or "
        "HM_DHT_BOOTSTRAP",
    )
    ap.add_argument(
        "--dht-bootstrap", action="append", default=[],
        metavar="HOST:PORT",
        help="DHT bootstrap node (repeatable; implies --dht)",
    )
    ap.add_argument(
        "--persist", action="store_true",
        help="keep serving after a frontend disconnects (ONE live "
        "backend is reused across frontend cycles: swarm port and "
        "replicated state persist)",
    )
    ap.add_argument(
        "--hub", action="store_true",
        help="serve MANY concurrent frontends against the one "
        "backend (per-connection reply routing, per-doc interest "
        "routing) — the many-writer daemon of bench config_writers",
    )
    args = ap.parse_args()
    serve_backend(
        args.sock_path,
        repo_path=None if args.repo_path == ":memory:" else args.repo_path,
        memory=args.repo_path == ":memory:",
        once=not args.persist,
        tcp_listen=args.listen,
        tcp_connect=args.connect,
        hub=args.hub,
        dht=args.dht,
        dht_bootstrap=args.dht_bootstrap,
    )


if __name__ == "__main__":
    main()
