"""Frontend/backend process split — the seam, realized across processes.

Parity: the reference's stated design goal is that RepoFrontend runs on
a UI thread/process while RepoBackend runs elsewhere, joined only by
JSON-serializable messages (reference README.md:160-184, one frontend
per backend). Every message in msgs.py is a plain dict, so the split is
a transport choice: this module pumps the two queues over a unix-domain
socket using the same framed duplex the TCP swarm uses.

Backend process:
    python -m hypermerge_tpu.net.ipc /path/to/repo /tmp/backend.sock

Frontend process:
    from hypermerge_tpu.net.ipc import connect_frontend
    front, close = connect_frontend("/tmp/backend.sock")
    url = front.create({"hello": "world"})
    ...
    close()

The XLA bulk path, storage, crypto, and networking all live with the
backend; the frontend process needs none of them loaded.
"""

from __future__ import annotations

import hashlib
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..analysis.lockdep import make_lock
from .tcp import TcpDuplex


class ReplyFence:
    """Fences one backend's query replies across frontend swaps.

    Persist mode reuses ONE live backend for successive frontends. The
    swap drains *buffered* messages, but a handler still in flight on
    another thread (a Materialize query walking a large history, a
    patch decode) pushes its Reply AFTER the drain — and the next
    frontend's queryId counter restarts at the same small integers, so
    a previous frontend's late reply would resolve the wrong promise.

    Inbound Query ids are tagged with the accepting connection's epoch;
    outbound Replies only pass a gate bound to the same epoch (and are
    untagged back to the frontend's raw id). A reply produced by an
    in-flight handler from a previous frontend therefore dies at the
    gate instead of being delivered cross-session.
    """

    def __init__(self) -> None:
        self.epoch = 0

    def advance(self) -> int:
        self.epoch += 1
        return self.epoch

    def inbound(self, msg, epoch: int):
        """Tag a frontend->backend Query with the accepting
        connection's epoch (the backend echoes queryId opaquely into
        its Reply). The epoch is bound at accept time, NOT read at
        dispatch time: a previous connection's reader thread that
        dispatches a decoded frame after the swap must tag with ITS
        epoch, so the resulting Reply still dies at the new gate."""
        if isinstance(msg, dict) and msg.get("type") == "Query":
            msg = dict(msg)
            msg["queryId"] = [epoch, msg["queryId"]]
        return msg

    def outbound(self, epoch: int, msg):
        """The backend->frontend message for a gate bound to `epoch`,
        with the raw queryId restored — or None when the Reply belongs
        to a different frontend session (dropped)."""
        if isinstance(msg, dict) and msg.get("type") == "Reply":
            qid = msg.get("queryId")
            if isinstance(qid, list) and len(qid) == 2:
                if qid[0] != epoch:
                    return None  # a previous frontend's late reply
                msg = dict(msg)
                msg["queryId"] = qid[1]
        return msg

    def gate(self, send):
        """A subscriber for backend.to_frontend bound to the CURRENT
        epoch: drops other epochs' replies, untags this one's."""
        epoch = self.epoch

        def fn(msg):
            out = self.outbound(epoch, msg)
            if out is not None:
                send(out)

        return fn


class _FrontendHub:
    """Many frontends, ONE daemon backend — the connection/interest
    table behind `serve_backend(hub=True)` (`--hub`), and the process
    topology bench `config_writers` measures: N writer processes
    editing disjoint docs against one backend, whose per-doc emission
    domains (backend/emission.py) let their {patch -> feed append ->
    WAL commit -> push} pipelines run concurrently.

    Each accepted frontend gets a connection key. Its Query ids are
    tagged `[key, raw]` so Replies route back to the issuing frontend
    only (the ReplyFence trick, per connection instead of per epoch —
    every frontend's queryId counter starts at the same small
    integers). Doc-addressed pushes (Ready/Patch/ActorId/Download/...)
    route by INTEREST: a frontend that named a doc id in any message
    (Open/Create/Request/...) receives that doc's pushes, and
    disjoint-doc writers never see each other's patch traffic; Close/
    Destroy retires the interest. Un-addressed pushes broadcast.
    Write topology: MANY writing frontends per doc. Create/Open/
    NeedsActorId are tagged with the connection key (`writer`), and the
    backend mints one actor PER WRITING CONNECTION (repo_backend
    `_grant_writer_actor`), so concurrent same-doc writers never share
    a seq counter. Ready/ActorId replies carrying a `writer` tag route
    ONLY to that connection (tag stripped); Patch traffic stays
    interest-broadcast — every connection converges through the
    backend's emission-ordered patch stream. HM_HUB_WRITERS=0 reverts
    to the legacy one-writer-per-doc tagging-free protocol.
    Socket sends run OUTSIDE the hub lock (`net.ipc.hub`,
    analysis/hierarchy.py): a slow frontend must not stall accepts or
    another connection's teardown."""

    def __init__(self, back) -> None:
        self._back = back
        self._writers = (
            os.environ.get("HM_HUB_WRITERS", "1") != "0"
        )
        self._lock = make_lock("net.ipc.hub")
        self._conns: Dict[int, TcpDuplex] = {}
        self._interest: Dict[str, Set[int]] = {}  # doc id -> conn keys
        self._next_key = 0

    def attach(self, duplex: TcpDuplex) -> None:
        with self._lock:
            self._next_key += 1
            key = self._next_key
            self._conns[key] = duplex
        duplex.on_close(lambda _k=key: self._detach(_k))
        duplex.on_message(lambda msg, _k=key: self._inbound(_k, msg))

    def _detach(self, key: int) -> None:
        with self._lock:
            self._conns.pop(key, None)
            # drop doc entries whose last watcher left — a long-lived
            # daemon's interest table must track LIVE interest, not
            # every doc id ever named (it would grow monotonically
            # with lifetime doc count otherwise)
            emptied = []
            for doc_id, keys in self._interest.items():
                keys.discard(key)
                if not keys:
                    emptied.append(doc_id)
            for doc_id in emptied:
                del self._interest[doc_id]
        if self._writers:
            # the backend forgets the gone connection's per-doc actor
            # grants (a long-lived daemon must not leak one map entry
            # per connection ever accepted). Outside the hub lock: the
            # backend takes its own locks.
            self._back.receive({"type": "WriterGone", "writer": key})

    def snapshot_interest(self):
        """Doc ids any live connection currently watches — the shard
        router's respawn replay set (a revived worker re-Opens these so
        its docs announce and resume patch pushes)."""
        with self._lock:
            return list(self._interest.keys())

    def _inbound(self, key: int, msg) -> None:
        if isinstance(msg, dict):
            t = msg.get("type")
            doc_id = (
                msg.get("publicKey") if t == "Create" else msg.get("id")
            )
            with self._lock:
                if doc_id is not None:
                    if t in ("Close", "Destroy"):
                        keys = self._interest.get(doc_id)
                        if keys is not None:
                            keys.discard(key)
                            if not keys:
                                del self._interest[doc_id]
                    else:
                        self._interest.setdefault(doc_id, set()).add(key)
                if t == "OpenBulk":
                    for i in msg.get("ids", ()):
                        self._interest.setdefault(i, set()).add(key)
            if t == "Query":
                msg = dict(msg)
                msg["queryId"] = [key, msg["queryId"]]
                # tenant attribution for the service plane: every
                # connection is its own tenant unless the client
                # named one — the overload controller's quotas and
                # refusal counters key on this
                inner = msg.get("query")
                if (
                    isinstance(inner, dict)
                    and inner.get("type") == "Read"
                    and isinstance(inner.get("query"), dict)
                    and "tenant" not in inner["query"]
                ):
                    inner = dict(inner)
                    inner["query"] = dict(
                        inner["query"], tenant=f"conn{key}"
                    )
                    msg["query"] = inner
            elif self._writers and t in (
                "Create", "Open", "NeedsActorId"
            ):
                # many-writer plane: the backend grants this CONNECTION
                # its own actor per doc and routes the tagged Ready/
                # ActorId back here only
                msg = dict(msg)
                msg["writer"] = key
        self._back.receive(msg)

    def dispatch(self, msg) -> None:
        """The ONE to_frontend subscriber: Replies to their issuing
        connection, doc-addressed pushes to the interested
        connections, everything else to everyone."""
        if isinstance(msg, dict):
            if msg.get("type") == "Reply":
                qid = msg.get("queryId")
                if not (isinstance(qid, list) and len(qid) == 2):
                    return  # not hub-tagged: no route back
                with self._lock:
                    duplex = self._conns.get(qid[0])
                if duplex is not None:
                    out = dict(msg)
                    out["queryId"] = qid[1]
                    self._send(duplex, out)
                return
            writer = msg.get("writer")
            if writer is not None:
                # per-connection push (tagged Ready/ActorId): ONLY the
                # connection it was minted for sees it. writer == -1 is
                # the respawn-replay sentinel (routes to nobody — the
                # Open existed to re-announce the doc in the worker).
                with self._lock:
                    duplex = self._conns.get(writer)
                if duplex is not None:
                    out = dict(msg)
                    del out["writer"]
                    self._send(duplex, out)
                return
            doc_id = msg.get("id")
            if doc_id is not None:
                with self._lock:
                    targets = [
                        self._conns[k]
                        for k in self._interest.get(doc_id, ())
                        if k in self._conns
                    ]
                for duplex in targets:
                    self._send(duplex, msg)
                return
        with self._lock:
            targets = list(self._conns.values())
        for duplex in targets:
            self._send(duplex, msg)

    @staticmethod
    def _send(duplex: TcpDuplex, msg) -> None:
        try:
            duplex.send(msg)
        except OSError:
            pass  # the duplex's on_close detach reaps the connection


def _shard_of(doc_id: str, n: int) -> int:
    """Stable doc-id -> worker shard (sha1 prefix mod n): every process
    — hub, tests, tools — computes the same owner for a doc."""
    digest = hashlib.sha1(
        doc_id.encode("utf-8", "surrogatepass")
    ).hexdigest()
    return int(digest[:8], 16) % n


class _ShardRouter:
    """HM_WORKERS per-doc-range worker PROCESSES behind one hub — the
    GIL-free write plane. The hub-facing surface is a RepoBackend
    stand-in (`receive`/`close`); behind it, doc-addressed messages
    route by `_shard_of(doc_id)` to a worker subprocess (a plain
    once-mode `net.ipc` daemon owning `<repo>/shard-<k>` — its OWN
    engine, feeds, and WAL) over the same framed duplex frontends use.
    Worker ReplyFence tagging nests queryIds transparently.

    Telemetry Queries fan out to every worker and merge (counters sum,
    time is the max, per-worker `workers.<i>.*` gauges are injected);
    a dead worker is covered by a timeout so `tools/top.py` never
    hangs on a crash window.

    Worker death (duplex close) is SUPERVISED: after
    HM_WORKER_RESPAWN_MS the old process is reaped, a fresh one is
    spawned on the same shard repo + socket, the hub's live interest
    set is replayed as `writer=-1` Opens (re-announce without waking
    any frontend), and messages buffered during the outage flush. The
    revived worker's own crash recovery (dirty marker + WAL journal
    prefix) restores every acked edit; persisted actor keys keep the
    reconnecting frontends' actors writable. An unacked in-flight
    request dies with the worker — exactly the pre-ack loss crash
    semantics the WAL tests pin.
    """

    def __init__(
        self,
        repo_path: Optional[str],
        sock_base: str,
        n_workers: int,
    ) -> None:
        self._repo_path = repo_path
        self._sock_base = sock_base
        self._n = n_workers
        self._lock = make_lock("net.ipc.router")
        self._workers: List[Optional[Dict[str, Any]]] = [None] * n_workers
        self._pending: List[List[Any]] = [[] for _ in range(n_workers)]
        self._respawns = [0] * n_workers
        self._gen = 0
        self._tele: Dict[int, Dict[str, Any]] = {}
        self._next_tele = 0
        self._closed = False
        # set-once wiring, installed by start() BEFORE workers spawn
        self._dispatch: Callable[[Any], None] = lambda _msg: None
        self._interest: Callable[[], list] = lambda: []
        if repo_path is not None:
            os.makedirs(repo_path, exist_ok=True)

    # -- lifecycle -----------------------------------------------------

    def start(self, dispatch, snapshot_interest) -> None:
        """Wire the hub sinks, then bring up every worker (order
        matters: a worker's first push must find dispatch installed)."""
        self._dispatch = dispatch
        self._interest = snapshot_interest
        for i in range(self._n):
            pid = self._spawn(i)
            print(f"worker {i} pid {pid}", flush=True)

    def _shard_repo(self, i: int) -> str:
        if self._repo_path is None:
            return ":memory:"
        return os.path.join(self._repo_path, f"shard-{i}")

    def _spawn(self, i: int) -> int:
        """Start worker i and connect to it (retried: the worker binds
        its socket only after its interpreter + backend imports)."""
        wsock = f"{self._sock_base}.w{i}"
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "hypermerge_tpu.net.ipc",
                self._shard_repo(i),
                wsock,
            ],
            stdout=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + 120.0
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"worker {i} died on startup "
                    f"(rc={proc.returncode})"
                )
            if time.monotonic() > deadline:
                proc.kill()
                raise RuntimeError(f"worker {i} never bound {wsock}")
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(wsock)
            except OSError:
                time.sleep(0.05)
                continue
            duplex = TcpDuplex(s, is_client=True)
            if duplex.closed:  # bind/handshake race: try again
                time.sleep(0.05)
                continue
            break
        with self._lock:
            self._gen += 1
            gen = self._gen
            self._workers[i] = {
                "proc": proc,
                "duplex": duplex,
                "gen": gen,
                "pid": proc.pid,
            }
        duplex.on_message(lambda msg, _i=i: self._from_worker(_i, msg))
        duplex.on_close(lambda _i=i, _g=gen: self._worker_gone(_i, _g))
        return proc.pid

    def _worker_gone(self, i: int, gen: int) -> None:
        with self._lock:
            slot = self._workers[i]
            if self._closed or slot is None or slot["gen"] != gen:
                return  # shutdown, or a respawn already superseded it
        threading.Thread(
            target=self._respawn, args=(i, gen), daemon=True
        ).start()

    def _respawn(self, i: int, gen: int) -> None:
        time.sleep(
            float(os.environ.get("HM_WORKER_RESPAWN_MS", "200")) / 1e3
        )
        with self._lock:
            slot = self._workers[i]
            if self._closed or slot is None or slot["gen"] != gen:
                return
        try:
            slot["proc"].kill()
            slot["proc"].wait(10)
        except OSError:
            pass
        try:
            pid = self._spawn(i)
        except RuntimeError:
            with self._lock:  # crash loop: leave the slot for close()
                if not self._closed:
                    self._workers[i] = None
            return
        with self._lock:
            self._respawns[i] += 1
            flush = list(self._pending[i])
            del self._pending[i][:]
        # re-announce the shard's live docs (writer=-1: the tagged
        # Readys route to nobody; frontends already initialized) so
        # journal-prefix recovery materializes them and patch pushes
        # resume, THEN release anything buffered during the outage
        for doc_id in self._interest():
            if _shard_of(doc_id, self._n) == i:
                self._send_to(
                    i, {"type": "Open", "id": doc_id, "writer": -1}
                )
        for msg in flush:
            self._send_to(i, msg)
        print(f"worker {i} pid {pid} respawned", flush=True)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            slots = [w for w in self._workers if w is not None]
        for w in slots:
            try:
                w["duplex"].close()
            except OSError:
                pass
            w["proc"].terminate()
        for w in slots:
            try:
                w["proc"].wait(10)
            except subprocess.TimeoutExpired:
                w["proc"].kill()
                w["proc"].wait(10)
        for i in range(self._n):
            wsock = f"{self._sock_base}.w{i}"
            if os.path.exists(wsock):
                os.remove(wsock)

    # -- hub-facing backend surface ------------------------------------

    def receive(self, msg) -> None:
        if not isinstance(msg, dict):
            return
        t = msg.get("type")
        if t == "Query":
            query = msg.get("query")
            qtype = (
                query.get("type") if isinstance(query, dict) else None
            )
            if qtype == "Telemetry":
                self._telemetry_fanout(msg)
                return
            doc_id = (
                query.get("id") if isinstance(query, dict) else None
            )
            if doc_id is not None:
                self._send_to(_shard_of(doc_id, self._n), msg)
                return
        elif t == "OpenBulk":
            buckets: Dict[int, list] = {}
            for doc_id in msg.get("ids", ()):
                buckets.setdefault(
                    _shard_of(doc_id, self._n), []
                ).append(doc_id)
            for i, ids in buckets.items():
                self._send_to(i, {**msg, "ids": ids})
            return
        else:
            doc_id = (
                msg.get("publicKey") if t == "Create" else msg.get("id")
            )
            if doc_id is not None:
                self._send_to(_shard_of(doc_id, self._n), msg)
                return
        # not doc-addressed (WriterGone, unkeyed queries, ...): every
        # worker gets it
        for i in range(self._n):
            self._send_to(i, msg)

    def _send_to(self, i: int, msg) -> None:
        with self._lock:
            slot = self._workers[i]
            if slot is None or slot["duplex"].closed:
                # respawn window: park (bounded) — flushed on revival
                if len(self._pending[i]) < 10_000:
                    self._pending[i].append(msg)
                return
            duplex = slot["duplex"]
        try:
            duplex.send(msg)
        except OSError:
            with self._lock:
                if len(self._pending[i]) < 10_000:
                    self._pending[i].append(msg)

    def _from_worker(self, i: int, msg) -> None:
        if isinstance(msg, dict) and msg.get("type") == "Reply":
            qid = msg.get("queryId")
            if (
                isinstance(qid, list)
                and len(qid) == 3
                and qid[0] == "_tele"
            ):
                self._tele_collect(qid[1], qid[2], msg.get("payload"))
                return
        self._dispatch(msg)

    # -- telemetry fan-out/merge ---------------------------------------

    def _telemetry_fanout(self, msg) -> None:
        with self._lock:
            tok = self._next_tele
            self._next_tele += 1
            slot = {
                "qid": msg.get("queryId"),
                "left": set(range(self._n)),
                "payloads": {},
                "timer": None,
            }
            self._tele[tok] = slot
        timer = threading.Timer(2.0, self._tele_finish, args=(tok,))
        timer.daemon = True
        slot["timer"] = timer
        timer.start()
        for i in range(self._n):
            self._send_to(
                i,
                {
                    "type": "Query",
                    "queryId": ["_tele", tok, i],
                    "query": {"type": "Telemetry"},
                },
            )

    def _tele_collect(self, tok: int, i: int, payload) -> None:
        with self._lock:
            slot = self._tele.get(tok)
            if slot is None:
                return  # timer already fired with partial results
            slot["payloads"][i] = payload
            slot["left"].discard(i)
            done = not slot["left"]
        if done:
            self._tele_finish(tok)

    def _tele_finish(self, tok: int) -> None:
        with self._lock:
            slot = self._tele.pop(tok, None)
        if slot is None:
            return
        if slot["timer"] is not None:
            slot["timer"].cancel()
        self._dispatch(
            {
                "type": "Reply",
                "queryId": slot["qid"],
                "payload": self._merge_tele(slot["payloads"]),
            }
        )

    def _merge_tele(self, payloads: Dict[int, Any]) -> Dict[str, Any]:
        """One fleet-shaped payload from N worker payloads: counters
        sum, `time` is the max, net doc tables union, and a `workers`
        block (mirrored into `workers.<i>.*` counters so counter-only
        consumers like the Prometheus dump see them too) carries the
        per-worker split."""
        counters: Dict[str, Any] = {}
        merged: Dict[str, Any] = {
            "counters": counters,
            "time": 0.0,
            "workers": {},
        }
        for i in range(self._n):
            p = payloads.get(i)
            with self._lock:
                slot = self._workers[i]
                queue = (
                    len(slot["duplex"]._outbox)
                    if slot is not None
                    else 0
                )
                respawns = self._respawns[i]
                pid = slot["pid"] if slot is not None else None
                alive = p is not None
            edits = 0
            if isinstance(p, dict):
                for name, v in (p.get("counters") or {}).items():
                    if isinstance(v, (int, float)):
                        counters[name] = counters.get(name, 0) + v
                if isinstance(p.get("time"), (int, float)):
                    merged["time"] = max(merged["time"], p["time"])
                for section in ("serve", "dht"):
                    if section in p and section not in merged:
                        merged[section] = p[section]
                net = p.get("net")
                if isinstance(net, dict):
                    merged.setdefault("net", {"docs": {}})[
                        "docs"
                    ].update(net.get("docs") or {})
                pc = p.get("counters") or {}
                # WAL appends count every locally-written change block
                # on the durable plane (the hot-doc bench's metric);
                # engine-applied changes cover the WAL-off config
                edits = pc.get("storage.wal.appends") or pc.get(
                    "live.local_changes", 0
                )
            merged["workers"][str(i)] = {
                "pid": pid,
                "alive": alive,
                "edits": edits,
                "queue": queue,
                "respawns": respawns,
            }
            counters[f"workers.{i}.edits"] = edits
            counters[f"workers.{i}.queue"] = queue
            counters[f"workers.{i}.respawns"] = respawns
        return merged


def serve_backend(
    sock_path: str,
    repo_path: Optional[str] = None,
    memory: bool = False,
    once: bool = True,
    tcp_listen: bool = False,
    tcp_connect: Optional[list] = None,
    hub: bool = False,
    dht: bool = False,
    dht_bootstrap: Optional[list] = None,
) -> None:
    """Host a RepoBackend behind a unix socket. `once` serves a single
    frontend connection then returns (the reference pairs exactly one
    frontend per backend). With `tcp_listen`/`tcp_connect` the backend
    process also joins the peer swarm over TCP (the daemon owns the
    networking; the frontend process needs none of it loaded). With
    `dht` it joins fleet-style instead (net/discovery/ DhtSwarm): dial
    targets come from DHT announce/lookup — no addresses to configure
    beyond `dht_bootstrap` ("host:port" strings; default
    HM_DHT_BOOTSTRAP)."""
    from ..backend.repo_backend import RepoBackend

    if os.path.exists(sock_path):
        os.remove(sock_path)
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    # backlog > 1: a probe burst (port scan, health check) must not make
    # a real frontend's connect fail with EAGAIN while the accept loop
    # is still tearing down the previous connection (AF_UNIX connect
    # does not wait for backlog space on Linux)
    server.listen(8)
    print(f"backend ready on {sock_path}", flush=True)

    def build_backend() -> "RepoBackend":
        # the daemon's repo + swarm come up BEFORE a frontend attaches:
        # it replicates with peers on its own; the frontend is a client
        back = RepoBackend(path=repo_path, memory=memory)
        if dht or dht_bootstrap:
            from .discovery import DhtSwarm

            bootstrap = None
            if dht_bootstrap:
                bootstrap = []
                for addr in dht_bootstrap:
                    h, _, p = addr.rpartition(":")
                    bootstrap.append((h, int(p)))
            swarm = DhtSwarm(bootstrap=bootstrap)
            # fleet posture: every feed on record joins discovery NOW
            # (announce + serve), not at first frontend/doc open
            back.hydrate_feeds()
            back.set_swarm(swarm)
            th, tp = swarm.address
            dh, dp = swarm.dht_address
            print(
                f"dht node {swarm.node.id_hex[:12]}… udp {dh}:{dp} "
                f"swarm listening on {th}:{tp}",
                flush=True,
            )
        elif tcp_listen or tcp_connect:
            from .tcp import TcpSwarm

            swarm = TcpSwarm()
            back.set_swarm(swarm)
            host, port = swarm.address
            print(f"swarm listening on {host}:{port}", flush=True)
            for addr in tcp_connect or []:
                h, _, p = addr.rpartition(":")
                swarm.connect((h, int(p)))
        return back

    if hub:
        # many-frontend mode: every accepted connection joins the hub;
        # the backend's push stream routes by doc interest and Replies
        # by issuing connection. The daemon runs until killed. With
        # HM_WORKERS=N (> 0) the "backend" is a _ShardRouter over N
        # per-doc-range worker processes instead of an in-process
        # RepoBackend — the hub neither loads XLA nor holds the GIL
        # for engine work, and disjoint shards commit in parallel
        # across real processes. (Worker daemons own their own repos;
        # swarm flags apply to single-backend daemons only.)
        workers = int(os.environ.get("HM_WORKERS", "0") or "0")
        if workers > 0:
            back = _ShardRouter(repo_path, sock_path, workers)
            hub_obj = _FrontendHub(back)
            back.start(hub_obj.dispatch, hub_obj.snapshot_interest)
        else:
            back = build_backend()
            hub_obj = _FrontendHub(back)
            back.subscribe(hub_obj.dispatch)
        try:
            while True:
                conn, _ = server.accept()
                duplex = TcpDuplex(conn, is_client=False)
                if duplex.closed:
                    continue  # probe/failed handshake
                hub_obj.attach(duplex)
        finally:
            back.close()
            server.close()
            if os.path.exists(sock_path):
                os.remove(sock_path)
        return
    back = build_backend()
    idle_sink = False  # a discard sink is attached between frontends
    fence = ReplyFence()  # queryIds are epoch-tagged per frontend: a
    # previous frontend's in-flight handler cannot deliver its late
    # Reply to the next one (whose queryId counter restarts)
    try:
        while True:
            conn, _ = server.accept()
            duplex = TcpDuplex(conn, is_client=False)
            if duplex.closed:
                # failed handshake (probe, health check, misconfigured
                # client): this was not the frontend — the LIVE backend,
                # its swarm, and its replicated state stay untouched
                continue
            if idle_sink:
                # swap the discard sink for the real frontend; drop the
                # handful of messages a push could buffer in the swap
                # window (a PREVIOUS frontend's replies/patches must
                # never reach this one — its queryId counter restarts)
                back.to_frontend.unsubscribe()
                back.to_frontend.drain()
                idle_sink = False
            epoch = fence.advance()
            back.subscribe(fence.gate(duplex.send))
            duplex.on_message(
                lambda msg, _f=fence, _e=epoch: back.receive(
                    _f.inbound(msg, _e)
                )
            )
            gone = threading.Event()
            duplex.on_close(gone.set)
            gone.wait()
            if once:
                return
            # non-once: REUSE the live backend for the next frontend —
            # closing + rebuilding per cycle would rebind the advertised
            # swarm port (stranding --connect peers), drop a :memory:
            # repo's replicated state, and spin up a fresh set of
            # debouncer threads/device caches every cycle. While no
            # frontend is attached, a DISCARD sink consumes pushes
            # (swarm-replicated patches, gossip) so the queue cannot
            # grow without bound on an idle daemon; the next frontend
            # opens its docs fresh and gets its own Ready/patch stream.
            back.to_frontend.unsubscribe()
            back.to_frontend.drain()
            back.subscribe(lambda _msg: None)
            idle_sink = True
    finally:
        back.close()
        server.close()
        if os.path.exists(sock_path):
            os.remove(sock_path)


def connect_frontend(
    sock_path: str,
) -> Tuple["RepoFrontend", Callable[[], None]]:
    """A RepoFrontend wired to a remote backend. Returns (frontend,
    close)."""
    from ..frontend.repo_frontend import RepoFrontend

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    duplex = TcpDuplex(sock, is_client=True)
    if duplex.closed:
        raise ConnectionError(
            f"handshake with backend at {sock_path} failed"
        )
    front = RepoFrontend()
    front.subscribe(duplex.send)
    duplex.on_message(front.receive)
    return front, duplex.close


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hypermerge_tpu.net.ipc",
        description="Host a RepoBackend daemon behind a unix socket.",
    )
    ap.add_argument("repo_path", help="repo directory, or :memory:")
    ap.add_argument("sock_path", help="unix socket for the frontend")
    ap.add_argument(
        "--listen", action="store_true",
        help="join the peer swarm: listen on TCP (address printed)",
    )
    ap.add_argument(
        "--connect", action="append", default=[], metavar="HOST:PORT",
        help="join the peer swarm: dial another backend (repeatable)",
    )
    ap.add_argument(
        "--dht", action="store_true",
        help="join the peer swarm fleet-style via the DHT "
        "(net/discovery/): announce/lookup by doc id, no explicit "
        "addresses; bootstrap from --dht-bootstrap or "
        "HM_DHT_BOOTSTRAP",
    )
    ap.add_argument(
        "--dht-bootstrap", action="append", default=[],
        metavar="HOST:PORT",
        help="DHT bootstrap node (repeatable; implies --dht)",
    )
    ap.add_argument(
        "--persist", action="store_true",
        help="keep serving after a frontend disconnects (ONE live "
        "backend is reused across frontend cycles: swarm port and "
        "replicated state persist)",
    )
    ap.add_argument(
        "--hub", action="store_true",
        help="serve MANY concurrent frontends against the one "
        "backend (per-connection reply routing, per-doc interest "
        "routing) — the many-writer daemon of bench config_writers",
    )
    args = ap.parse_args()
    serve_backend(
        args.sock_path,
        repo_path=None if args.repo_path == ":memory:" else args.repo_path,
        memory=args.repo_path == ":memory:",
        once=not args.persist,
        tcp_listen=args.listen,
        tcp_connect=args.connect,
        hub=args.hub,
        dht=args.dht,
        dht_bootstrap=args.dht_bootstrap,
    )


if __name__ == "__main__":
    main()
