"""Frontend/backend process split — the seam, realized across processes.

Parity: the reference's stated design goal is that RepoFrontend runs on
a UI thread/process while RepoBackend runs elsewhere, joined only by
JSON-serializable messages (reference README.md:160-184, one frontend
per backend). Every message in msgs.py is a plain dict, so the split is
a transport choice: this module pumps the two queues over a unix-domain
socket using the same framed duplex the TCP swarm uses.

Backend process:
    python -m hypermerge_tpu.net.ipc /path/to/repo /tmp/backend.sock

Frontend process:
    from hypermerge_tpu.net.ipc import connect_frontend
    front, close = connect_frontend("/tmp/backend.sock")
    url = front.create({"hello": "world"})
    ...
    close()

The XLA bulk path, storage, crypto, and networking all live with the
backend; the frontend process needs none of them loaded.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable, Optional, Tuple

from .tcp import TcpDuplex


def serve_backend(
    sock_path: str,
    repo_path: Optional[str] = None,
    memory: bool = False,
    once: bool = True,
) -> None:
    """Host a RepoBackend behind a unix socket. `once` serves a single
    frontend connection then returns (the reference pairs exactly one
    frontend per backend)."""
    from ..backend.repo_backend import RepoBackend

    if os.path.exists(sock_path):
        os.remove(sock_path)
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    server.listen(1)
    print(f"backend ready on {sock_path}", flush=True)
    while True:
        conn, _ = server.accept()
        duplex = TcpDuplex(conn, is_client=False)
        if duplex.closed:
            # failed handshake (probe, misconfigured client): this was
            # not the frontend — keep the serve slot open
            continue
        back = RepoBackend(path=repo_path, memory=memory)
        back.subscribe(duplex.send)
        duplex.on_message(back.receive)
        gone = threading.Event()
        duplex.on_close(gone.set)
        gone.wait()
        back.close()
        if once:
            server.close()
            os.remove(sock_path)
            return


def connect_frontend(
    sock_path: str,
) -> Tuple["RepoFrontend", Callable[[], None]]:
    """A RepoFrontend wired to a remote backend. Returns (frontend,
    close)."""
    from ..frontend.repo_frontend import RepoFrontend

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    duplex = TcpDuplex(sock, is_client=True)
    if duplex.closed:
        raise ConnectionError(
            f"handshake with backend at {sock_path} failed"
        )
    front = RepoFrontend()
    front.subscribe(duplex.send)
    duplex.on_message(front.receive)
    return front, duplex.close


def main() -> None:
    import sys

    if len(sys.argv) < 3:
        print(
            "usage: python -m hypermerge_tpu.net.ipc "
            "(<repo-path>|:memory:) <socket-path>",
            file=sys.stderr,
        )
        raise SystemExit(2)
    repo_path, sock_path = sys.argv[1], sys.argv[2]
    if repo_path == ":memory:":
        serve_backend(sock_path, memory=True)
    else:
        serve_backend(sock_path, repo_path=repo_path)


if __name__ == "__main__":
    main()
