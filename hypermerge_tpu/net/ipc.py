"""Frontend/backend process split — the seam, realized across processes.

Parity: the reference's stated design goal is that RepoFrontend runs on
a UI thread/process while RepoBackend runs elsewhere, joined only by
JSON-serializable messages (reference README.md:160-184, one frontend
per backend). Every message in msgs.py is a plain dict, so the split is
a transport choice: this module pumps the two queues over a unix-domain
socket using the same framed duplex the TCP swarm uses.

Backend process:
    python -m hypermerge_tpu.net.ipc /path/to/repo /tmp/backend.sock

Frontend process:
    from hypermerge_tpu.net.ipc import connect_frontend
    front, close = connect_frontend("/tmp/backend.sock")
    url = front.create({"hello": "world"})
    ...
    close()

The XLA bulk path, storage, crypto, and networking all live with the
backend; the frontend process needs none of them loaded.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable, Optional, Tuple

from .tcp import TcpDuplex


def serve_backend(
    sock_path: str,
    repo_path: Optional[str] = None,
    memory: bool = False,
    once: bool = True,
    tcp_listen: bool = False,
    tcp_connect: Optional[list] = None,
) -> None:
    """Host a RepoBackend behind a unix socket. `once` serves a single
    frontend connection then returns (the reference pairs exactly one
    frontend per backend). With `tcp_listen`/`tcp_connect` the backend
    process also joins the peer swarm over TCP (the daemon owns the
    networking; the frontend process needs none of it loaded)."""
    from ..backend.repo_backend import RepoBackend

    if os.path.exists(sock_path):
        os.remove(sock_path)
    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    server.bind(sock_path)
    server.listen(1)
    print(f"backend ready on {sock_path}", flush=True)

    def build_backend() -> "RepoBackend":
        # the daemon's repo + swarm come up BEFORE a frontend attaches:
        # it replicates with peers on its own; the frontend is a client
        back = RepoBackend(path=repo_path, memory=memory)
        if tcp_listen or tcp_connect:
            from .tcp import TcpSwarm

            swarm = TcpSwarm()
            back.set_swarm(swarm)
            host, port = swarm.address
            print(f"swarm listening on {host}:{port}", flush=True)
            for addr in tcp_connect or []:
                h, _, p = addr.rpartition(":")
                swarm.connect((h, int(p)))
        return back

    back = build_backend()
    idle_sink = False  # a discard sink is attached between frontends
    try:
        while True:
            conn, _ = server.accept()
            duplex = TcpDuplex(conn, is_client=False)
            if duplex.closed:
                # failed handshake (probe, health check, misconfigured
                # client): this was not the frontend — the LIVE backend,
                # its swarm, and its replicated state stay untouched
                continue
            if idle_sink:
                # swap the discard sink for the real frontend; drop the
                # handful of messages a push could buffer in the swap
                # window (a PREVIOUS frontend's replies/patches must
                # never reach this one — its queryId counter restarts)
                back.to_frontend.unsubscribe()
                back.to_frontend.drain()
                idle_sink = False
            back.subscribe(duplex.send)
            duplex.on_message(back.receive)
            gone = threading.Event()
            duplex.on_close(gone.set)
            gone.wait()
            if once:
                return
            # non-once: REUSE the live backend for the next frontend —
            # closing + rebuilding per cycle would rebind the advertised
            # swarm port (stranding --connect peers), drop a :memory:
            # repo's replicated state, and spin up a fresh set of
            # debouncer threads/device caches every cycle. While no
            # frontend is attached, a DISCARD sink consumes pushes
            # (swarm-replicated patches, gossip) so the queue cannot
            # grow without bound on an idle daemon; the next frontend
            # opens its docs fresh and gets its own Ready/patch stream.
            back.to_frontend.unsubscribe()
            back.to_frontend.drain()
            back.subscribe(lambda _msg: None)
            idle_sink = True
    finally:
        back.close()
        server.close()
        if os.path.exists(sock_path):
            os.remove(sock_path)


def connect_frontend(
    sock_path: str,
) -> Tuple["RepoFrontend", Callable[[], None]]:
    """A RepoFrontend wired to a remote backend. Returns (frontend,
    close)."""
    from ..frontend.repo_frontend import RepoFrontend

    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.connect(sock_path)
    duplex = TcpDuplex(sock, is_client=True)
    if duplex.closed:
        raise ConnectionError(
            f"handshake with backend at {sock_path} failed"
        )
    front = RepoFrontend()
    front.subscribe(duplex.send)
    duplex.on_message(front.receive)
    return front, duplex.close


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m hypermerge_tpu.net.ipc",
        description="Host a RepoBackend daemon behind a unix socket.",
    )
    ap.add_argument("repo_path", help="repo directory, or :memory:")
    ap.add_argument("sock_path", help="unix socket for the frontend")
    ap.add_argument(
        "--listen", action="store_true",
        help="join the peer swarm: listen on TCP (address printed)",
    )
    ap.add_argument(
        "--connect", action="append", default=[], metavar="HOST:PORT",
        help="join the peer swarm: dial another backend (repeatable)",
    )
    ap.add_argument(
        "--persist", action="store_true",
        help="keep serving after a frontend disconnects (ONE live "
        "backend is reused across frontend cycles: swarm port and "
        "replicated state persist)",
    )
    args = ap.parse_args()
    serve_backend(
        args.sock_path,
        repo_path=None if args.repo_path == ":memory:" else args.repo_path,
        memory=args.repo_path == ":memory:",
        once=not args.persist,
        tcp_listen=args.listen,
        tcp_connect=args.connect,
    )


if __name__ == "__main__":
    main()
