"""Process-wide metrics registry: counters, gauges, histograms.

One registry instrument per process (the module-level ``REGISTRY``),
shared by every subsystem — live engine ticks, pipeline stage busy,
mesh dispatches, replication frames, fsync barriers all land in the
same namespace, so one snapshot answers "what is this daemon doing"
without scraping N private stats dicts (the pre-round-13 story).

Design constraints, in order:

- Hot-path writes must be lock-cheap. ``Counter.add`` bumps a cell
  owned by the CALLING thread (a dict lookup plus one attribute ``+=``
  on an object no other thread writes — safe under the GIL); only the
  first add from a new thread takes a lock, to install the shard.
  Reads merge the shards. Concurrent adds are therefore EXACT, which
  is also the fix for the unlocked read-modify-write races the old
  ad-hoc stats dicts carried (``stats["t_resync_ms"] +=`` from reader
  threads).
- Series are keyed (name, labels). Components that need per-instance
  exactness (two repos in one process must not blur each other's
  ``adopted`` count) label their series with an instance tag
  (``next_instance``) and keep handles; process-level views aggregate
  across labels by name (``snapshot``).
- Values are plain Python numbers: ints for event counts, float
  seconds/bytes for accumulators. ``snapshot`` preserves int-ness so
  JSON output stays bit-compatible with the dicts it replaced.
"""

from __future__ import annotations

import itertools
import threading
from bisect import bisect_left
from threading import get_ident
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..analysis import hierarchy, lockdep
from ..analysis.lockdep import make_lock

LabelsT = Tuple[Tuple[str, str], ...]

# the dotted `subsystem.metric` convention (telemetry/__init__.py):
# tools/top.py groups per-subsystem rates by the prefix, so a flat or
# oddly-cased name silently falls out of every view. Checked statically
# by the `telemetry-name` lint rule where the name is a literal, and
# here at creation time when runtime lockdep is on (HM_LOCKDEP=1) for
# the dynamically-built names the linter cannot see. The pattern is
# shared with the linter (analysis/hierarchy.py) so the two halves of
# the rule cannot drift.
_NAME_RE = hierarchy.TELEMETRY_NAME_RE


def _check_name(name: str) -> None:
    if lockdep.enabled() and not _NAME_RE.match(name):
        raise ValueError(
            f"telemetry series {name!r} breaks the dotted "
            f"`subsystem.metric` naming convention"
        )


class _Cell:
    __slots__ = ("v",)

    def __init__(self) -> None:
        self.v = 0.0


class Counter:
    """Monotone accumulator (event counts, seconds, bytes).

    ``add`` is exact under concurrency without a hot-path lock: each
    thread owns one shard cell (thread idents are reused after a thread
    dies, which only re-targets the dead thread's cell — cumulative
    totals stay exact)."""

    __slots__ = ("name", "labels", "_shards", "_lock")
    kind = "counter"

    def __init__(self, name: str, labels: LabelsT = ()) -> None:
        self.name = name
        self.labels = labels
        self._shards: Dict[int, _Cell] = {}
        self._lock = make_lock("telemetry.shard")

    def add(self, v: float = 1) -> None:
        ident = get_ident()
        cell = self._shards.get(ident)
        if cell is None:
            with self._lock:
                cell = self._shards.setdefault(ident, _Cell())
        cell.v += v

    inc = add

    def value(self) -> float:
        # list() snapshots against a concurrent shard install; the 0.0
        # start keeps untouched counters FLOAT (the migrated stats
        # dicts' time keys were 0.0, and bench JSON must stay
        # bit-compatible)
        return sum((c.v for c in list(self._shards.values())), 0.0)


class Gauge:
    """Last-value instrument (queue depth, resident bytes). ``set`` is
    one attribute assignment (atomic under the GIL); ``add`` takes the
    lock — use counters for high-rate accumulation."""

    __slots__ = ("name", "labels", "_v", "_lock")
    kind = "gauge"

    def __init__(self, name: str, labels: LabelsT = ()) -> None:
        self.name = name
        self.labels = labels
        self._v: float = 0
        self._lock = make_lock("telemetry.shard")

    def set(self, v: float) -> None:
        self._v = v

    def add(self, v: float = 1) -> None:
        with self._lock:
            self._v += v

    def value(self) -> float:
        return self._v


class _HistCell:
    __slots__ = ("counts", "sum", "n")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.n = 0


# seconds: 100µs .. ~100s, the spread of every stage this repo times
DEFAULT_TIME_BUCKETS_S = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
    10.0, 60.0,
)


class Histogram:
    """Fixed-bucket histogram (upper bounds + implicit +Inf), sharded
    per thread like Counter so concurrent observes stay exact."""

    __slots__ = ("name", "labels", "buckets", "_shards", "_lock")
    kind = "histogram"

    def __init__(
        self,
        name: str,
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS_S,
        labels: LabelsT = (),
    ) -> None:
        self.name = name
        self.labels = labels
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        self._shards: Dict[int, _HistCell] = {}
        self._lock = make_lock("telemetry.shard")

    def observe(self, v: float) -> None:
        ident = get_ident()
        cell = self._shards.get(ident)
        if cell is None:
            with self._lock:
                cell = self._shards.setdefault(
                    ident, _HistCell(len(self.buckets) + 1)
                )
        cell.counts[bisect_left(self.buckets, v)] += 1
        cell.sum += v
        cell.n += 1

    def value(self) -> Dict[str, Any]:
        """Merged view: per-bucket counts (not cumulative), sum, count."""
        counts = [0] * (len(self.buckets) + 1)
        total = 0.0
        n = 0
        for cell in list(self._shards.values()):
            for i, c in enumerate(cell.counts):
                counts[i] += c
            total += cell.sum
            n += cell.n
        return {"buckets": counts, "sum": total, "count": n}


def _labels_key(labels: Dict[str, Any]) -> LabelsT:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """The process-wide series table. ``counter``/``gauge``/``histogram``
    get-or-create, so callers may either cache handles (hot paths do)
    or re-resolve by name (tools do)."""

    def __init__(self) -> None:
        self._lock = make_lock("telemetry.table")
        self._series: Dict[Tuple[str, str, LabelsT], Any] = {}

    # -- get-or-create -------------------------------------------------

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get("gauge", Gauge, name, labels)

    def histogram(
        self,
        name: str,
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS_S,
        **labels: Any,
    ) -> Histogram:
        _check_name(name)
        key = ("histogram", name, _labels_key(labels))
        with self._lock:
            m = self._series.get(key)
            if m is None:
                m = self._series[key] = Histogram(
                    name, buckets, key[2]
                )
            return m

    def _get(self, kind: str, cls, name: str, labels: Dict) -> Any:
        _check_name(name)
        key = (kind, name, _labels_key(labels))
        with self._lock:
            m = self._series.get(key)
            if m is None:
                m = self._series[key] = cls(name, key[2])
            return m

    # -- read side -----------------------------------------------------

    def series(self) -> List[Any]:
        with self._lock:
            return list(self._series.values())

    def snapshot(self) -> Dict[str, Any]:
        """name -> merged value, aggregated ACROSS label sets (the
        process-level view: two repos' ``live.ticks`` sum). Counters
        and gauges sum; histograms surface as ``<name>.count`` and
        ``<name>.sum``. Integral values stay ints so embedding the
        snapshot in a JSON line round-trips bit-identically."""
        out: Dict[str, Any] = {}
        for m in self.series():
            if m.kind == "histogram":
                v = m.value()
                out[m.name + ".count"] = (
                    out.get(m.name + ".count", 0) + v["count"]
                )
                out[m.name + ".sum"] = round(
                    out.get(m.name + ".sum", 0.0) + v["sum"], 6
                )
            else:
                out[m.name] = _num(out.get(m.name, 0) + m.value())
        return dict(sorted(out.items()))

    def retire(self, *metrics: Any) -> None:
        """Fold a CLOSED component's labeled series into an
        ``inst="closed"`` aggregate and drop them from the table.
        Components that open and close freely (one engine per repo, one
        replication manager per network) call this from their close
        path so a long-lived process does not grow the registry by a
        label set per lifecycle — while ``snapshot()`` keeps the
        process totals. The component's cached handles stay readable
        (its ``stats`` view is handle-based), they just stop being
        listed."""
        closed = (("inst", "closed"),)
        with self._lock:
            for m in metrics:
                key = (m.kind, m.name, m.labels)
                if self._series.get(key) is not m:
                    continue  # reset/replaced already
                del self._series[key]
                if m.kind != "counter":
                    continue  # a dead gauge's last value is noise
                v = m.value()
                if not v:
                    continue
                akey = ("counter", m.name, closed)
                agg = self._series.get(akey)
                if agg is None:
                    agg = self._series[akey] = Counter(m.name, closed)
                agg.add(v)

    def reset(self) -> None:
        """Zero every series IN PLACE (tests/embedding apps isolating
        runs). The table keeps its entries, so module-level cached
        handles (net.tcp.*, pipeline.*, storage.* are created once at
        import) stay live and visible afterwards — dropping them would
        blind those subsystems for the process lifetime. An add racing
        the reset on another thread may be lost; this is a measurement
        hook, not a synchronization point."""
        with self._lock:
            for m in self._series.values():
                if m.kind == "gauge":
                    m.set(0)
                else:
                    m._shards.clear()


def _num(v: float) -> Any:
    """ints stay ints; floats round to 6 (stable JSON)."""
    if isinstance(v, float):
        if v.is_integer():
            return int(v)
        return round(v, 6)
    return v


REGISTRY = MetricsRegistry()

_instances = itertools.count(1)


def next_instance() -> int:
    """A process-unique instance tag for per-component label sets
    (two RepoBackends in one process must not blur each other's
    per-engine stats)."""
    return next(_instances)
