"""Bounded structured-trace ring buffer of spans.

Spans are begin/end windows with tags, recorded into a fixed-capacity
ring (HM_TRACE_RING events, default 65536) — a long-running daemon
keeps the LAST N events, never unbounded memory. Export renders
Chrome trace-event JSON (load the file in Perfetto / chrome://tracing)
via telemetry.export.

Off by default and cheap when off: ``span()`` checks one module flag
and returns a shared no-op singleton — no object allocation, no
timestamp read. Enable with:

- ``HM_TRACE=<path>`` in the environment (read at import): tracing on
  for the process lifetime, the trace file written at exit (atexit)
  and on explicit ``flush()``.
- ``enable(path=None)`` at runtime (tests, tools). ``path=None`` keeps
  the ring in memory only (``events()`` reads it).

Recording is lock-free on the hot path: a global monotone sequence
(itertools.count — atomic in CPython) claims a slot, and the slot
assignment is a single list-item store. Wraparound overwrites the
oldest slot; ``events()`` reorders by sequence.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# event tuples: (seq implicit via slot, ph, name, cat, ts_us, dur_us,
# tid, args) — converted to Chrome dicts at export time (export.py)
EventT = Tuple[str, str, str, float, float, int, Optional[Dict]]


def _ring_capacity() -> int:
    try:
        return max(16, int(os.environ.get("HM_TRACE_RING", "65536")))
    except ValueError:
        return 65536


class _Ring:
    __slots__ = ("cap", "_buf", "_seq")

    def __init__(self, cap: int) -> None:
        self.cap = cap
        self._buf: List[Optional[Tuple[int, EventT]]] = [None] * cap
        self._seq = itertools.count()

    def add(self, ev: EventT) -> None:
        i = next(self._seq)  # atomic claim
        self._buf[i % self.cap] = (i, ev)

    def events(self) -> List[EventT]:
        got = [s for s in list(self._buf) if s is not None]
        got.sort(key=lambda s: s[0])
        return [ev for _i, ev in got]

    def __len__(self) -> int:
        return sum(1 for s in self._buf if s is not None)


class _Tracer:
    def __init__(self) -> None:
        self.on = False
        self.path: Optional[str] = None
        self.ring = _Ring(_ring_capacity())
        self.t0 = time.perf_counter()
        self.tid_names: Dict[int, str] = {}
        self._tid_seen = threading.local()
        self._atexit = False


_T = _Tracer()


def enabled() -> bool:
    return _T.on


def enable(path: Optional[str] = None, capacity: Optional[int] = None):
    """Turn tracing on (idempotent). ``path`` is where ``flush()`` and
    the atexit hook write the Chrome trace; None keeps the ring
    memory-only."""
    if capacity is not None:
        _T.ring = _Ring(max(16, capacity))
    if path:
        _T.path = path
        if not _T._atexit:
            import atexit

            atexit.register(_atexit_flush)
            _T._atexit = True
    _T.on = True


def disable() -> None:
    _T.on = False


def reset() -> None:
    """Drop recorded events (tests); keeps the enabled flag/path."""
    _T.ring = _Ring(_T.ring.cap)
    _T.tid_names.clear()
    # threads must RE-register their names (the per-thread seen flag
    # would otherwise leave post-reset exports without thread labels)
    _T._tid_seen = threading.local()


def _note_thread() -> int:
    tid = threading.get_ident()
    seen = getattr(_T._tid_seen, "done", False)
    if not seen:
        _T.tid_names[tid] = threading.current_thread().name
        _T._tid_seen.done = True
    return tid


class SpanHandle:
    """An open span: ``end()`` records it. Use via ``span()`` as a
    context manager, or ``begin()``/``end()`` across seams where the
    window opens and closes on different code paths."""

    __slots__ = ("name", "cat", "t0", "args")

    def __init__(self, name: str, cat: str, args: Optional[Dict]):
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = time.perf_counter()

    def end(self, **more: Any) -> None:
        if not _T.on:
            return
        t1 = time.perf_counter()
        args = self.args
        if more:
            args = {**(args or {}), **more}
        _T.ring.add((
            "X",
            self.name,
            self.cat,
            (self.t0 - _T.t0) * 1e6,
            (t1 - self.t0) * 1e6,
            _note_thread(),
            args,
        ))

    # context-manager protocol (what span() hands out when enabled)
    def __enter__(self) -> "SpanHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NoopSpan:
    """The shared disabled span: no allocation, no clock read."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def end(self, **more: Any) -> None:
        pass


NOOP = _NoopSpan()


def span(name: str, cat: str = "", **args: Any):
    """A context manager timing one section into the ring. Disabled
    tracing returns the shared no-op singleton."""
    if not _T.on:
        return NOOP
    return SpanHandle(name, cat, args or None)


def begin(name: str, cat: str = "", **args: Any):
    """Open a span to be closed by ``handle.end()`` later (possibly on
    another code path). Disabled tracing returns the no-op handle."""
    if not _T.on:
        return NOOP
    return SpanHandle(name, cat, args or None)


def instant(name: str, cat: str = "", **args: Any) -> None:
    """A point event (demotions, resync closures, faults)."""
    if not _T.on:
        return
    _T.ring.add((
        "i",
        name,
        cat,
        (time.perf_counter() - _T.t0) * 1e6,
        0.0,
        _note_thread(),
        args or None,
    ))


def events() -> List[EventT]:
    """Recorded events, oldest first (ring order)."""
    return _T.ring.events()


def event_count() -> int:
    return len(_T.ring)


def trace_path() -> Optional[str]:
    return _T.path


def flush(path: Optional[str] = None) -> Optional[str]:
    """Write the ring as Chrome trace JSON to ``path`` (default: the
    enable()/HM_TRACE path). Returns the path written, or None when
    there is nowhere to write."""
    out = path or _T.path
    if out is None:
        return None
    from .export import write_chrome_trace

    write_chrome_trace(out, events(), dict(_T.tid_names))
    return out


def _atexit_flush() -> None:
    try:
        flush()
    except Exception:
        pass  # never fail interpreter shutdown over a trace file


def _maybe_enable_from_env() -> None:
    v = os.environ.get("HM_TRACE", "")
    if v and v != "0":
        # HM_TRACE=<path>: run-long trace file. A bare "1" enables the
        # in-memory ring without a file.
        enable(None if v == "1" else v)


_maybe_enable_from_env()
