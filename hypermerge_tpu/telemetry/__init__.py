"""Unified telemetry: one metrics registry + span tracing per process.

This package is the repo's single observability surface (ROADMAP
round 13). Every subsystem registers into the same two instruments:

- a process-wide **metrics registry** (``registry.REGISTRY``):
  counters, gauges, fixed-bucket histograms — lock-cheap via
  per-thread shards, merged on read, exportable as a Prometheus text
  snapshot (``prometheus_text``) or a plain dict (``snapshot``);
- a bounded **span ring** (``trace``): begin/end spans with tags,
  off by default (``span()`` is a no-op singleton), activated by
  ``HM_TRACE=<path>`` (Chrome trace JSON written at exit, loadable in
  Perfetto) or ``enable_tracing()``.

Naming convention: ``<subsystem>.<metric>`` with subsystems
``live`` (apply engine), ``pipeline`` (bulk cold open), ``mesh``
(multi-chip programs), ``net`` (tcp/replication/resilience),
``storage`` (durability/scrub), ``repo``. Snapshot keys group by the
prefix — tools/top.py renders per-subsystem rates from exactly this.

Consumers:
- components cache handles: ``C = telemetry.counter("net.tcp.frames_tx")``
- tools read ``telemetry.snapshot()`` / ``prometheus_text()``
- the backend answers a ``{"type": "Telemetry"}`` query over the
  IPC/serve seam with ``query_payload()`` (tools/top.py's feed)
- bench.py embeds ``snapshot()`` as the JSON line's ``telemetry`` block
"""

from __future__ import annotations

import time
from typing import Any, Dict

from .export import chrome_trace_events, prometheus_text, write_chrome_trace
from .registry import (
    DEFAULT_TIME_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    next_instance,
)
from .trace import (
    NOOP,
    SpanHandle,
    begin,
    disable as disable_tracing,
    enable as enable_tracing,
    enabled as tracing_enabled,
    event_count,
    events as trace_events,
    flush as flush_trace,
    instant,
    reset as reset_trace,
    span,
    trace_path,
)

# module-level conveniences bound to the process registry
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
snapshot = REGISTRY.snapshot


def snapshot_repo(repo_path: str) -> Dict[str, Any]:
    """Open the repo at ``repo_path`` in-process, prime every doc
    (bulk open + summary barrier), and return ``query_payload()`` —
    the ONE recipe behind ``tools/meta.py --stats`` and
    ``tools/top.py``'s repo mode. The numbers describe THIS process'
    open, not a running daemon (attach to a daemon's socket for
    that). Lazy imports: the telemetry package itself must stay
    dependency-free."""
    from ..repo import Repo
    from ..utils.ids import to_doc_url

    repo = Repo(path=repo_path)
    try:
        doc_ids = repo.back.clocks.all_doc_ids(repo.back.id)
        if doc_ids:
            repo.open_many([to_doc_url(d) for d in doc_ids])
            repo.back.fetch_bulk_summaries()
        return query_payload()
    finally:
        repo.close()


def query_payload() -> Dict[str, Any]:
    """The ``{"type": "Telemetry"}`` IPC query's reply: the merged
    counter snapshot plus trace state, stamped with a monotonic time
    so pollers (tools/top.py) compute exact rates between polls."""
    return {
        "time": time.monotonic(),
        "counters": snapshot(),
        "tracing": tracing_enabled(),
        "trace_spans": event_count(),
        "trace_path": trace_path(),
    }


__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_TIME_BUCKETS_S", "counter", "gauge", "histogram",
    "snapshot", "next_instance", "prometheus_text",
    "chrome_trace_events", "write_chrome_trace", "span", "begin",
    "instant", "NOOP", "SpanHandle", "enable_tracing",
    "disable_tracing", "tracing_enabled", "trace_events",
    "event_count", "flush_trace", "reset_trace", "trace_path",
    "query_payload", "snapshot_repo",
]
