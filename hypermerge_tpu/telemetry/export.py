"""Exporters: Chrome trace-event JSON and Prometheus text format.

- ``chrome_trace_events`` / ``write_chrome_trace`` render the span
  ring as the Trace Event Format ("X" complete events + "i" instants,
  plus thread-name metadata), the JSON Perfetto and chrome://tracing
  load directly.
- ``prometheus_text`` renders the metrics registry as the Prometheus
  exposition format (one ``# TYPE`` header per family, label sets
  preserved, histograms as cumulative ``_bucket{le=...}`` +
  ``_sum``/``_count``). Metric names sanitize to the Prometheus
  charset with an ``hm_`` prefix: ``live.ticks`` -> ``hm_live_ticks``.

Both formats are pinned by golden tests (tests/test_telemetry.py).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional

from .registry import REGISTRY, MetricsRegistry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "hm_" + _NAME_RE.sub("_", name)


def _prom_labels(labels, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry as Prometheus exposition text (a snapshot, not a
    server — tools/top.py --prom and operators' curl-into-a-file)."""
    reg = registry if registry is not None else REGISTRY
    by_family: Dict[str, List[Any]] = {}
    kinds: Dict[str, str] = {}
    for m in reg.series():
        by_family.setdefault(m.name, []).append(m)
        kinds[m.name] = m.kind
    lines: List[str] = []
    for name in sorted(by_family):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} {kinds[name]}")
        for m in sorted(by_family[name], key=lambda s: s.labels):
            if m.kind == "histogram":
                v = m.value()
                acc = 0
                for ub, c in zip(m.buckets, v["buckets"]):
                    acc += c
                    le = 'le="' + _fmt(float(ub)) + '"'
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(m.labels, le)} {acc}"
                    )
                acc += v["buckets"][-1]
                inf = 'le="+Inf"'
                lines.append(
                    f"{pname}_bucket"
                    f"{_prom_labels(m.labels, inf)} {acc}"
                )
                lines.append(
                    f"{pname}_sum{_prom_labels(m.labels)} "
                    f"{_fmt(round(v['sum'], 6))}"
                )
                lines.append(
                    f"{pname}_count{_prom_labels(m.labels)} {v['count']}"
                )
            else:
                lines.append(
                    f"{pname}{_prom_labels(m.labels)} "
                    f"{_fmt(float(m.value()))}"
                )
    return "\n".join(lines) + "\n"


def chrome_trace_events(
    events, tid_names: Optional[Dict[int, str]] = None
) -> List[Dict[str, Any]]:
    """Span-ring tuples -> Trace Event Format dicts. Thread idents map
    to small stable tids (Perfetto's track list stays readable) with
    thread_name metadata rows."""
    pid = os.getpid()
    tid_map: Dict[int, int] = {}
    out: List[Dict[str, Any]] = []
    for ph, name, cat, ts, dur, tid, args in events:
        small = tid_map.setdefault(tid, len(tid_map) + 1)
        ev: Dict[str, Any] = {
            "ph": ph,
            "name": name,
            "cat": cat or "hm",
            "ts": round(ts, 3),
            "pid": pid,
            "tid": small,
        }
        if ph == "X":
            ev["dur"] = round(dur, 3)
        elif ph == "i":
            ev["s"] = "t"  # thread-scoped instant
        if args:
            ev["args"] = args
        out.append(ev)
    meta: List[Dict[str, Any]] = [{
        "ph": "M",
        "name": "process_name",
        "pid": pid,
        "tid": 0,
        "args": {"name": "hypermerge-tpu"},
    }]
    names = tid_names or {}
    for raw, small in sorted(tid_map.items(), key=lambda kv: kv[1]):
        meta.append({
            "ph": "M",
            "name": "thread_name",
            "pid": pid,
            "tid": small,
            "args": {"name": names.get(raw, f"thread-{raw}")},
        })
    return meta + out


def write_chrome_trace(
    path: str, events, tid_names: Optional[Dict[int, str]] = None
) -> str:
    """Write ``{"traceEvents": [...]}`` to ``path`` atomically (the
    atexit writer must never leave a torn file a later Perfetto load
    chokes on)."""
    payload = {
        "traceEvents": chrome_trace_events(events, tid_names),
        "displayTimeUnit": "ms",
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path
