// hm_native: C++ native layer for hypermerge_tpu.
//
// Provides the primitives the reference gets from native npm addons
// (SURVEY.md §2.4): ed25519 keypairs/signatures (sodium-native
// equivalent), BLAKE2b hashing (discovery keys, merkle nodes), and
// brotli block compression (iltorb equivalent), with a zlib fallback.
//
// The image ships runtime shared objects for libsodium and libbrotli but
// no headers, so the stable C ABIs are declared here and the libraries
// are dlopen'd at init; zlib has headers and is linked directly. Every
// entry point degrades gracefully: callers check hm_caps() and fall back
// to pure-Python implementations when a capability is absent.
//
// Build: make -C hypermerge_tpu/native  (produces libhm_native.so)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dlfcn.h>
#include <zlib.h>

// ---------------------------------------------------------------------
// dlopen'd ABIs

typedef int (*fn_sodium_init)(void);
typedef int (*fn_sign_seed_keypair)(unsigned char *, unsigned char *,
                                    const unsigned char *);
typedef int (*fn_sign_detached)(unsigned char *, unsigned long long *,
                                const unsigned char *, unsigned long long,
                                const unsigned char *);
typedef int (*fn_sign_verify_detached)(const unsigned char *,
                                       const unsigned char *,
                                       unsigned long long,
                                       const unsigned char *);
typedef int (*fn_generichash)(unsigned char *, size_t, const unsigned char *,
                              unsigned long long, const unsigned char *,
                              size_t);

typedef int (*fn_brotli_compress)(int, int, int, size_t, const uint8_t *,
                                  size_t *, uint8_t *);
typedef int (*fn_brotli_decompress)(size_t, const uint8_t *, size_t *,
                                    uint8_t *);
typedef size_t (*fn_brotli_bound)(size_t);

typedef int (*fn_scalarmult)(unsigned char *, const unsigned char *,
                             const unsigned char *);
typedef int (*fn_scalarmult_base)(unsigned char *, const unsigned char *);
typedef int (*fn_aead_encrypt)(unsigned char *, unsigned long long *,
                               const unsigned char *, unsigned long long,
                               const unsigned char *, unsigned long long,
                               const unsigned char *, const unsigned char *,
                               const unsigned char *);
typedef int (*fn_aead_decrypt)(unsigned char *, unsigned long long *,
                               unsigned char *, const unsigned char *,
                               unsigned long long, const unsigned char *,
                               unsigned long long, const unsigned char *,
                               const unsigned char *);

static fn_sign_seed_keypair p_seed_keypair = nullptr;
static fn_sign_detached p_sign = nullptr;
static fn_sign_verify_detached p_verify = nullptr;
static fn_generichash p_generichash = nullptr;
static fn_scalarmult p_scalarmult = nullptr;
static fn_scalarmult_base p_scalarmult_base = nullptr;
static fn_aead_encrypt p_aead_encrypt = nullptr;
static fn_aead_decrypt p_aead_decrypt = nullptr;
static fn_brotli_compress p_br_compress = nullptr;
static fn_brotli_decompress p_br_decompress = nullptr;
static fn_brotli_bound p_br_bound = nullptr;

static const int CAP_SODIUM = 1;
static const int CAP_BROTLI = 2;
static const int CAP_ZLIB = 4;
static int g_caps = -1;

extern "C" {

int hm_init(void) {
  if (g_caps >= 0)
    return g_caps;
  int caps = CAP_ZLIB; // linked directly

  void *sodium = dlopen("libsodium.so.23", RTLD_NOW | RTLD_GLOBAL);
  if (!sodium)
    sodium = dlopen("libsodium.so", RTLD_NOW | RTLD_GLOBAL);
  if (sodium) {
    fn_sodium_init init =
        (fn_sodium_init)dlsym(sodium, "sodium_init");
    p_seed_keypair =
        (fn_sign_seed_keypair)dlsym(sodium, "crypto_sign_seed_keypair");
    p_sign = (fn_sign_detached)dlsym(sodium, "crypto_sign_detached");
    p_verify = (fn_sign_verify_detached)dlsym(
        sodium, "crypto_sign_verify_detached");
    p_generichash = (fn_generichash)dlsym(sodium, "crypto_generichash");
    p_scalarmult = (fn_scalarmult)dlsym(sodium, "crypto_scalarmult");
    p_scalarmult_base =
        (fn_scalarmult_base)dlsym(sodium, "crypto_scalarmult_base");
    p_aead_encrypt = (fn_aead_encrypt)dlsym(
        sodium, "crypto_aead_chacha20poly1305_ietf_encrypt");
    p_aead_decrypt = (fn_aead_decrypt)dlsym(
        sodium, "crypto_aead_chacha20poly1305_ietf_decrypt");
    if (init && init() >= 0 && p_seed_keypair && p_sign && p_verify &&
        p_generichash && p_scalarmult && p_scalarmult_base &&
        p_aead_encrypt && p_aead_decrypt)
      caps |= CAP_SODIUM;
  }

  void *enc = dlopen("libbrotlienc.so.1", RTLD_NOW);
  if (!enc)
    enc = dlopen("libbrotlienc.so", RTLD_NOW);
  void *dec = dlopen("libbrotlidec.so.1", RTLD_NOW);
  if (!dec)
    dec = dlopen("libbrotlidec.so", RTLD_NOW);
  if (enc && dec) {
    p_br_compress = (fn_brotli_compress)dlsym(enc, "BrotliEncoderCompress");
    p_br_bound = (fn_brotli_bound)dlsym(enc, "BrotliEncoderMaxCompressedSize");
    p_br_decompress =
        (fn_brotli_decompress)dlsym(dec, "BrotliDecoderDecompress");
    if (p_br_compress && p_br_decompress && p_br_bound)
      caps |= CAP_BROTLI;
  }

  g_caps = caps;
  return caps;
}

int hm_caps(void) { return hm_init(); }

// -------------------------------------------------------------------
// ed25519 (requires CAP_SODIUM; returns -2 when unavailable)

int hm_ed25519_public(const uint8_t seed[32], uint8_t pub[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  uint8_t sk[64];
  return p_seed_keypair(pub, sk, seed) == 0 ? 0 : -1;
}

int hm_ed25519_sign(const uint8_t seed[32], const uint8_t *msg, size_t len,
                    uint8_t sig[64]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  uint8_t pk[32], sk[64];
  if (p_seed_keypair(pk, sk, seed) != 0)
    return -1;
  unsigned long long siglen = 64;
  return p_sign(sig, &siglen, msg, (unsigned long long)len, sk) == 0 ? 0 : -1;
}

int hm_ed25519_verify(const uint8_t pub[32], const uint8_t *msg, size_t len,
                      const uint8_t sig[64]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_verify(sig, msg, (unsigned long long)len, pub) == 0 ? 1 : 0;
}

// -------------------------------------------------------------------
// BLAKE2b (keyed) — discovery keys + merkle nodes

int hm_blake2b(const uint8_t *data, size_t len, const uint8_t *key,
               size_t keylen, uint8_t *out, size_t outlen) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_generichash(out, outlen, data, (unsigned long long)len, key,
                       keylen) == 0
             ? 0
             : -1;
}

// -------------------------------------------------------------------
// Merkle root over leaf hashes (32-byte nodes): parent =
// blake2b32(0x01 || left || right); an odd trailing node is promoted.
// Leaf hashing (0x00 || block) is done by the caller per block.

int hm_merkle_root(const uint8_t *leaves, size_t n, uint8_t out[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  if (n == 0) {
    memset(out, 0, 32);
    return 0;
  }
  // work buffer: copy of current level
  uint8_t *level = new uint8_t[n * 32];
  memcpy(level, leaves, n * 32);
  size_t count = n;
  uint8_t node[65];
  node[0] = 0x01;
  while (count > 1) {
    size_t next = 0;
    for (size_t i = 0; i + 1 < count; i += 2) {
      memcpy(node + 1, level + i * 32, 32);
      memcpy(node + 33, level + (i + 1) * 32, 32);
      if (p_generichash(level + next * 32, 32, node, 65, nullptr, 0) != 0) {
        delete[] level;
        return -1;
      }
      next++;
    }
    if (count % 2 == 1) { // odd node promoted
      memcpy(level + next * 32, level + (count - 1) * 32, 32);
      next++;
    }
    count = next;
  }
  memcpy(out, level, 32);
  delete[] level;
  return 0;
}

// -------------------------------------------------------------------
// X25519 + ChaCha20-Poly1305-IETF — the transport-encryption primitives
// (net/secure.py builds the kx handshake and per-direction nonce
// counters on top; reference: noise-peer wrapping every PeerConnection,
// src/PeerConnection.ts:36).

int hm_x25519_base(const uint8_t sk[32], uint8_t pk[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_scalarmult_base(pk, sk) == 0 ? 0 : -1;
}

int hm_x25519(const uint8_t sk[32], const uint8_t pk[32], uint8_t out[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_scalarmult(out, sk, pk) == 0 ? 0 : -1;
}

// out must hold len + 16 bytes; returns ciphertext length or <0
long hm_aead_encrypt(const uint8_t key[32], const uint8_t nonce[12],
                     const uint8_t *msg, size_t len, uint8_t *out) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  unsigned long long outlen = 0;
  if (p_aead_encrypt(out, &outlen, msg, (unsigned long long)len, nullptr, 0,
                     nullptr, nonce, key) != 0)
    return -1;
  return (long)outlen;
}

// out must hold len - 16 bytes; returns plaintext length, -1 on auth
// failure, -2 if unavailable
long hm_aead_decrypt(const uint8_t key[32], const uint8_t nonce[12],
                     const uint8_t *ct, size_t len, uint8_t *out) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  unsigned long long outlen = 0;
  if (p_aead_decrypt(out, &outlen, nullptr, ct, (unsigned long long)len,
                     nullptr, 0, nonce, key) != 0)
    return -1;
  return (long)outlen;
}

// -------------------------------------------------------------------
// Columnar pack: the host-serial hot loop of the bulk cold open
// (ops/columnar.py _try_pack_prefix_single). The Python twin builds a
// dozen [M] temporaries (concat, where, astype) and scatters them into
// padded [Dp, N] planes; this entry point fuses all of it into one pass
// per column that reads each feed's narrow source planes directly and
// writes the padded output planes in place — pad cells are written
// exactly once, real cells exactly once, no intermediates. The numpy
// path remains as the fallback twin and the two are fuzz-verified
// bit-identical (tests/test_native_pack.py).
//
// dtype codes match storage/colcache.py _V3_DTYPES:
//   0 = int8, 1 = int16, 2 = int32, 3 = uint8
//
// Source plane order (per feed, NP pointers):
//   0 action, 1 ctr, 2 seq, 3 obj_ctr, 4 obj_a, 5 key, 6 ref_ctr,
//   7 ref_a, 8 insert, 9 vkind, 10 value, 11 dt
//
// Output column order (ops/columnar.py COLUMNS):
//   0 action, 1 actor, 2 ctr, 3 seq, 4 obj, 5 key, 6 ref, 7 insert,
//   8 vkind, 9 value, 10 dt

static const int PACK_NP = 12;
static const int PACK_NOUT = 11;

// v3 checkpoint planes sit back-to-back behind 1-byte dtype tags, so a
// plane pointer is usually NOT aligned for its element type: all typed
// loads/stores go through memcpy (compiles to a plain mov on x86/ARM64,
// and is defined behavior everywhere — unlike a misaligned typed deref)
static inline long long pk_ld(const void *p, int dt, long long i) {
  switch (dt) {
  case 0:
    return ((const int8_t *)p)[i];
  case 1: {
    int16_t v;
    memcpy(&v, (const char *)p + i * 2, 2);
    return v;
  }
  case 2: {
    int32_t v;
    memcpy(&v, (const char *)p + i * 4, 4);
    return v;
  }
  default:
    return ((const uint8_t *)p)[i];
  }
}

static inline void pk_st(void *p, int dt, long long i, long long v) {
  switch (dt) {
  case 0:
    ((int8_t *)p)[i] = (int8_t)v;
    break;
  case 1: {
    int16_t w = (int16_t)v;
    memcpy((char *)p + i * 2, &w, 2);
    break;
  }
  case 2: {
    int32_t w = (int32_t)v;
    memcpy((char *)p + i * 4, &w, 4);
    break;
  }
  default:
    ((uint8_t *)p)[i] = (uint8_t)v;
    break;
  }
}

static inline void pk_fill(void *p, int dt, long long start, long long end,
                           long long v) {
  for (long long i = start; i < end; i++)
    pk_st(p, dt, i, v);
}

static inline int pk_itemsize(int dt) { return dt == 1 ? 2 : dt == 2 ? 4 : 1; }

// value kinds that remap through a side table (ops/columnar.py VK_*)
static const int PK_VK_FLOAT = 2;
static const int PK_VK_STR = 3;
static const int PK_VK_BIGINT = 5;

// LUT indices come from DISK (sidecar planes): clamp every gather into
// the table bounds, like the numpy twin's clipped key gather — a
// corrupt sidecar must at worst pack garbage that downstream validation
// rejects, never read out of process memory. On well-formed input the
// clamp is a no-op, so the twins stay bit-identical.
static inline long long pk_lut(const long long *lut, long long len,
                               long long i) {
  if (len <= 0)
    return 0; // empty table (callers pad to >=1, but never trust that)
  if (i < 0)
    i = 0;
  if (i >= len)
    i = len - 1;
  return lut[i];
}

// min/max of the remapped value column over all real rows, folded with 0
// (the numpy twin's .min(initial=0)/.max(initial=0)) — the caller picks
// the wire dtype from this BEFORE allocating outputs.
int hm_pack_value_minmax(
    long long D, const long long *fc_idx, const long long *ends,
    const long long *src_ptrs, const uint8_t *src_dt, const long long *slut,
    const long long *soffs, const long long *flut, const long long *foffs,
    const long long *blut, const long long *boffs,
    const long long *lut_lens /* [4]: klen, slen, flen, blen */,
    long long *out_minmax) {
  long long lo = 0, hi = 0;
  for (long long d = 0; d < D; d++) {
    long long f = fc_idx[d];
    long long n = ends[d];
    const void *vk = (const void *)src_ptrs[f * PACK_NP + 9];
    int vk_dt = src_dt[f * PACK_NP + 9];
    const void *val = (const void *)src_ptrs[f * PACK_NP + 10];
    int val_dt = src_dt[f * PACK_NP + 10];
    long long so = soffs[f], fo = foffs[f], bo = boffs[f];
    for (long long i = 0; i < n; i++) {
      long long k = pk_ld(vk, vk_dt, i);
      long long v = pk_ld(val, val_dt, i);
      if (k == PK_VK_STR)
        v = pk_lut(slut, lut_lens[1], so + v);
      else if (k == PK_VK_FLOAT)
        v = pk_lut(flut, lut_lens[2], fo + v);
      else if (k == PK_VK_BIGINT)
        v = pk_lut(blut, lut_lens[3], bo + v);
      if (v < lo)
        lo = v;
      if (v > hi)
        hi = v;
    }
  }
  out_minmax[0] = lo;
  out_minmax[1] = hi;
  return 0;
}

int hm_pack_prefix(
    long long D, long long Dp, long long N, const long long *fc_idx,
    const long long *ends, const long long *src_ptrs, const uint8_t *src_dt,
    const long long *klut, const long long *koffs, const long long *slut,
    const long long *soffs, const long long *flut, const long long *foffs,
    const long long *blut, const long long *boffs,
    const long long *lut_lens /* [4]: klen, slen, flen, blen */,
    const long long *writer_g, const long long *out_ptrs,
    const uint8_t *out_dt) {
  // defaults per output column (pad rows + pad docs)
  static const long long defaults[PACK_NOUT] = {7, 0, 0, 0, -1, -1,
                                                -3, 0, 0, 0, 0};
  // plain source -> output copies: {out column, source plane}
  static const int plain[][2] = {{0, 0},  {2, 1},  {3, 2}, {7, 8},
                                 {8, 9},  {10, 11}};
  for (long long d = 0; d < D; d++) {
    long long f = fc_idx[d];
    long long n = ends[d];
    if (n < 0 || n > N)
      return -1;
    long long base = d * N;
    const long long *sp = src_ptrs + f * PACK_NP;
    const uint8_t *sd = src_dt + f * PACK_NP;

    for (size_t c = 0; c < sizeof(plain) / sizeof(plain[0]); c++) {
      int oc = plain[c][0], sc = plain[c][1];
      void *out = (void *)out_ptrs[oc];
      if (out_dt[oc] == sd[sc]) {
        memcpy((char *)out + base * pk_itemsize(out_dt[oc]),
               (const char *)sp[sc], (size_t)(n * pk_itemsize(out_dt[oc])));
      } else {
        const void *src = (const void *)sp[sc];
        for (long long i = 0; i < n; i++)
          pk_st(out, out_dt[oc], base + i, pk_ld(src, sd[sc], i));
      }
      pk_fill(out, out_dt[oc], base + n, base + N, defaults[oc]);
    }

    { // actor: the feed writer's batch-global (string-sorted) id
      void *out = (void *)out_ptrs[1];
      pk_fill(out, out_dt[1], base, base + n, writer_g[f]);
      pk_fill(out, out_dt[1], base + n, base + N, defaults[1]);
    }
    { // obj: row index of the container's MAKE op (-1 = root map)
      void *out = (void *)out_ptrs[4];
      const void *oa = (const void *)sp[4];
      const void *oc_ = (const void *)sp[3];
      int oa_dt = sd[4], oc_dt = sd[3];
      for (long long i = 0; i < n; i++) {
        long long a = pk_ld(oa, oa_dt, i);
        pk_st(out, out_dt[4], base + i,
              a == 0 ? pk_ld(oc_, oc_dt, i) - 1 : -1);
      }
      pk_fill(out, out_dt[4], base + n, base + N, defaults[4]);
    }
    { // key: feed-local key idx -> batch-global (-1 = none)
      void *out = (void *)out_ptrs[5];
      const void *kl = (const void *)sp[5];
      int kl_dt = sd[5];
      long long ko = koffs[f];
      for (long long i = 0; i < n; i++) {
        long long k = pk_ld(kl, kl_dt, i);
        pk_st(out, out_dt[5], base + i,
              k >= 0 ? pk_lut(klut, lut_lens[0], ko + k) : -1);
      }
      pk_fill(out, out_dt[5], base + n, base + N, defaults[5]);
    }
    { // ref: dense ctr -> row (-2 HEAD, -3 none)
      void *out = (void *)out_ptrs[6];
      const void *ra = (const void *)sp[7];
      const void *rc = (const void *)sp[6];
      int ra_dt = sd[7], rc_dt = sd[6];
      for (long long i = 0; i < n; i++) {
        long long a = pk_ld(ra, ra_dt, i);
        pk_st(out, out_dt[6], base + i,
              a == 0 ? pk_ld(rc, rc_dt, i) - 1 : a == -2 ? -2 : -3);
      }
      pk_fill(out, out_dt[6], base + n, base + N, defaults[6]);
    }
    { // value: side-table kinds remap through the flat global LUTs
      void *out = (void *)out_ptrs[9];
      const void *vk = (const void *)sp[9];
      const void *val = (const void *)sp[10];
      int vk_dt = sd[9], val_dt = sd[10];
      long long so = soffs[f], fo = foffs[f], bo = boffs[f];
      for (long long i = 0; i < n; i++) {
        long long k = pk_ld(vk, vk_dt, i);
        long long v = pk_ld(val, val_dt, i);
        if (k == PK_VK_STR)
          v = pk_lut(slut, lut_lens[1], so + v);
        else if (k == PK_VK_FLOAT)
          v = pk_lut(flut, lut_lens[2], fo + v);
        else if (k == PK_VK_BIGINT)
          v = pk_lut(blut, lut_lens[3], bo + v);
        pk_st(out, out_dt[9], base + i, v);
      }
      pk_fill(out, out_dt[9], base + n, base + N, defaults[9]);
    }
  }
  // pad docs [D, Dp): every column all-default
  for (int oc = 0; oc < PACK_NOUT; oc++)
    pk_fill((void *)out_ptrs[oc], out_dt[oc], D * N, Dp * N, defaults[oc]);
  return 0;
}

// -------------------------------------------------------------------
// Block codec. codec: 1 = brotli, 2 = zlib. Returns compressed size,
// -1 on error, -2 if codec unavailable. Caller sizes `out` with
// hm_compress_bound.

size_t hm_compress_bound(size_t len) {
  size_t z = compressBound((uLong)len);
  if (hm_init() & CAP_BROTLI) {
    size_t b = p_br_bound(len);
    if (b > z)
      z = b;
  }
  return z;
}

long hm_compress(int codec, int quality, const uint8_t *in, size_t len,
                 uint8_t *out, size_t cap) {
  int caps = hm_init();
  if (codec == 1) {
    if (!(caps & CAP_BROTLI))
      return -2;
    size_t outlen = cap;
    // lgwin 22, mode 0 (generic) — quality per caller (reference iltorb
    // default quality is 11; block packing wants speed, callers pass ~5)
    if (p_br_compress(quality, 22, 0, len, in, &outlen, out) != 1)
      return -1;
    return (long)outlen;
  }
  if (codec == 2) {
    uLongf outlen = (uLongf)cap;
    if (compress2(out, &outlen, in, (uLong)len, quality) != Z_OK)
      return -1;
    return (long)outlen;
  }
  return -2;
}

long hm_decompress(int codec, const uint8_t *in, size_t len, uint8_t *out,
                   size_t cap) {
  int caps = hm_init();
  if (codec == 1) {
    if (!(caps & CAP_BROTLI))
      return -2;
    size_t outlen = cap;
    if (p_br_decompress(len, in, &outlen, out) != 1)
      return -1;
    return (long)outlen;
  }
  if (codec == 2) {
    uLongf outlen = (uLongf)cap;
    if (uncompress(out, &outlen, in, (uLong)len) != Z_OK)
      return -1;
    return (long)outlen;
  }
  return -2;
}

// -------------------------------------------------------------------
// Change-frame codec: canonical change JSON <-> compact binary frame
// (magic 0xC5 0x01). The contract that keeps the Python twin
// (crdt/codec.py) bit-identical without reimplementing Python's JSON
// string formatter here: string fields are stored as the JSON-ESCAPED
// inner bytes exactly as json.dumps produced them, and op values as
// their full canonical JSON token bytes — this code only SCANS tokens
// on encode and copies them back verbatim on decode, so the only
// bytes it ever formats itself are decimal integers and the fixed
// canonical key skeleton. Input to encode is always
// utils/json_buffer.bufferify output (sort_keys, compact separators);
// anything off-canon returns -1 and the caller falls back to the JSON
// block format. Both entry points touch only caller-owned buffers —
// no allocation, no Python objects — so ctypes calls run GIL-free
// (the hm_pack_prefix contract, pinned by codec_drops_gil()).
//
// Frame layout after the 2-byte magic (varint = unsigned LEB128,
// token = varint length + raw bytes) — fields appear in CANONICAL
// JSON KEY ORDER so encode is one forward pass over the input:
//   token actor;
//   varint n_deps; n_deps * (token key, varint seq);
//   token message;
//   varint n_ops; per op: varint action; uint8 flags
//     (1=key 2=ref 4=insert 8=value 16=datatype 32=pred);
//     token obj; [token key] [token ref] [token value-JSON]
//     [token datatype] [varint n_pred + n_pred * token];
//   varint seq, startOp, time.
//
// Return protocol (both entries): bytes required (written only when
// <= cap; caller retries with the returned size), or -1 on
// malformed/unsupported input.

static const uint8_t CH_MAGIC0 = 0xC5;
static const uint8_t CH_MAGIC1 = 0x01;
static const unsigned long long CH_IMAX =
    ((unsigned long long)1 << 63) - 1;

struct ChWr {
  uint8_t *buf;
  size_t cap;
  size_t pos;
};

static inline void ch_put(ChWr *w, uint8_t b) {
  if (w->pos < w->cap)
    w->buf[w->pos] = b;
  w->pos++;
}

static inline void ch_bytes(ChWr *w, const uint8_t *p, size_t n) {
  if (w->pos + n <= w->cap)
    memcpy(w->buf + w->pos, p, n);
  w->pos += n;
}

static inline void ch_str(ChWr *w, const char *s) {
  ch_bytes(w, (const uint8_t *)s, strlen(s));
}

static inline void ch_varint(ChWr *w, unsigned long long v) {
  do {
    uint8_t b = v & 0x7f;
    v >>= 7;
    ch_put(w, b | (v ? 0x80 : 0));
  } while (v);
}

static inline void ch_token(ChWr *w, const uint8_t *p, size_t n) {
  ch_varint(w, n);
  ch_bytes(w, p, n);
}

static inline void ch_decimal(ChWr *w, unsigned long long v) {
  char tmp[24];
  int n = snprintf(tmp, sizeof(tmp), "%llu", v);
  ch_bytes(w, (const uint8_t *)tmp, (size_t)n);
}

// --- encode side: strict scanner over canonical JSON ----------------

struct ChRd {
  const uint8_t *buf;
  size_t len;
  size_t pos;
};

static inline bool ch_lit(ChRd *r, const char *s) {
  size_t n = strlen(s);
  if (r->pos + n > r->len || memcmp(r->buf + r->pos, s, n) != 0)
    return false;
  r->pos += n;
  return true;
}

static inline uint8_t ch_peek(ChRd *r) {
  return r->pos < r->len ? r->buf[r->pos] : 0;
}

// nonnegative decimal integer < 2^63 (canonical json never emits
// leading zeros / signs for the fields this parses)
static bool ch_int(ChRd *r, unsigned long long *out) {
  size_t start = r->pos;
  unsigned long long v = 0;
  while (r->pos < r->len) {
    uint8_t c = r->buf[r->pos];
    if (c < '0' || c > '9')
      break;
    if (v > CH_IMAX / 10)
      return false;
    v = v * 10 + (c - '0');
    if (v > CH_IMAX)
      return false;
    r->pos++;
  }
  if (r->pos == start)
    return false;
  *out = v;
  return true;
}

// JSON string: cursor on the opening quote; yields the inner
// (still-escaped) span
static bool ch_jstr(ChRd *r, size_t *tok, size_t *tok_len) {
  if (ch_peek(r) != '"')
    return false;
  r->pos++;
  size_t start = r->pos;
  while (r->pos < r->len) {
    uint8_t c = r->buf[r->pos];
    if (c == '\\') {
      r->pos += 2;
      continue;
    }
    if (c == '"') {
      *tok = start;
      *tok_len = r->pos - start;
      r->pos++;
      return true;
    }
    r->pos++;
  }
  return false;
}

// one JSON value of any shape (the op "v" payload): raw token span
// ending at the first depth-0 delimiter (',' '}' ']') past the start
static bool ch_jvalue(ChRd *r, size_t *tok, size_t *tok_len) {
  size_t start = r->pos;
  int depth = 0;
  bool in_str = false;
  while (r->pos < r->len) {
    uint8_t c = r->buf[r->pos];
    if (in_str) {
      if (c == '\\') {
        r->pos += 2;
        continue;
      }
      if (c == '"')
        in_str = false;
      r->pos++;
      continue;
    }
    if (depth == 0 && r->pos != start &&
        (c == ',' || c == '}' || c == ']'))
      break; // delimiter belongs to the enclosing op object
    if (c == '"') {
      in_str = true;
    } else if (c == '{' || c == '[') {
      depth++;
    } else if (c == '}' || c == ']') {
      if (depth == 0)
        return false; // value cannot OPEN with a closer
      depth--;
    }
    r->pos++;
  }
  *tok = start;
  *tok_len = r->pos - start;
  return r->pos > start && !in_str && depth == 0;
}

long hm_change_encode(const uint8_t *in, size_t len, uint8_t *out,
                      size_t cap) {
  ChRd r = {in, len, 0};
  ChWr w = {out, cap, 0};
  size_t tok, tn;
  unsigned long long v;

  ch_put(&w, CH_MAGIC0);
  ch_put(&w, CH_MAGIC1);

  if (!ch_lit(&r, "{\"actor\":"))
    return -1;
  if (!ch_jstr(&r, &tok, &tn))
    return -1;
  ch_token(&w, in + tok, tn);

  if (!ch_lit(&r, ",\"deps\":{"))
    return -1;
  {
    // count deps by a lookahead scan (flat object of str:int pairs)
    ChRd s = r;
    unsigned long long ndeps = 0;
    if (ch_peek(&s) == '}') {
      s.pos++;
    } else {
      while (true) {
        if (!ch_jstr(&s, &tok, &tn))
          return -1;
        if (!ch_lit(&s, ":"))
          return -1;
        if (!ch_int(&s, &v))
          return -1;
        ndeps++;
        if (ch_peek(&s) == ',') {
          s.pos++;
          continue;
        }
        if (!ch_lit(&s, "}"))
          return -1;
        break;
      }
    }
    ch_varint(&w, ndeps);
    if (ch_peek(&r) == '}') {
      r.pos++;
    } else {
      while (true) {
        if (!ch_jstr(&r, &tok, &tn))
          return -1;
        ch_token(&w, in + tok, tn);
        if (!ch_lit(&r, ":"))
          return -1;
        if (!ch_int(&r, &v))
          return -1;
        ch_varint(&w, v);
        if (ch_peek(&r) == ',') {
          r.pos++;
          continue;
        }
        if (!ch_lit(&r, "}"))
          return -1;
        break;
      }
    }
  }

  if (!ch_lit(&r, ",\"message\":"))
    return -1;
  if (!ch_jstr(&r, &tok, &tn))
    return -1;
  ch_token(&w, in + tok, tn);

  if (!ch_lit(&r, ",\"ops\":["))
    return -1;
  {
    // ops count via lookahead: count top-level '{' at depth 1 of the
    // array by a light bracket scan (strings skipped)
    ChRd s = r;
    unsigned long long nops = 0;
    int depth = 1; // inside the ops array
    bool in_str = false;
    while (s.pos < s.len && depth > 0) {
      uint8_t c = s.buf[s.pos];
      if (in_str) {
        if (c == '\\')
          s.pos++;
        else if (c == '"')
          in_str = false;
      } else if (c == '"') {
        in_str = true;
      } else if (c == '{' || c == '[') {
        if (depth == 1 && c == '{')
          nops++;
        depth++;
      } else if (c == '}' || c == ']') {
        depth--;
      }
      s.pos++;
    }
    if (depth != 0)
      return -1;
    ch_varint(&w, nops);
  }
  if (ch_peek(&r) == ']') {
    r.pos++;
  } else {
    while (true) {
      if (!ch_lit(&r, "{\"a\":"))
        return -1;
      if (!ch_int(&r, &v))
        return -1;
      ch_varint(&w, v);
      uint8_t flags = 0;
      size_t k_tok = 0, k_tn = 0, r_tok = 0, r_tn = 0;
      size_t v_tok = 0, v_tn = 0, d_tok = 0, d_tn = 0;
      size_t o_tok = 0, o_tn = 0;
      // "a" is always the first key, so every later key (sorted:
      // d, i, k, o, p, r, v; "o" mandatory) arrives comma-prefixed.
      // Collect spans, then emit in flag order.
      bool have_o = false;
      // pred list span (re-scanned at emit time)
      size_t preds_at = 0;
      unsigned long long npred = 0;
      bool have_p = false;
      while (true) {
        if (ch_lit(&r, ",\"d\":")) {
          if (!ch_jstr(&r, &d_tok, &d_tn))
            return -1;
          flags |= 16;
          continue;
        }
        if (ch_lit(&r, ",\"i\":true")) {
          flags |= 4;
          continue;
        }
        if (ch_lit(&r, ",\"k\":")) {
          if (!ch_jstr(&r, &k_tok, &k_tn))
            return -1;
          flags |= 1;
          continue;
        }
        if (ch_lit(&r, ",\"o\":")) {
          if (!ch_jstr(&r, &o_tok, &o_tn))
            return -1;
          have_o = true;
          continue;
        }
        if (ch_lit(&r, ",\"p\":[")) {
          flags |= 32;
          have_p = true;
          preds_at = r.pos;
          npred = 0;
          if (ch_peek(&r) == ']') {
            r.pos++;
          } else {
            while (true) {
              if (!ch_jstr(&r, &tok, &tn))
                return -1;
              npred++;
              if (ch_peek(&r) == ',') {
                r.pos++;
                continue;
              }
              if (!ch_lit(&r, "]"))
                return -1;
              break;
            }
          }
          continue;
        }
        if (ch_lit(&r, ",\"r\":")) {
          if (!ch_jstr(&r, &r_tok, &r_tn))
            return -1;
          flags |= 2;
          continue;
        }
        if (ch_lit(&r, ",\"v\":")) {
          if (!ch_jvalue(&r, &v_tok, &v_tn))
            return -1;
          flags |= 8;
          continue;
        }
        break;
      }
      if (!have_o || !ch_lit(&r, "}"))
        return -1;
      ch_put(&w, flags);
      ch_token(&w, in + o_tok, o_tn);
      if (flags & 1)
        ch_token(&w, in + k_tok, k_tn);
      if (flags & 2)
        ch_token(&w, in + r_tok, r_tn);
      if (flags & 8)
        ch_token(&w, in + v_tok, v_tn);
      if (flags & 16)
        ch_token(&w, in + d_tok, d_tn);
      if (have_p) {
        ch_varint(&w, npred);
        ChRd pr = {in, len, preds_at};
        if (ch_peek(&pr) == ']') {
          pr.pos++;
        } else {
          for (unsigned long long i = 0; i < npred; i++) {
            if (!ch_jstr(&pr, &tok, &tn))
              return -1;
            ch_token(&w, in + tok, tn);
            if (ch_peek(&pr) == ',')
              pr.pos++;
          }
        }
      }
      if (ch_peek(&r) == ',') {
        r.pos++;
        continue;
      }
      if (!ch_lit(&r, "]"))
        return -1;
      break;
    }
  }

  if (!ch_lit(&r, ",\"seq\":"))
    return -1;
  if (!ch_int(&r, &v))
    return -1;
  ch_varint(&w, v);
  if (!ch_lit(&r, ",\"startOp\":"))
    return -1;
  if (!ch_int(&r, &v))
    return -1;
  ch_varint(&w, v);
  if (!ch_lit(&r, ",\"time\":"))
    return -1;
  if (!ch_int(&r, &v))
    return -1;
  ch_varint(&w, v);
  if (!ch_lit(&r, "}") || r.pos != len)
    return -1;
  return (long)w.pos;
}

// --- decode side: binary frame -> canonical JSON --------------------

static bool ch_rd_varint(ChRd *r, unsigned long long *out) {
  unsigned long long v = 0;
  int shift = 0;
  while (r->pos < r->len) {
    uint8_t b = r->buf[r->pos++];
    if (shift >= 63 && (b & 0x7f) > 1)
      return false;
    v |= (unsigned long long)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return v <= CH_IMAX;
    }
    shift += 7;
    if (shift > 63)
      return false;
  }
  return false;
}

static bool ch_rd_token(ChRd *r, size_t *tok, size_t *tn) {
  unsigned long long n;
  if (!ch_rd_varint(r, &n))
    return false;
  if (n > r->len - r->pos)
    return false;
  *tok = r->pos;
  *tn = (size_t)n;
  r->pos += (size_t)n;
  return true;
}

long hm_change_decode(const uint8_t *in, size_t len, uint8_t *out,
                      size_t cap) {
  ChRd r = {in, len, 0};
  ChWr w = {out, cap, 0};
  size_t tok, tn;
  unsigned long long v, n;

  if (len < 2 || in[0] != CH_MAGIC0 || in[1] != CH_MAGIC1)
    return -1;
  r.pos = 2;

  ch_str(&w, "{\"actor\":\"");
  if (!ch_rd_token(&r, &tok, &tn))
    return -1;
  ch_bytes(&w, in + tok, tn);
  ch_str(&w, "\",\"deps\":{");
  if (!ch_rd_varint(&r, &n) || n > len)
    return -1;
  for (unsigned long long i = 0; i < n; i++) {
    if (i)
      ch_put(&w, ',');
    if (!ch_rd_token(&r, &tok, &tn))
      return -1;
    ch_put(&w, '"');
    ch_bytes(&w, in + tok, tn);
    ch_str(&w, "\":");
    if (!ch_rd_varint(&r, &v))
      return -1;
    ch_decimal(&w, v);
  }
  ch_str(&w, "},\"message\":\"");
  if (!ch_rd_token(&r, &tok, &tn))
    return -1;
  ch_bytes(&w, in + tok, tn);
  ch_str(&w, "\",\"ops\":[");
  if (!ch_rd_varint(&r, &n) || n > len)
    return -1;
  for (unsigned long long i = 0; i < n; i++) {
    if (i)
      ch_put(&w, ',');
    unsigned long long action;
    if (!ch_rd_varint(&r, &action))
      return -1;
    if (r.pos >= r.len)
      return -1;
    uint8_t flags = r.buf[r.pos++];
    if (flags & ~(1 | 2 | 4 | 8 | 16 | 32))
      return -1;
    size_t o_tok, o_tn, k_tok = 0, k_tn = 0, r_tok = 0, r_tn = 0;
    size_t v_tok = 0, v_tn = 0, d_tok = 0, d_tn = 0;
    if (!ch_rd_token(&r, &o_tok, &o_tn))
      return -1;
    if ((flags & 1) && !ch_rd_token(&r, &k_tok, &k_tn))
      return -1;
    if ((flags & 2) && !ch_rd_token(&r, &r_tok, &r_tn))
      return -1;
    if ((flags & 8) && !ch_rd_token(&r, &v_tok, &v_tn))
      return -1;
    if ((flags & 16) && !ch_rd_token(&r, &d_tok, &d_tn))
      return -1;
    ch_str(&w, "{\"a\":");
    ch_decimal(&w, action);
    if (flags & 16) {
      ch_str(&w, ",\"d\":\"");
      ch_bytes(&w, in + d_tok, d_tn);
      ch_put(&w, '"');
    }
    if (flags & 4)
      ch_str(&w, ",\"i\":true");
    if (flags & 1) {
      ch_str(&w, ",\"k\":\"");
      ch_bytes(&w, in + k_tok, k_tn);
      ch_put(&w, '"');
    }
    ch_str(&w, ",\"o\":\"");
    ch_bytes(&w, in + o_tok, o_tn);
    ch_put(&w, '"');
    if (flags & 32) {
      unsigned long long np;
      if (!ch_rd_varint(&r, &np) || np > len)
        return -1;
      ch_str(&w, ",\"p\":[");
      for (unsigned long long j = 0; j < np; j++) {
        if (j)
          ch_put(&w, ',');
        if (!ch_rd_token(&r, &tok, &tn))
          return -1;
        ch_put(&w, '"');
        ch_bytes(&w, in + tok, tn);
        ch_put(&w, '"');
      }
      ch_put(&w, ']');
    }
    if (flags & 2) {
      ch_str(&w, ",\"r\":\"");
      ch_bytes(&w, in + r_tok, r_tn);
      ch_put(&w, '"');
    }
    if (flags & 8) {
      ch_str(&w, ",\"v\":");
      ch_bytes(&w, in + v_tok, v_tn);
    }
    ch_put(&w, '}');
  }
  ch_str(&w, "],\"seq\":");
  if (!ch_rd_varint(&r, &v))
    return -1;
  ch_decimal(&w, v);
  ch_str(&w, ",\"startOp\":");
  if (!ch_rd_varint(&r, &v))
    return -1;
  ch_decimal(&w, v);
  ch_str(&w, ",\"time\":");
  if (!ch_rd_varint(&r, &v))
    return -1;
  ch_decimal(&w, v);
  ch_put(&w, '}');
  if (r.pos != len)
    return -1;
  return (long)w.pos;
}

} // extern "C"
