// hm_native: C++ native layer for hypermerge_tpu.
//
// Provides the primitives the reference gets from native npm addons
// (SURVEY.md §2.4): ed25519 keypairs/signatures (sodium-native
// equivalent), BLAKE2b hashing (discovery keys, merkle nodes), and
// brotli block compression (iltorb equivalent), with a zlib fallback.
//
// The image ships runtime shared objects for libsodium and libbrotli but
// no headers, so the stable C ABIs are declared here and the libraries
// are dlopen'd at init; zlib has headers and is linked directly. Every
// entry point degrades gracefully: callers check hm_caps() and fall back
// to pure-Python implementations when a capability is absent.
//
// Build: make -C hypermerge_tpu/native  (produces libhm_native.so)

#include <cstdint>
#include <cstring>
#include <dlfcn.h>
#include <zlib.h>

// ---------------------------------------------------------------------
// dlopen'd ABIs

typedef int (*fn_sodium_init)(void);
typedef int (*fn_sign_seed_keypair)(unsigned char *, unsigned char *,
                                    const unsigned char *);
typedef int (*fn_sign_detached)(unsigned char *, unsigned long long *,
                                const unsigned char *, unsigned long long,
                                const unsigned char *);
typedef int (*fn_sign_verify_detached)(const unsigned char *,
                                       const unsigned char *,
                                       unsigned long long,
                                       const unsigned char *);
typedef int (*fn_generichash)(unsigned char *, size_t, const unsigned char *,
                              unsigned long long, const unsigned char *,
                              size_t);

typedef int (*fn_brotli_compress)(int, int, int, size_t, const uint8_t *,
                                  size_t *, uint8_t *);
typedef int (*fn_brotli_decompress)(size_t, const uint8_t *, size_t *,
                                    uint8_t *);
typedef size_t (*fn_brotli_bound)(size_t);

typedef int (*fn_scalarmult)(unsigned char *, const unsigned char *,
                             const unsigned char *);
typedef int (*fn_scalarmult_base)(unsigned char *, const unsigned char *);
typedef int (*fn_aead_encrypt)(unsigned char *, unsigned long long *,
                               const unsigned char *, unsigned long long,
                               const unsigned char *, unsigned long long,
                               const unsigned char *, const unsigned char *,
                               const unsigned char *);
typedef int (*fn_aead_decrypt)(unsigned char *, unsigned long long *,
                               unsigned char *, const unsigned char *,
                               unsigned long long, const unsigned char *,
                               unsigned long long, const unsigned char *,
                               const unsigned char *);

static fn_sign_seed_keypair p_seed_keypair = nullptr;
static fn_sign_detached p_sign = nullptr;
static fn_sign_verify_detached p_verify = nullptr;
static fn_generichash p_generichash = nullptr;
static fn_scalarmult p_scalarmult = nullptr;
static fn_scalarmult_base p_scalarmult_base = nullptr;
static fn_aead_encrypt p_aead_encrypt = nullptr;
static fn_aead_decrypt p_aead_decrypt = nullptr;
static fn_brotli_compress p_br_compress = nullptr;
static fn_brotli_decompress p_br_decompress = nullptr;
static fn_brotli_bound p_br_bound = nullptr;

static const int CAP_SODIUM = 1;
static const int CAP_BROTLI = 2;
static const int CAP_ZLIB = 4;
static int g_caps = -1;

extern "C" {

int hm_init(void) {
  if (g_caps >= 0)
    return g_caps;
  int caps = CAP_ZLIB; // linked directly

  void *sodium = dlopen("libsodium.so.23", RTLD_NOW | RTLD_GLOBAL);
  if (!sodium)
    sodium = dlopen("libsodium.so", RTLD_NOW | RTLD_GLOBAL);
  if (sodium) {
    fn_sodium_init init =
        (fn_sodium_init)dlsym(sodium, "sodium_init");
    p_seed_keypair =
        (fn_sign_seed_keypair)dlsym(sodium, "crypto_sign_seed_keypair");
    p_sign = (fn_sign_detached)dlsym(sodium, "crypto_sign_detached");
    p_verify = (fn_sign_verify_detached)dlsym(
        sodium, "crypto_sign_verify_detached");
    p_generichash = (fn_generichash)dlsym(sodium, "crypto_generichash");
    p_scalarmult = (fn_scalarmult)dlsym(sodium, "crypto_scalarmult");
    p_scalarmult_base =
        (fn_scalarmult_base)dlsym(sodium, "crypto_scalarmult_base");
    p_aead_encrypt = (fn_aead_encrypt)dlsym(
        sodium, "crypto_aead_chacha20poly1305_ietf_encrypt");
    p_aead_decrypt = (fn_aead_decrypt)dlsym(
        sodium, "crypto_aead_chacha20poly1305_ietf_decrypt");
    if (init && init() >= 0 && p_seed_keypair && p_sign && p_verify &&
        p_generichash && p_scalarmult && p_scalarmult_base &&
        p_aead_encrypt && p_aead_decrypt)
      caps |= CAP_SODIUM;
  }

  void *enc = dlopen("libbrotlienc.so.1", RTLD_NOW);
  if (!enc)
    enc = dlopen("libbrotlienc.so", RTLD_NOW);
  void *dec = dlopen("libbrotlidec.so.1", RTLD_NOW);
  if (!dec)
    dec = dlopen("libbrotlidec.so", RTLD_NOW);
  if (enc && dec) {
    p_br_compress = (fn_brotli_compress)dlsym(enc, "BrotliEncoderCompress");
    p_br_bound = (fn_brotli_bound)dlsym(enc, "BrotliEncoderMaxCompressedSize");
    p_br_decompress =
        (fn_brotli_decompress)dlsym(dec, "BrotliDecoderDecompress");
    if (p_br_compress && p_br_decompress && p_br_bound)
      caps |= CAP_BROTLI;
  }

  g_caps = caps;
  return caps;
}

int hm_caps(void) { return hm_init(); }

// -------------------------------------------------------------------
// ed25519 (requires CAP_SODIUM; returns -2 when unavailable)

int hm_ed25519_public(const uint8_t seed[32], uint8_t pub[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  uint8_t sk[64];
  return p_seed_keypair(pub, sk, seed) == 0 ? 0 : -1;
}

int hm_ed25519_sign(const uint8_t seed[32], const uint8_t *msg, size_t len,
                    uint8_t sig[64]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  uint8_t pk[32], sk[64];
  if (p_seed_keypair(pk, sk, seed) != 0)
    return -1;
  unsigned long long siglen = 64;
  return p_sign(sig, &siglen, msg, (unsigned long long)len, sk) == 0 ? 0 : -1;
}

int hm_ed25519_verify(const uint8_t pub[32], const uint8_t *msg, size_t len,
                      const uint8_t sig[64]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_verify(sig, msg, (unsigned long long)len, pub) == 0 ? 1 : 0;
}

// -------------------------------------------------------------------
// BLAKE2b (keyed) — discovery keys + merkle nodes

int hm_blake2b(const uint8_t *data, size_t len, const uint8_t *key,
               size_t keylen, uint8_t *out, size_t outlen) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_generichash(out, outlen, data, (unsigned long long)len, key,
                       keylen) == 0
             ? 0
             : -1;
}

// -------------------------------------------------------------------
// Merkle root over leaf hashes (32-byte nodes): parent =
// blake2b32(0x01 || left || right); an odd trailing node is promoted.
// Leaf hashing (0x00 || block) is done by the caller per block.

int hm_merkle_root(const uint8_t *leaves, size_t n, uint8_t out[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  if (n == 0) {
    memset(out, 0, 32);
    return 0;
  }
  // work buffer: copy of current level
  uint8_t *level = new uint8_t[n * 32];
  memcpy(level, leaves, n * 32);
  size_t count = n;
  uint8_t node[65];
  node[0] = 0x01;
  while (count > 1) {
    size_t next = 0;
    for (size_t i = 0; i + 1 < count; i += 2) {
      memcpy(node + 1, level + i * 32, 32);
      memcpy(node + 33, level + (i + 1) * 32, 32);
      if (p_generichash(level + next * 32, 32, node, 65, nullptr, 0) != 0) {
        delete[] level;
        return -1;
      }
      next++;
    }
    if (count % 2 == 1) { // odd node promoted
      memcpy(level + next * 32, level + (count - 1) * 32, 32);
      next++;
    }
    count = next;
  }
  memcpy(out, level, 32);
  delete[] level;
  return 0;
}

// -------------------------------------------------------------------
// X25519 + ChaCha20-Poly1305-IETF — the transport-encryption primitives
// (net/secure.py builds the kx handshake and per-direction nonce
// counters on top; reference: noise-peer wrapping every PeerConnection,
// src/PeerConnection.ts:36).

int hm_x25519_base(const uint8_t sk[32], uint8_t pk[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_scalarmult_base(pk, sk) == 0 ? 0 : -1;
}

int hm_x25519(const uint8_t sk[32], const uint8_t pk[32], uint8_t out[32]) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  return p_scalarmult(out, sk, pk) == 0 ? 0 : -1;
}

// out must hold len + 16 bytes; returns ciphertext length or <0
long hm_aead_encrypt(const uint8_t key[32], const uint8_t nonce[12],
                     const uint8_t *msg, size_t len, uint8_t *out) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  unsigned long long outlen = 0;
  if (p_aead_encrypt(out, &outlen, msg, (unsigned long long)len, nullptr, 0,
                     nullptr, nonce, key) != 0)
    return -1;
  return (long)outlen;
}

// out must hold len - 16 bytes; returns plaintext length, -1 on auth
// failure, -2 if unavailable
long hm_aead_decrypt(const uint8_t key[32], const uint8_t nonce[12],
                     const uint8_t *ct, size_t len, uint8_t *out) {
  if (!(hm_init() & CAP_SODIUM))
    return -2;
  unsigned long long outlen = 0;
  if (p_aead_decrypt(out, &outlen, nullptr, ct, (unsigned long long)len,
                     nullptr, 0, nonce, key) != 0)
    return -1;
  return (long)outlen;
}

// -------------------------------------------------------------------
// Block codec. codec: 1 = brotli, 2 = zlib. Returns compressed size,
// -1 on error, -2 if codec unavailable. Caller sizes `out` with
// hm_compress_bound.

size_t hm_compress_bound(size_t len) {
  size_t z = compressBound((uLong)len);
  if (hm_init() & CAP_BROTLI) {
    size_t b = p_br_bound(len);
    if (b > z)
      z = b;
  }
  return z;
}

long hm_compress(int codec, int quality, const uint8_t *in, size_t len,
                 uint8_t *out, size_t cap) {
  int caps = hm_init();
  if (codec == 1) {
    if (!(caps & CAP_BROTLI))
      return -2;
    size_t outlen = cap;
    // lgwin 22, mode 0 (generic) — quality per caller (reference iltorb
    // default quality is 11; block packing wants speed, callers pass ~5)
    if (p_br_compress(quality, 22, 0, len, in, &outlen, out) != 1)
      return -1;
    return (long)outlen;
  }
  if (codec == 2) {
    uLongf outlen = (uLongf)cap;
    if (compress2(out, &outlen, in, (uLong)len, quality) != Z_OK)
      return -1;
    return (long)outlen;
  }
  return -2;
}

long hm_decompress(int codec, const uint8_t *in, size_t len, uint8_t *out,
                   size_t cap) {
  int caps = hm_init();
  if (codec == 1) {
    if (!(caps & CAP_BROTLI))
      return -2;
    size_t outlen = cap;
    if (p_br_decompress(len, in, &outlen, out) != 1)
      return -1;
    return (long)outlen;
  }
  if (codec == 2) {
    uLongf outlen = (uLongf)cap;
    if (uncompress(out, &outlen, in, (uLong)len) != Z_OK)
      return -1;
    return (long)outlen;
  }
  return -2;
}

} // extern "C"
