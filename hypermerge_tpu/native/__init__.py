"""ctypes loader for the C++ native layer (libhm_native.so).

The reference leans on four native npm addons — sodium (ed25519/blake2b),
iltorb (brotli), better-sqlite3, utp-native (SURVEY.md §2.4). This module
loads our C++ equivalent for the crypto + codec surface and exposes it to
Python; every capability degrades to a pure-Python fallback at the call
site (utils/crypto.py, storage/block.py), so the framework runs — slower
— on machines without a toolchain or the shared libraries.

The shared object builds on demand: first import runs `make` in this
directory when `libhm_native.so` is absent and a compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading
from typing import Optional

from ..analysis.lockdep import make_lock

CAP_SODIUM = 1
CAP_BROTLI = 2
CAP_ZLIB = 4

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libhm_native.so")

_lock = make_lock("native.load")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    if shutil.which("make") is None or shutil.which("g++") is None:
        return False
    try:
        subprocess.run(
            ["make", "-C", _DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (subprocess.SubprocessError, OSError):
        return False
    return os.path.exists(_SO)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.hm_caps.restype = ctypes.c_int
    lib.hm_ed25519_public.restype = ctypes.c_int
    lib.hm_ed25519_public.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.hm_ed25519_sign.restype = ctypes.c_int
    lib.hm_ed25519_sign.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.hm_ed25519_verify.restype = ctypes.c_int
    lib.hm_ed25519_verify.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.hm_blake2b.restype = ctypes.c_int
    lib.hm_blake2b.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.hm_merkle_root.restype = ctypes.c_int
    lib.hm_merkle_root.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.hm_x25519_base.restype = ctypes.c_int
    lib.hm_x25519_base.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.hm_x25519.restype = ctypes.c_int
    lib.hm_x25519.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.hm_aead_encrypt.restype = ctypes.c_long
    lib.hm_aead_encrypt.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.hm_aead_decrypt.restype = ctypes.c_long
    lib.hm_aead_decrypt.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_size_t, ctypes.c_char_p,
    ]
    lib.hm_compress_bound.restype = ctypes.c_size_t
    lib.hm_compress_bound.argtypes = [ctypes.c_size_t]
    lib.hm_compress.restype = ctypes.c_long
    lib.hm_compress.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.hm_decompress.restype = ctypes.c_long
    lib.hm_decompress.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    # columnar pack entry points are OPTIONAL: a prebuilt .so from an
    # older tree (no compiler to rebuild with) must keep serving crypto
    # + codec rather than disabling the whole native layer.
    #
    # GIL contract: the library is loaded with ctypes.CDLL (never
    # PyDLL), so every foreign call — hm_pack_prefix included — RUNS
    # WITH THE GIL RELEASED for the duration of the C call. The
    # streaming slab pipeline (backend/pipeline.py) depends on this:
    # its pack worker thread spends its time inside hm_pack_prefix
    # while the io thread reads the next slab's sidecars and the
    # dispatch thread feeds the device. The pack entries touch only
    # caller-owned buffers (no Python objects, no allocation through
    # CPython), which is what makes the GIL-free call sound; pinned by
    # tests/test_native_pack.py::test_pack_releases_gil.
    try:
        ll = ctypes.c_longlong
        lib.hm_pack_value_minmax.restype = ctypes.c_int
        lib.hm_pack_value_minmax.argtypes = [ll] + [ctypes.c_void_p] * 12
        lib.hm_pack_prefix.restype = ctypes.c_int
        lib.hm_pack_prefix.argtypes = [ll, ll, ll] + [ctypes.c_void_p] * 16
        lib._has_pack = True
    except AttributeError:
        lib._has_pack = False
    # change-frame codec entry points are OPTIONAL for the same
    # prebuilt-.so reason; same GIL contract as the pack entries
    # (caller-owned buffers only — pinned by codec_drops_gil()).
    try:
        buf = ctypes.c_char_p
        lib.hm_change_encode.restype = ctypes.c_long
        lib.hm_change_encode.argtypes = [
            buf, ctypes.c_size_t, buf, ctypes.c_size_t,
        ]
        lib.hm_change_decode.restype = ctypes.c_long
        lib.hm_change_decode.argtypes = [
            buf, ctypes.c_size_t, buf, ctypes.c_size_t,
        ]
        lib._has_codec = True
    except AttributeError:
        lib._has_codec = False
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The bound library, building it first if needed; None when
    unavailable (no compiler and no prebuilt .so)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HM_NO_NATIVE"):
            return None
        src = os.path.join(_DIR, "src", "hm_native.cpp")
        stale = os.path.exists(_SO) and os.path.exists(src) and (
            os.path.getmtime(src) > os.path.getmtime(_SO)
        )
        if (not os.path.exists(_SO) or stale) and not _build():
            if not os.path.exists(_SO):
                return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except (OSError, AttributeError):
            # unloadable, or a stale prebuilt .so missing newer symbols
            # (rebuild failed): fall back to pure Python
            _lib = None
        return _lib


def caps() -> int:
    lib = load()
    return lib.hm_caps() if lib is not None else 0


def pack_lib() -> Optional[ctypes.CDLL]:
    """The library handle iff it carries the columnar pack entry points
    (ops/columnar.py native fast path); None otherwise."""
    lib = load()
    if lib is None or not getattr(lib, "_has_pack", False):
        return None
    return lib


def pack_drops_gil() -> bool:
    """True when the pack entry points are bound through a plain
    ctypes.CDLL, whose foreign calls release the GIL — the property the
    bulk loader's pipelined pack stage relies on to overlap packing
    with sidecar IO and device dispatch. (ctypes.PyDLL would hold the
    GIL; we never load through it.)"""
    lib = pack_lib()
    return lib is not None and not isinstance(lib, ctypes.PyDLL)


def pack_parallel_ok() -> bool:
    """True when hm_pack_prefix / hm_pack_value_minmax may be called
    from SEVERAL threads at once — the pack pool's contract
    (HM_PACK_WORKERS > 1, backend/pipeline.py).

    The entry points are stateless C loops: every pointer they touch
    (source planes, LUTs, output buffers) is a caller-owned argument,
    there are no globals, no allocation, and no errno-style side
    channels, so concurrent calls with DISTINCT output buffers are
    safe by construction. Distinctness is the caller's obligation and
    holds trivially for the pool: each worker packs a different slab
    into buffers it just allocated. Combined with the GIL release
    (pack_drops_gil) this is what makes N pack workers N-core real
    rather than time-sliced."""
    return pack_drops_gil()


def codec_lib() -> Optional[ctypes.CDLL]:
    """The library handle iff it carries the change-frame codec entry
    points (crdt/codec.py native fast path); None otherwise."""
    lib = load()
    if lib is None or not getattr(lib, "_has_codec", False):
        return None
    return lib


def codec_drops_gil() -> bool:
    """True when the change-codec entry points are bound through a
    plain ctypes.CDLL, whose foreign calls release the GIL — the
    property the sharded write daemon relies on to parse frames from N
    connections on real threads. (ctypes.PyDLL would hold the GIL; we
    never load through it.)"""
    lib = codec_lib()
    return lib is not None and not isinstance(lib, ctypes.PyDLL)


def _codec_call(fn, data: bytes, guess: int) -> Optional[bytes]:
    """Counting-writer protocol shared by encode/decode: the entry
    point always returns the size it NEEDS and only writes what fits
    in cap, so one retry with the returned size always lands."""
    out = ctypes.create_string_buffer(guess)
    n = fn(data, len(data), out, guess)
    if n < 0:
        return None
    if n > guess:
        out = ctypes.create_string_buffer(n)
        n = fn(data, len(data), out, n)
        if n < 0 or n > len(out):
            return None
    return out.raw[:n]


def change_encode(raw: bytes) -> Optional[bytes]:
    """Canonical change JSON -> binary change frame; None when the
    native layer is absent or the input is off-canon (caller falls
    back to the Python twin / raw JSON block)."""
    lib = codec_lib()
    if lib is None:
        return None
    return _codec_call(lib.hm_change_encode, raw, len(raw) + 16)


def change_decode(frame: bytes) -> Optional[bytes]:
    """Binary change frame -> canonical change JSON; None when the
    native layer is absent or the frame is malformed."""
    lib = codec_lib()
    if lib is None:
        return None
    return _codec_call(lib.hm_change_decode, frame, 2 * len(frame) + 64)


def available() -> bool:
    return load() is not None


# ---------------------------------------------------------------------
# typed wrappers (None / raise on unavailable capability — callers that
# want graceful degradation go through utils/crypto.py)


def ed25519_public(seed: bytes) -> Optional[bytes]:
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    out = ctypes.create_string_buffer(32)
    if lib.hm_ed25519_public(seed, out) != 0:
        return None
    return out.raw


def ed25519_sign(seed: bytes, msg: bytes) -> Optional[bytes]:
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    sig = ctypes.create_string_buffer(64)
    if lib.hm_ed25519_sign(seed, msg, len(msg), sig) != 0:
        return None
    return sig.raw


def ed25519_verify(pub: bytes, msg: bytes, sig: bytes) -> Optional[bool]:
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    return bool(lib.hm_ed25519_verify(pub, msg, len(msg), sig))


def blake2b(
    data: bytes, key: bytes = b"", outlen: int = 32
) -> Optional[bytes]:
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    out = ctypes.create_string_buffer(outlen)
    if lib.hm_blake2b(data, len(data), key or None, len(key), out, outlen) != 0:
        return None
    return out.raw


def merkle_root(leaves: bytes) -> Optional[bytes]:
    """Root over concatenated 32-byte leaf hashes."""
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    if len(leaves) % 32:
        raise ValueError("leaves must be a multiple of 32 bytes")
    out = ctypes.create_string_buffer(32)
    if lib.hm_merkle_root(leaves, len(leaves) // 32, out) != 0:
        return None
    return out.raw


def x25519_base(sk: bytes) -> Optional[bytes]:
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    out = ctypes.create_string_buffer(32)
    if lib.hm_x25519_base(sk, out) != 0:
        return None
    return out.raw


def x25519(sk: bytes, pk: bytes) -> Optional[bytes]:
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    out = ctypes.create_string_buffer(32)
    if lib.hm_x25519(sk, pk, out) != 0:
        return None
    return out.raw


def aead_encrypt(key: bytes, nonce: bytes, msg: bytes) -> Optional[bytes]:
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    out = ctypes.create_string_buffer(len(msg) + 16)
    n = lib.hm_aead_encrypt(key, nonce, msg, len(msg), out)
    if n < 0:
        return None
    return out.raw[:n]


_AEAD_FAIL = object()


def aead_decrypt(key: bytes, nonce: bytes, ct: bytes):
    """None = native unavailable; _AEAD_FAIL = authentication failed."""
    lib = load()
    if lib is None or not (lib.hm_caps() & CAP_SODIUM):
        return None
    if len(ct) < 16:
        return _AEAD_FAIL
    out = ctypes.create_string_buffer(max(len(ct) - 16, 1))
    n = lib.hm_aead_decrypt(key, nonce, ct, len(ct), out)
    if n == -2:
        return None
    if n < 0:
        return _AEAD_FAIL
    return out.raw[:n]


CODEC_BROTLI = 1
CODEC_ZLIB = 2


def compress(codec: int, data: bytes, quality: int = 5) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    cap = lib.hm_compress_bound(len(data))
    out = ctypes.create_string_buffer(cap)
    n = lib.hm_compress(codec, quality, data, len(data), out, cap)
    if n < 0:
        return None
    return out.raw[:n]


def decompress(codec: int, data: bytes, raw_len: int) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(max(raw_len, 1))
    n = lib.hm_decompress(codec, data, len(data), out, raw_len)
    if n < 0:
        return None
    return out.raw[:n]
