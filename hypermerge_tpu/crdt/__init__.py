"""CRDT core: clocks, changes, host apply path, patch generation."""
