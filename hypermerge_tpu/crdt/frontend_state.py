"""FrontendDoc — materialized document state + change-fn proxy.

Semantic twin of Automerge's Frontend as the reference uses it
(SURVEY.md §2.2: Frontend.init/change/applyPatch/setActorId). The frontend
holds ONLY patch-derived state — the backend (OpSet or the batched device
path) is the single source of truth — so frontend and backend can live on
different threads/processes exactly like the reference's split
(reference README.md:160-184, src/DocFrontend.ts).

`change(fn)` runs the user's mutation function against a scratch mirror of
the current state, records OpIntents, and returns (request, preview):
- the preview is pushed to subscribers immediately («change preview»,
  reference src/DocFrontend.ts:142),
- the request goes to the backend, whose patch echo produces the canonical
  state («change final», reference src/RepoBackend.ts:348-362).
"""

from __future__ import annotations

import itertools
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..models import Counter, Table, Text
from .change import Action, ChangeRequest, OpIntent
from .patch import Diff, Patch

ROOT_STR = "0@_root"


@dataclass
class _Cell:
    value: Any = None
    link: bool = False  # value is an object-id str into FrontendDoc.objs
    datatype: Optional[str] = None
    conflicts: tuple = ()


@dataclass
class _FObj:
    type: str
    data: Dict[str, _Cell] = field(default_factory=dict)  # map/table
    items: List[_Cell] = field(default_factory=list)  # list/text
    elem_ids: List[str] = field(default_factory=list)


class FrontendDoc:
    def __init__(self) -> None:
        self.objs: Dict[str, _FObj] = {ROOT_STR: _FObj("map")}
        self.clock: Dict[str, int] = {}
        self.max_op = 0
        self._cache: Any = None
        self._dirty = True

    # ------------------------------------------------------------------
    # patch application (backend -> frontend)

    def apply_patch(self, patch: Patch) -> None:
        for diff in patch.diffs:
            self._apply_diff(diff)
        self.clock = dict(patch.clock)
        self.max_op = patch.max_op
        self._dirty = True

    def _apply_diff(self, d: Diff) -> None:
        if d.action == "create":
            self.objs[d.obj] = _FObj(d.obj_type)
            return
        obj = self.objs.get(d.obj)
        if obj is None:
            return
        if d.action == "set":
            cell = _Cell(d.value, d.link, d.datatype, d.conflicts)
            if d.key is not None:
                obj.data[d.key] = cell
            elif d.index is not None and 0 <= d.index < len(obj.items):
                obj.items[d.index] = cell
                if d.elem_id:
                    obj.elem_ids[d.index] = d.elem_id
        elif d.action == "insert":
            cell = _Cell(d.value, d.link, d.datatype)
            idx = d.index if d.index is not None else len(obj.items)
            idx = max(0, min(idx, len(obj.items)))
            obj.items.insert(idx, cell)
            obj.elem_ids.insert(idx, d.elem_id or "")
        elif d.action == "remove":
            if d.key is not None:
                obj.data.pop(d.key, None)
            elif d.index is not None and 0 <= d.index < len(obj.items):
                del obj.items[d.index]
                del obj.elem_ids[d.index]

    # ------------------------------------------------------------------
    # reads

    def materialize(self) -> Any:
        if self._dirty:
            self._cache = self._mat_obj(ROOT_STR)
            self._dirty = False
        return self._cache

    def _mat_obj(self, obj_id: str) -> Any:
        obj = self.objs.get(obj_id)
        if obj is None:
            return None
        if obj.type in ("list", "text"):
            values = [self._mat_cell(c) for c in obj.items]
            if obj.type == "text":
                return Text([str(v) for v in values])
            return values
        data = {k: self._mat_cell(c) for k, c in obj.data.items()}
        if obj.type == "table":
            return Table(data)
        return data

    def _mat_cell(self, cell: _Cell) -> Any:
        if cell.link:
            return self._mat_obj(cell.value)
        if cell.datatype == "counter":
            return Counter(cell.value)
        return cell.value

    def conflicts_at(self, obj_id: str, key: str):
        obj = self.objs.get(obj_id)
        if not obj:
            return {}
        cell = obj.data.get(key)
        if not cell:
            return {}
        return {c.op_id: c.value for c in cell.conflicts}

    # ------------------------------------------------------------------
    # local change recording

    def change(
        self,
        fn: Callable[[Any], None],
        actor: str,
        seq: int,
        message: str = "",
    ) -> Tuple[Optional[ChangeRequest], Any]:
        """Run fn over a mutable scratch mirror; returns (request|None if no
        mutations, preview materialized doc)."""
        rec = _Recorder()
        scratch = _scratch_from(self, ROOT_STR)
        fn(_proxy_for(scratch, rec))
        if not rec.intents:
            return None, self.materialize()
        request = ChangeRequest(
            actor=actor,
            seq=seq,
            time=int(_time.time()),
            message=message,
            intents=tuple(rec.intents),
        )
        return request, _scratch_to_plain(scratch)


# ---------------------------------------------------------------------------
# scratch mirror + proxies


class _Scratch:
    __slots__ = ("type", "obj_id", "entries", "items")

    def __init__(self, type_: str, obj_id: str) -> None:
        self.type = type_
        self.obj_id = obj_id  # real op-id str or "tmp:<n>"
        self.entries: Dict[str, Any] = {}
        self.items: List[Any] = []


def _scratch_from(doc: FrontendDoc, obj_id: str) -> _Scratch:
    obj = doc.objs[obj_id]
    s = _Scratch(obj.type, obj_id)
    if obj.type in ("list", "text"):
        s.items = [_scratch_cell(doc, c) for c in obj.items]
    else:
        s.entries = {k: _scratch_cell(doc, c) for k, c in obj.data.items()}
    return s


def _scratch_cell(doc: FrontendDoc, cell: _Cell) -> Any:
    if cell.link:
        return _scratch_from(doc, cell.value)
    if cell.datatype == "counter":
        return Counter(cell.value)
    return cell.value


def _scratch_to_plain(s: _Scratch) -> Any:
    if s.type == "text":
        return Text([str(_plain(v)) for v in s.items])
    if s.type == "list":
        return [_plain(v) for v in s.items]
    data = {k: _plain(v) for k, v in s.entries.items()}
    if s.type == "table":
        return Table(data)
    return data


def _plain(v: Any) -> Any:
    return _scratch_to_plain(v) if isinstance(v, _Scratch) else v


class _Recorder:
    def __init__(self) -> None:
        self.intents: List[OpIntent] = []
        self._tmp = itertools.count()

    def next_tmp(self) -> str:
        return f"tmp:{next(self._tmp)}"


_MAKE_BY_VALUE = (
    (dict, Action.MAKE_MAP, "map"),
    (list, Action.MAKE_LIST, "list"),
    (Text, Action.MAKE_TEXT, "text"),
    (Table, Action.MAKE_TABLE, "table"),
)


def _classify(value: Any):
    for cls, action, type_ in _MAKE_BY_VALUE:
        if isinstance(value, cls):
            return action, type_
    return None, None


def _proxy_for(s: _Scratch, rec: _Recorder):
    if s.type in ("list",):
        return ListProxy(s, rec)
    if s.type == "text":
        return TextProxy(s, rec)
    if s.type == "table":
        return TableProxy(s, rec)
    return MapProxy(s, rec)


class _BaseProxy:
    def __init__(self, scratch: _Scratch, rec: _Recorder) -> None:
        self._s = scratch
        self._rec = rec

    @property
    def _obj(self) -> str:
        return self._s.obj_id

    def _ingest(self, value: Any, key=None, index=None, insert=False):
        """Record intents for assigning `value` at a location; returns the
        scratch representation. Container values expand into MAKE + child
        population (deep create, like Automerge's proxy assignment)."""
        action, type_ = _classify(value)
        if action is None:
            datatype = "counter" if isinstance(value, Counter) else None
            self._rec.intents.append(
                OpIntent(
                    action=Action.SET,
                    obj=self._obj,
                    key=key,
                    index=index,
                    insert=insert,
                    value=int(value) if datatype == "counter" else value,
                    datatype=datatype,
                )
            )
            return value
        tmp = self._rec.next_tmp()
        self._rec.intents.append(
            OpIntent(
                action=action,
                obj=self._obj,
                key=key,
                index=index,
                insert=insert,
                temp_id=tmp,
            )
        )
        child = _Scratch(type_, tmp)
        child_proxy = _proxy_for(child, self._rec)
        if isinstance(value, dict):
            for k, v in value.items():
                child_proxy[k] = v
        elif isinstance(value, Table):
            for rid in value.ids:
                child_proxy.add(rid, value.by_id(rid))
        elif isinstance(value, Text):
            for i, ch in enumerate(value):
                child_proxy.insert(i, ch)
        elif isinstance(value, list):
            for i, v in enumerate(value):
                child_proxy.insert(i, v)
        return child


class MapProxy(_BaseProxy):
    def __getitem__(self, key: str) -> Any:
        v = self._s.entries[key]
        return _proxy_for(v, self._rec) if isinstance(v, _Scratch) else v

    def get(self, key: str, default: Any = None) -> Any:
        try:
            return self[key]
        except KeyError:
            return default

    def __contains__(self, key: str) -> bool:
        return key in self._s.entries

    def keys(self):
        return self._s.entries.keys()

    def __setitem__(self, key: str, value: Any) -> None:
        self._s.entries[key] = self._ingest(value, key=key)

    def __delitem__(self, key: str) -> None:
        if key in self._s.entries:
            del self._s.entries[key]
            self._rec.intents.append(
                OpIntent(action=Action.DEL, obj=self._obj, key=key)
            )

    def increment(self, key: str, delta: int = 1) -> None:
        cur = self._s.entries.get(key)
        if not isinstance(cur, Counter):
            raise TypeError(f"{key!r} is not a Counter")
        self._rec.intents.append(
            OpIntent(action=Action.INC, obj=self._obj, key=key, value=delta)
        )
        self._s.entries[key] = Counter(int(cur) + delta)


class TableProxy(_BaseProxy):
    def add(self, row_id: str, row: Any) -> str:
        self._s.entries[row_id] = self._ingest(row, key=row_id)
        return row_id

    def remove(self, row_id: str) -> None:
        if row_id in self._s.entries:
            del self._s.entries[row_id]
            self._rec.intents.append(
                OpIntent(action=Action.DEL, obj=self._obj, key=row_id)
            )

    def by_id(self, row_id: str) -> Any:
        v = self._s.entries.get(row_id)
        return _proxy_for(v, self._rec) if isinstance(v, _Scratch) else v

    @property
    def ids(self):
        return sorted(self._s.entries.keys())


class ListProxy(_BaseProxy):
    def __len__(self) -> int:
        return len(self._s.items)

    def __getitem__(self, i: int) -> Any:
        v = self._s.items[i]
        return _proxy_for(v, self._rec) if isinstance(v, _Scratch) else v

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def insert(self, i: int, value: Any) -> None:
        i = max(0, min(i, len(self._s.items)))
        self._s.items.insert(i, self._ingest(value, index=i, insert=True))

    def append(self, value: Any) -> None:
        self.insert(len(self._s.items), value)

    def __setitem__(self, i: int, value: Any) -> None:
        if not 0 <= i < len(self._s.items):
            raise IndexError(i)
        self._s.items[i] = self._ingest(value, index=i)

    def __delitem__(self, i: int) -> None:
        if not 0 <= i < len(self._s.items):
            raise IndexError(i)
        del self._s.items[i]
        self._rec.intents.append(
            OpIntent(action=Action.DEL, obj=self._obj, index=i)
        )

    def increment(self, i: int, delta: int = 1) -> None:
        cur = self._s.items[i]
        if not isinstance(cur, Counter):
            raise TypeError(f"index {i} is not a Counter")
        self._rec.intents.append(
            OpIntent(action=Action.INC, obj=self._obj, index=i, value=delta)
        )
        self._s.items[i] = Counter(int(cur) + delta)


class TextProxy(_BaseProxy):
    def __len__(self) -> int:
        return len(self._s.items)

    def __str__(self) -> str:
        return "".join(str(v) for v in self._s.items)

    def insert(self, i: int, text: str) -> None:
        i = max(0, min(i, len(self._s.items)))
        for offset, ch in enumerate(text):
            self._rec.intents.append(
                OpIntent(
                    action=Action.SET,
                    obj=self._obj,
                    index=i + offset,
                    insert=True,
                    value=ch,
                )
            )
            self._s.items.insert(i + offset, ch)

    def delete(self, i: int, count: int = 1) -> None:
        for _ in range(count):
            if not 0 <= i < len(self._s.items):
                return
            del self._s.items[i]
            self._rec.intents.append(
                OpIntent(action=Action.DEL, obj=self._obj, index=i)
            )
