"""Binary change-frame codec: canonical change JSON <-> compact frame.

PR 14 measured the write plane's ceiling as ~0.9ms of per-edit pure-
Python CPU under one GIL, much of it JSON change-frame work. This
module moves that hot loop behind `native/src/hm_native.cpp`'s
`hm_change_encode` / `hm_change_decode` (plain ctypes.CDLL, so the C
call runs GIL-FREE — frames from N connections parse on real
threads), with this file's pure-Python twin as the always-available
fallback and the parity oracle.

The parity trick that makes bit-identical twins cheap: the frame
stores every string field as its JSON-ESCAPED inner bytes exactly as
`utils/json_buffer.bufferify` produced them, and op values as their
full canonical JSON token bytes. The native side only SCANS tokens
out of canonical JSON on encode and copies them back verbatim on
decode — it never formats a float or escapes a string, so there is no
formatter to keep in sync with CPython. The only bytes either side
formats itself are decimal integers and the fixed canonical key
skeleton. Pinned by tests/test_native_codec.py's fuzz across
HM_NATIVE_CODEC=1/0 in both orders.

Frame layout (varint = unsigned LEB128, token = varint len + bytes),
fields in canonical JSON key order so encode is one forward pass:

    b"\\xc5\\x01" magic; token actor;
    varint n_deps; n_deps * (token key, varint seq);
    token message;
    varint n_ops; per op: varint action; uint8 flags
      (1=key 2=ref 4=insert 8=value 16=datatype 32=pred);
      token obj; [token key] [token ref] [token value-JSON]
      [token datatype] [varint n_pred + n_pred * token];
    varint seq, startOp, time.

`HM_NATIVE_CODEC=0` is the escape hatch: it stops NEW blocks being
written as binary frames (and routes decode through the twin), but
readers always handle both formats — a feed written with the codec on
stays readable with it off, and vice versa.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

from .. import native
from ..utils.json_buffer import bufferify

MAGIC = b"\xc5\x01"

_IMAX = (1 << 63) - 1  # native ch_int / ch_rd_varint ceiling

_F_KEY = 1
_F_REF = 2
_F_INSERT = 4
_F_VALUE = 8
_F_DATATYPE = 16
_F_PRED = 32

_TOP_KEYS = frozenset(
    ("actor", "deps", "message", "ops", "seq", "startOp", "time")
)
_OP_KEYS = frozenset("adikoprv")


def enabled() -> bool:
    """Whether writers should emit binary change frames at all."""
    return os.environ.get("HM_NATIVE_CODEC", "1") != "0"


def is_frame(data: bytes) -> bool:
    return data[:2] == MAGIC


# ---------------------------------------------------------------------
# shared primitives


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _jstr(s: str) -> bytes:
    """The JSON-escaped inner bytes of `s`, exactly as bufferify would
    embed them (ensure_ascii keeps the result pure ASCII)."""
    return json.dumps(s)[1:-1].encode("ascii")


def _token(b: bytes) -> bytes:
    return _varint(len(b)) + b


def _uint_ok(v: Any) -> bool:
    # `type is int` on purpose: True/False are ints by subclass but
    # serialize as true/false, which the native scanner rejects
    return type(v) is int and 0 <= v <= _IMAX


# ---------------------------------------------------------------------
# encode


def _encode_py(obj: Any) -> Optional[bytes]:
    """The twin: canonical change dict -> frame bytes, or None when the
    shape is outside what the native scanner accepts (caller falls back
    to the JSON block format). The supported-shape rules here MUST
    match hm_change_encode's strictness exactly — that agreement is
    what the fuzz pins."""
    if type(obj) is not dict or set(obj) != _TOP_KEYS:
        return None
    actor, deps, message, ops = (
        obj["actor"], obj["deps"], obj["message"], obj["ops"],
    )
    if type(actor) is not str or type(message) is not str:
        return None
    if not (_uint_ok(obj["seq"]) and _uint_ok(obj["startOp"])
            and _uint_ok(obj["time"])):
        return None
    if type(deps) is not dict or type(ops) is not list:
        return None
    out = bytearray(MAGIC)
    out += _token(_jstr(actor))
    out += _varint(len(deps))
    for k in sorted(deps):
        v = deps[k]
        if type(k) is not str or not _uint_ok(v):
            return None
        out += _token(_jstr(k))
        out += _varint(v)
    out += _token(_jstr(message))
    out += _varint(len(ops))
    for op in ops:
        if type(op) is not dict or "a" not in op or "o" not in op:
            return None
        if not _OP_KEYS.issuperset(op):
            return None
        if not _uint_ok(op["a"]) or type(op["o"]) is not str:
            return None
        flags = 0
        if "k" in op:
            if type(op["k"]) is not str:
                return None
            flags |= _F_KEY
        if "r" in op:
            if type(op["r"]) is not str:
                return None
            flags |= _F_REF
        if "i" in op:
            if op["i"] is not True:
                return None
            flags |= _F_INSERT
        if "v" in op:
            flags |= _F_VALUE
        if "d" in op:
            if type(op["d"]) is not str:
                return None
            flags |= _F_DATATYPE
        if "p" in op:
            if type(op["p"]) is not list or any(
                type(p) is not str for p in op["p"]
            ):
                return None
            flags |= _F_PRED
        out += _varint(op["a"])
        out.append(flags)
        out += _token(_jstr(op["o"]))
        if flags & _F_KEY:
            out += _token(_jstr(op["k"]))
        if flags & _F_REF:
            out += _token(_jstr(op["r"]))
        if flags & _F_VALUE:
            out += _token(bufferify(op["v"]))
        if flags & _F_DATATYPE:
            out += _token(_jstr(op["d"]))
        if flags & _F_PRED:
            out += _varint(len(op["p"]))
            for p in op["p"]:
                out += _token(_jstr(p))
    out += _varint(obj["seq"])
    out += _varint(obj["startOp"])
    out += _varint(obj["time"])
    return bytes(out)


def _use_native() -> bool:
    return enabled() and native.codec_lib() is not None


def encode_change(obj: Any) -> Optional[bytes]:
    """Change dict -> binary frame; None when the shape is unsupported
    (caller stores the JSON block instead). Native-first: the C scan
    of bufferify output runs without the GIL."""
    if _use_native():
        frame = native.change_encode(bufferify(obj))
        if frame is not None:
            return frame
        # native said unsupported; the twin must agree (fuzz-pinned),
        # so fall through to it only to produce the same None
    return _encode_py(obj)


# ---------------------------------------------------------------------
# decode


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def varint(self) -> int:
        v = 0
        shift = 0
        while True:
            if self.pos >= len(self.buf):
                raise ValueError("corrupt change frame: truncated varint")
            b = self.buf[self.pos]
            self.pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                if v > _IMAX:
                    raise ValueError("corrupt change frame: varint range")
                return v
            shift += 7
            if shift > 63:
                raise ValueError("corrupt change frame: varint overflow")

    def count(self) -> int:
        # list/dict lengths from untrusted frames must be bounded by
        # the bytes that could possibly back them before sizing loops
        n = self.varint()
        if n > len(self.buf):
            raise ValueError("corrupt change frame: implausible count")
        return n

    def token(self) -> bytes:
        n = self.varint()
        if n > len(self.buf) - self.pos:
            raise ValueError("corrupt change frame: truncated token")
        t = self.buf[self.pos : self.pos + n]
        self.pos += n
        return t


def _decode_py(frame: bytes) -> bytes:
    """The twin: frame bytes -> canonical change JSON bytes. Raises
    ValueError on malformed input (same frames hm_change_decode
    rejects with -1)."""
    if not is_frame(frame):
        raise ValueError("corrupt change frame: bad magic")
    r = _Reader(frame)
    r.pos = 2
    out = bytearray(b'{"actor":"')
    out += r.token()
    out += b'","deps":{'
    for i in range(r.count()):
        if i:
            out += b","
        out += b'"' + r.token() + b'":' + str(r.varint()).encode()
    out += b'},"message":"'
    out += r.token()
    out += b'","ops":['
    for i in range(r.count()):
        if i:
            out += b","
        out += b'{"a":' + str(r.varint()).encode()
        if r.pos >= len(frame):
            raise ValueError("corrupt change frame: truncated op")
        flags = frame[r.pos]
        r.pos += 1
        if flags & ~0x3F:
            raise ValueError("corrupt change frame: unknown op flags")
        o = r.token()
        k = r.token() if flags & _F_KEY else b""
        ref = r.token() if flags & _F_REF else b""
        val = r.token() if flags & _F_VALUE else b""
        dt = r.token() if flags & _F_DATATYPE else b""
        if flags & _F_DATATYPE:
            out += b',"d":"' + dt + b'"'
        if flags & _F_INSERT:
            out += b',"i":true'
        if flags & _F_KEY:
            out += b',"k":"' + k + b'"'
        out += b',"o":"' + o + b'"'
        if flags & _F_PRED:
            out += b',"p":['
            for j in range(r.count()):
                if j:
                    out += b","
                out += b'"' + r.token() + b'"'
            out += b"]"
        if flags & _F_REF:
            out += b',"r":"' + ref + b'"'
        if flags & _F_VALUE:
            out += b',"v":' + val
        out += b"}"
    out += b'],"seq":' + str(r.varint()).encode()
    out += b',"startOp":' + str(r.varint()).encode()
    out += b',"time":' + str(r.varint()).encode()
    out += b"}"
    if r.pos != len(frame):
        raise ValueError("corrupt change frame: trailing bytes")
    return bytes(out)


def decode_change(frame: bytes) -> bytes:
    """Binary frame -> canonical change JSON bytes. Works regardless of
    HM_NATIVE_CODEC (the hatch only stops new frames being WRITTEN and
    routes this through the twin); raises ValueError when malformed."""
    if _use_native():
        raw = native.change_decode(frame)
        if raw is not None:
            return raw
        # fall through: the twin raises the descriptive error
    return _decode_py(frame)
