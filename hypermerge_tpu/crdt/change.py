"""Change/Op data model — the unit of CRDT replication.

Semantic parity target: the Automerge 0.14 change format used by the
reference (SURVEY.md §2.2: change identity = (actor, seq), seq equals feed
length + 1, deps are a vector clock; ops create objects / set keys / insert
list elements). The op model here is redesigned for columnar encoding
(BASELINE.json: `(actor, seq, lamport, ref, action)` int32 arrays):

- Every op has a lamport **counter** (`ctr`); its identity is the OpId
  `(ctr, actor)`. A change's ops get consecutive counters starting at
  `start_op`; `start_op` is assigned by the writer's backend as
  `max_op_seen + 1`, which guarantees any op referencing object/element X
  has ctr > X.ctr (causal lamport property — the device RGA kernel's
  sibling ordering relies on it).
- Supersession is explicit: `pred` lists the OpIds a SET/DEL/MAKE op
  overwrites (observed-remove semantics). A value is *visible* iff no
  applied op names it in `pred`. Concurrent SETs leave multiple visible
  ops = a conflict; display winner is the max OpId.
- List ops address elements by OpId (`ref`); `insert=True` creates a new
  element after `ref` (HEAD for the front). RGA ordering: among elements
  inserted after the same ref, descending OpId order.

Changes are canonically serialized as JSON dicts (wire + feed block format;
block compression lives in storage/block.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

# ---------------------------------------------------------------------------
# identities


class OpId(NamedTuple):
    """Lamport-ordered op identity. Ordering = (ctr, actor) — the conflict
    tie-break used everywhere (host and device kernels must agree).
    A NamedTuple, not a dataclass: OpIds are hashed/compared millions of
    times (opset dict keys, supersession maps) and tuple hash/eq run in
    C — measurably faster on the interactive change path."""

    ctr: int
    actor: str

    def __str__(self) -> str:
        return f"{self.ctr}@{self.actor}"

    @staticmethod
    def parse(s: str) -> "OpId":
        ctr, _, actor = s.partition("@")
        return OpId(int(ctr), actor)


ROOT = OpId(0, "_root")  # the document root map
HEAD = OpId(0, "_head")  # list front sentinel for insert-after


class Action(IntEnum):
    """Op actions. IntEnum values are the device-side action codes
    (ops/columnar.py packs these verbatim into int32 lanes)."""

    MAKE_MAP = 0
    MAKE_LIST = 1
    MAKE_TEXT = 2
    MAKE_TABLE = 3
    SET = 4
    DEL = 5
    INC = 6
    PAD = 7  # device-only padding lane; never appears in a Change

    @property
    def makes_object(self) -> bool:
        return self in (
            Action.MAKE_MAP,
            Action.MAKE_LIST,
            Action.MAKE_TEXT,
            Action.MAKE_TABLE,
        )


OBJ_TYPE_BY_MAKE = {
    Action.MAKE_MAP: "map",
    Action.MAKE_LIST: "list",
    Action.MAKE_TEXT: "text",
    Action.MAKE_TABLE: "table",
}


# ---------------------------------------------------------------------------
# ops & changes (backend/wire form — fully resolved ids)


@dataclass(frozen=True)
class Op:
    action: Action
    obj: OpId  # container object id (ROOT for the root map)
    key: Optional[str] = None  # map/table key (None for list ops)
    ref: Optional[OpId] = None  # list element addressed (HEAD = front)
    insert: bool = False  # True: create new elem after ref
    value: Any = None  # scalar payload (SET/INS) or INC delta
    datatype: Optional[str] = None  # 'counter' | 'timestamp' | None
    pred: Tuple[OpId, ...] = ()  # ops this op supersedes/deletes

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"a": int(self.action), "o": str(self.obj)}
        if self.key is not None:
            d["k"] = self.key
        if self.ref is not None:
            d["r"] = str(self.ref)
        if self.insert:
            d["i"] = True
        if self.value is not None:
            d["v"] = self.value
        if self.datatype is not None:
            d["d"] = self.datatype
        if self.pred:
            d["p"] = [str(p) for p in self.pred]
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Op":
        return Op(
            action=Action(d["a"]),
            obj=OpId.parse(d["o"]),
            key=d.get("k"),
            ref=OpId.parse(d["r"]) if "r" in d else None,
            insert=bool(d.get("i", False)),
            value=d.get("v"),
            datatype=d.get("d"),
            pred=tuple(OpId.parse(p) for p in d.get("p", ())),
        )


@dataclass(frozen=True)
class Change:
    actor: str
    seq: int  # 1-based, == writer feed length + 1 (append-only order)
    start_op: int  # ctr of ops[0]; ops[i].ctr == start_op + i
    deps: Dict[str, int]  # vector clock of causal dependencies (excl. self)
    ops: Tuple[Op, ...]
    time: int = 0
    message: str = ""

    def op_id(self, i: int) -> OpId:
        return OpId(self.start_op + i, self.actor)

    @property
    def max_op(self) -> int:
        return self.start_op + len(self.ops) - 1 if self.ops else self.start_op - 1

    def to_json(self) -> Dict[str, Any]:
        return {
            "actor": self.actor,
            "seq": self.seq,
            "startOp": self.start_op,
            "deps": dict(self.deps),
            "time": self.time,
            "message": self.message,
            "ops": [op.to_json() for op in self.ops],
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Change":
        return Change(
            actor=d["actor"],
            seq=d["seq"],
            start_op=d["startOp"],
            deps=dict(d["deps"]),
            time=d.get("time", 0),
            message=d.get("message", ""),
            ops=tuple(Op.from_json(o) for o in d["ops"]),
        )


# ---------------------------------------------------------------------------
# frontend intents (request form — ids unresolved, assigned by the writer's
# backend at applyLocalChange time, mirroring the reference's
# Frontend.change -> RequestMsg -> Backend.applyLocalChange flow,
# reference src/DocFrontend.ts:137, src/DocBackend.ts:187-205)


@dataclass(frozen=True)
class OpIntent:
    """One user mutation recorded by the change-fn proxy.

    `obj` is either a resolved OpId string (existing object) or a temp id
    `"tmp:<n>"` for objects created earlier in the same change fn. List
    positions are indices into the list as the frontend displayed it.
    """

    action: Action
    obj: str  # OpId str | "tmp:<n>" | "_root"
    key: Optional[str] = None
    index: Optional[int] = None  # list index (for insert: insert-before idx)
    insert: bool = False
    value: Any = None
    datatype: Optional[str] = None
    temp_id: Optional[str] = None  # set for MAKE_*: id used later in the fn

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"a": int(self.action), "o": self.obj}
        for name, v in (
            ("k", self.key),
            ("x", self.index),
            ("v", self.value),
            ("d", self.datatype),
            ("t", self.temp_id),
        ):
            if v is not None:
                d[name] = v
        if self.insert:
            d["i"] = True
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "OpIntent":
        return OpIntent(
            action=Action(d["a"]),
            obj=d["o"],
            key=d.get("k"),
            index=d.get("x"),
            insert=bool(d.get("i", False)),
            value=d.get("v"),
            datatype=d.get("d"),
            temp_id=d.get("t"),
        )


@dataclass(frozen=True)
class ChangeRequest:
    """Frontend -> backend local change request (reference RequestMsg)."""

    actor: str
    seq: int
    time: int
    message: str
    intents: Tuple[OpIntent, ...]

    def to_json(self) -> Dict[str, Any]:
        return {
            "actor": self.actor,
            "seq": self.seq,
            "time": self.time,
            "message": self.message,
            "intents": [i.to_json() for i in self.intents],
        }

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ChangeRequest":
        return ChangeRequest(
            actor=d["actor"],
            seq=d["seq"],
            time=d.get("time", 0),
            message=d.get("message", ""),
            intents=tuple(OpIntent.from_json(i) for i in d["intents"]),
        )
