"""Patch / Diff — what the backend sends the frontend after each apply.

Parity: the reference frontend consumes Automerge patches with `.clock`,
`.deps`, `.diffs` and skips empty-diff patches (reference
src/DocFrontend.ts:157-179). Diffs here are self-contained instructions a
frontend can apply mechanically to its materialized state:

- create: a new object (id, type) came into existence
- set:    map key / list elem now has a value (or link to an object),
          with any concurrent-conflict losers attached
- insert: list gained an element at index (with its stable elem id)
- remove: map key / list elem disappeared

Diffs for one change are ordered so that `create` precedes any `set`/
`insert` linking the created object.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass(frozen=True)
class Conflict:
    """A losing concurrent value at the same location (winner excluded)."""

    op_id: str
    value: Any = None
    link: bool = False
    datatype: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"op": self.op_id}
        if self.value is not None:
            d["v"] = self.value
        if self.link:
            d["l"] = True
        if self.datatype:
            d["d"] = self.datatype
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Conflict":
        return Conflict(d["op"], d.get("v"), bool(d.get("l")), d.get("d"))


@dataclass(frozen=True)
class Diff:
    action: str  # 'create' | 'set' | 'insert' | 'remove'
    obj: str  # container object id ('0@_root' for the root map)
    obj_type: str  # 'map' | 'table' | 'list' | 'text'
    key: Optional[str] = None  # map/table location
    index: Optional[int] = None  # list/text location (live index)
    elem_id: Optional[str] = None  # stable elem identity for list/text
    value: Any = None
    link: bool = False  # value is an object id string
    datatype: Optional[str] = None
    conflicts: tuple = ()  # Tuple[Conflict, ...]

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"ac": self.action, "o": self.obj, "t": self.obj_type}
        if self.key is not None:
            d["k"] = self.key
        if self.index is not None:
            d["x"] = self.index
        if self.elem_id is not None:
            d["e"] = self.elem_id
        if self.value is not None:
            d["v"] = self.value
        if self.link:
            d["l"] = True
        if self.datatype:
            d["d"] = self.datatype
        if self.conflicts:
            d["c"] = [c.to_json() for c in self.conflicts]
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Diff":
        return Diff(
            action=d["ac"],
            obj=d["o"],
            obj_type=d["t"],
            key=d.get("k"),
            index=d.get("x"),
            elem_id=d.get("e"),
            value=d.get("v"),
            link=bool(d.get("l")),
            datatype=d.get("d"),
            conflicts=tuple(Conflict.from_json(c) for c in d.get("c", ())),
        )


@dataclass(frozen=True)
class Patch:
    clock: Dict[str, int]
    deps: Dict[str, int]
    max_op: int
    diffs: tuple  # Tuple[Diff, ...]
    actor: Optional[str] = None  # set for the local-change echo
    seq: Optional[int] = None

    @property
    def is_empty(self) -> bool:
        return not self.diffs

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "clock": dict(self.clock),
            "deps": dict(self.deps),
            "maxOp": self.max_op,
            "diffs": [x.to_json() for x in self.diffs],
        }
        if self.actor is not None:
            d["actor"] = self.actor
        if self.seq is not None:
            d["seq"] = self.seq
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Patch":
        return Patch(
            clock=dict(d["clock"]),
            deps=dict(d["deps"]),
            max_op=d["maxOp"],
            diffs=tuple(Diff.from_json(x) for x in d["diffs"]),
            actor=d.get("actor"),
            seq=d.get("seq"),
        )
