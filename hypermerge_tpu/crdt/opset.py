"""OpSet — the CRDT state machine (host incremental path).

This is the semantic twin of Automerge's Backend as the reference uses it
(SURVEY.md §2.2: Backend.init/applyChanges/applyLocalChange returning
[state', patch]); the interactive O(1)-latency path of the dual-path design
(SURVEY.md §7.3.4). The bulk path — ops/materialize.py — replays the same
changes as one batched XLA program; tests assert both materialize
identically for arbitrary histories.

Semantics:
- Causal order: a change (actor, seq) applies when seq == clock[actor]+1
  and every dep is satisfied; otherwise it parks in a pending set
  (reference DocBackend queues via its remoteChangesQ + syncChanges window).
- Map/table keys and list elements hold a *visible set* of value ops.
  An op's `pred` list removes the ops it supersedes (observed-remove).
  Winner for display = max OpId; the rest surface as conflicts.
- List order: RGA insert-after with descending-OpId sibling order. The
  lamport property (child.ctr > parent.ctr, enforced at change creation)
  makes the sequential skip-scan insertion below equivalent to the
  tree-DFS formulation the device kernel uses.
- Counters: INC ops accumulate on a specific counter value op (`ref`);
  superseding the counter op discards its increments.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from ..models import Counter, Table, Text
from .change import (
    HEAD,
    OBJ_TYPE_BY_MAKE,
    ROOT,
    Action,
    Change,
    ChangeRequest,
    Op,
    OpId,
)
from .patch import Conflict, Diff, Patch

ROOT_STR = str(ROOT)


def resolve_intent(
    intent, opid: OpId, temp_map: Dict[str, OpId], objects_get, live_elems
) -> Optional[Op]:
    """Translate one frontend intent into a concrete Op against the
    current visible state. ONE implementation shared by the host OpSet
    and the live apply engine (backend/live.py) so the HM_LIVE=1/0
    twins cannot drift on local-change resolution — parameterized over
    the state representation: `objects_get(obj_id)` returns an object
    with `.is_sequence` + `.fields` (or None), `live_elems(obj)` its
    live element order."""
    if intent.obj in temp_map:
        obj_id = temp_map[intent.obj]
    elif intent.obj == ROOT_STR or intent.obj == "_root":
        obj_id = ROOT
    elif intent.obj.startswith("tmp:"):
        return None  # references a temp id whose MAKE failed
    else:
        try:
            obj_id = OpId.parse(intent.obj)
        except ValueError:
            return None
    obj = objects_get(obj_id)
    if obj is None:
        return None
    op = build_intent_op(intent, obj_id, obj, live_elems)
    if op is not None and intent.temp_id is not None:
        # register only on success: a failed intent must not alias its
        # temp id onto the OpId the next successful op will consume
        temp_map[intent.temp_id] = opid
    return op


def build_intent_op(intent, obj_id: OpId, obj, live_elems) -> Optional[Op]:
    action = intent.action
    if obj.is_sequence:
        if intent.insert:
            live = live_elems(obj)
            idx = intent.index if intent.index is not None else len(live)
            if idx < 0 or idx > len(live):
                return None
            ref = HEAD if idx == 0 else live[idx - 1]
            return Op(
                action=action,
                obj=obj_id,
                ref=ref,
                insert=True,
                value=intent.value,
                datatype=intent.datatype,
            )
        live = live_elems(obj)
        if intent.index is None or not (0 <= intent.index < len(live)):
            return None
        elem = live[intent.index]
        visible = obj.fields.get(elem, {})
        if action == Action.INC:
            target = max(visible) if visible else None
            if target is None:
                return None
            return Op(
                action=action, obj=obj_id, ref=elem, value=intent.value,
                pred=(target,),
            )
        return Op(
            action=action,
            obj=obj_id,
            ref=elem,
            value=intent.value,
            datatype=intent.datatype,
            pred=tuple(sorted(visible)),
        )
    # map/table
    visible = obj.fields.get(intent.key, {})
    if action == Action.INC:
        target = max(visible) if visible else None
        if target is None:
            return None
        return Op(
            action=action, obj=obj_id, key=intent.key,
            value=intent.value, pred=(target,),
        )
    return Op(
        action=action,
        obj=obj_id,
        key=intent.key,
        value=intent.value,
        datatype=intent.datatype,
        pred=tuple(sorted(visible)),
    )


@dataclass
class _Obj:
    """State of one object (map/table/list/text)."""

    type: str  # 'map' | 'table' | 'list' | 'text'
    # map/table: key -> {OpId: Op}; list/text: elem OpId -> {OpId: Op}
    fields: Dict[Any, Dict[OpId, Op]] = field(default_factory=dict)
    order: List[OpId] = field(default_factory=list)  # list/text: RGA order
    # elem liveness cache: an elem is live iff its visible set is non-empty

    @property
    def is_sequence(self) -> bool:
        return self.type in ("list", "text")


class OpSet:
    def __init__(self) -> None:
        self.objects: Dict[OpId, _Obj] = {ROOT: _Obj("map")}
        self.clock: Dict[str, int] = {}
        self.max_op: int = 0
        self.history: List[Change] = []
        self._history_index: Set[Tuple[str, int]] = set()
        self._pending: List[Change] = []
        self._inc_totals: Dict[OpId, float] = {}

    # ------------------------------------------------------------------
    # public api

    def apply_changes(self, changes: Iterable[Change]) -> Patch:
        """Apply remote/loaded changes in causal order; returns one Patch
        covering everything that became applicable."""
        diffs: List[Diff] = []
        for change in changes:
            self._enqueue(change, diffs)
        self._drain_pending(diffs)
        return self._patch(diffs)

    def apply_local_request(self, req: ChangeRequest) -> Tuple[Change, Patch]:
        """Resolve a frontend ChangeRequest into a fully-identified Change
        (assigning start_op, object ids, refs, preds — the writer-side half
        of Backend.applyLocalChange) and apply it."""
        expected = self.clock.get(req.actor, 0) + 1
        if req.seq != expected:
            raise ValueError(
                f"out-of-order local change: seq {req.seq} != {expected}"
            )
        start_op = self.max_op + 1
        deps = {a: s for a, s in self.clock.items() if a != req.actor}
        temp_map: Dict[str, OpId] = {}
        ops: List[Op] = []
        diffs: List[Diff] = []
        ctr = start_op
        for intent in req.intents:
            op = self._resolve_intent(intent, OpId(ctr, req.actor), temp_map)
            if op is None:
                continue  # unresolvable intent (e.g. index out of range)
            self._apply_op(OpId(ctr, req.actor), op, diffs)
            ops.append(op)
            ctr += 1
        change = Change(
            actor=req.actor,
            seq=req.seq,
            start_op=start_op,
            deps=deps,
            ops=tuple(ops),
            time=req.time,
            message=req.message,
        )
        self._commit(change)
        patch = self._patch(diffs, actor=req.actor, seq=req.seq)
        return change, patch

    def materialize(self) -> Any:
        """Full read of the document as plain Python values."""
        return self._materialize_obj(ROOT)

    def materialize_at(self, n_changes: int) -> Any:
        """Time travel: replay the first n history entries into a fresh
        OpSet (reference MaterializeMsg path, src/RepoBackend.ts:570-579)."""
        sub = OpSet()
        sub.apply_changes(self.history[:n_changes])
        return sub.materialize()

    def snapshot_patch(self) -> Patch:
        """A from-scratch patch reconstructing current state — used for
        DocReady messages to new frontends (reference ReadyMsg carries the
        init patch, src/DocBackend.ts:144-167)."""
        diffs: List[Diff] = []
        self._snapshot_obj(ROOT, diffs)
        return self._patch(diffs)

    def missing_deps(self) -> Dict[str, int]:
        """Smallest clock that would unblock pending changes."""
        need: Dict[str, int] = {}
        for change in self._pending:
            for actor, seq in change.deps.items():
                if self.clock.get(actor, 0) < seq:
                    need[actor] = max(need.get(actor, 0), seq)
            if self.clock.get(change.actor, 0) + 1 < change.seq:
                need[change.actor] = max(
                    need.get(change.actor, 0), change.seq - 1
                )
        return need

    def get_changes_since(self, clock: Dict[str, int]) -> List[Change]:
        return [
            c for c in self.history if c.seq > clock.get(c.actor, 0)
        ]

    # ------------------------------------------------------------------
    # intent resolution (writer side)

    def _resolve_intent(
        self, intent, opid: OpId, temp_map: Dict[str, OpId]
    ) -> Optional[Op]:
        return resolve_intent(
            intent, opid, temp_map, self.objects.get, self._live_elems
        )

    # ------------------------------------------------------------------
    # causal application

    def _enqueue(self, change: Change, diffs: List[Diff]) -> None:
        if (change.actor, change.seq) in self._history_index:
            return  # duplicate
        if self._applicable(change):
            self._apply_change(change, diffs)
        else:
            self._pending.append(change)

    def _drain_pending(self, diffs: List[Diff]) -> None:
        progressed = True
        while progressed and self._pending:
            progressed = False
            still: List[Change] = []
            for change in self._pending:
                if (change.actor, change.seq) in self._history_index:
                    progressed = True
                    continue
                if self._applicable(change):
                    self._apply_change(change, diffs)
                    progressed = True
                else:
                    still.append(change)
            self._pending = still

    def _applicable(self, change: Change) -> bool:
        if change.seq != self.clock.get(change.actor, 0) + 1:
            return False
        return all(
            self.clock.get(a, 0) >= s for a, s in change.deps.items()
        )

    def _apply_change(self, change: Change, diffs: List[Diff]) -> None:
        for i, op in enumerate(change.ops):
            self._apply_op(change.op_id(i), op, diffs)
        self._commit(change)

    def _commit(self, change: Change) -> None:
        self.clock[change.actor] = change.seq
        self.max_op = max(self.max_op, change.max_op)
        self.history.append(change)
        self._history_index.add((change.actor, change.seq))

    # ------------------------------------------------------------------
    # op application

    def _apply_op(self, opid: OpId, op: Op, diffs: List[Diff]) -> None:
        obj = self.objects.get(op.obj)
        if obj is None:
            return  # tolerate ops against unknown objects (corrupt feeds)
        if op.action.makes_object and opid not in self.objects:
            child_type = OBJ_TYPE_BY_MAKE[op.action]
            self.objects[opid] = _Obj(child_type)
            diffs.append(
                Diff(action="create", obj=str(opid), obj_type=child_type)
            )
        if obj.is_sequence:
            self._apply_seq_op(obj, opid, op, diffs)
        else:
            self._apply_map_op(obj, opid, op, diffs)

    def _apply_map_op(self, obj: _Obj, opid: OpId, op: Op, diffs) -> None:
        key = op.key
        if key is None:
            return
        visible = obj.fields.setdefault(key, {})
        had = bool(visible)
        if op.action == Action.INC:
            for p in op.pred:
                if p in visible:
                    self._inc_totals[p] = self._inc_totals.get(p, 0) + (
                        op.value or 0
                    )
        else:
            for p in op.pred:
                removed = visible.pop(p, None)
                if removed is not None:
                    self._inc_totals.pop(p, None)
            if op.action in (Action.SET,) or op.action.makes_object:
                visible[opid] = op
        self._emit_map_diff(obj, op.obj, key, visible, had, diffs)

    def _emit_map_diff(self, obj, obj_id, key, visible, had, diffs) -> None:
        if not visible:
            if had:
                diffs.append(
                    Diff(
                        action="remove",
                        obj=str(obj_id),
                        obj_type=obj.type,
                        key=key,
                    )
                )
            else:
                obj.fields.pop(key, None)
            return
        winner_id = max(visible)
        value, link, datatype = self._op_value(winner_id, visible[winner_id])
        conflicts = tuple(
            Conflict(str(oid), *self._op_value(oid, visible[oid]))
            for oid in sorted(visible, reverse=True)
            if oid != winner_id
        )
        diffs.append(
            Diff(
                action="set",
                obj=str(obj_id),
                obj_type=obj.type,
                key=key,
                value=value,
                link=link,
                datatype=datatype,
                conflicts=conflicts,
            )
        )

    def _apply_seq_op(self, obj: _Obj, opid: OpId, op: Op, diffs) -> None:
        if op.insert:
            # RGA insert-after with descending-OpId skip scan. Causal lamport
            # property guarantees any descendant of a skipped sibling also
            # has a larger OpId, so a flat forward scan is sufficient.
            if op.ref == HEAD:
                pos = 0
            else:
                try:
                    pos = obj.order.index(op.ref) + 1
                except ValueError:
                    return  # unknown predecessor (corrupt / out of order)
            while pos < len(obj.order) and obj.order[pos] > opid:
                pos += 1
            obj.order.insert(pos, opid)
            obj.fields[opid] = {opid: op}
            live_index = self._live_index(obj, opid)
            value, link, datatype = self._op_value(opid, op)
            diffs.append(
                Diff(
                    action="insert",
                    obj=str(op.obj),
                    obj_type=obj.type,
                    index=live_index,
                    elem_id=str(opid),
                    value=value,
                    link=link,
                    datatype=datatype,
                )
            )
            return
        elem = op.ref
        if elem is None or elem not in obj.fields:
            return
        visible = obj.fields[elem]
        had = bool(visible)
        if op.action == Action.INC:
            for p in op.pred:
                if p in visible:
                    self._inc_totals[p] = self._inc_totals.get(p, 0) + (
                        op.value or 0
                    )
        else:
            for p in op.pred:
                removed = visible.pop(p, None)
                if removed is not None:
                    self._inc_totals.pop(p, None)
            if op.action in (Action.SET,) or op.action.makes_object:
                visible[opid] = op
        # emit diff with live index (computed before tombstone collapse)
        if visible:
            live_index = self._live_index(obj, elem)
            winner_id = max(visible)
            value, link, datatype = self._op_value(winner_id, visible[winner_id])
            conflicts = tuple(
                Conflict(str(oid), *self._op_value(oid, visible[oid]))
                for oid in sorted(visible, reverse=True)
                if oid != winner_id
            )
            diffs.append(
                Diff(
                    # a tombstoned element coming back to life (concurrent
                    # set vs delete) is an *insert* from the frontend's
                    # point of view — it removed the elem already
                    action="set" if had else "insert",
                    obj=str(op.obj),
                    obj_type=obj.type,
                    index=live_index,
                    elem_id=str(elem),
                    value=value,
                    link=link,
                    datatype=datatype,
                    conflicts=conflicts,
                )
            )
        elif had:
            live_index = self._live_index_before_removal(obj, elem)
            diffs.append(
                Diff(
                    action="remove",
                    obj=str(op.obj),
                    obj_type=obj.type,
                    index=live_index,
                    elem_id=str(elem),
                )
            )

    # ------------------------------------------------------------------
    # reads

    def _op_value(self, opid: OpId, op: Op):
        """-> (value, link, datatype) for a visible value op."""
        if op.action.makes_object:
            return str(opid), True, None
        if op.datatype == "counter":
            base = op.value or 0
            return base + self._inc_totals.get(opid, 0), False, "counter"
        return op.value, False, op.datatype

    def _live_elems(self, obj: _Obj) -> List[OpId]:
        return [e for e in obj.order if obj.fields.get(e)]

    def _live_index(self, obj: _Obj, elem: OpId) -> int:
        idx = 0
        for e in obj.order:
            if e == elem:
                return idx
            if obj.fields.get(e):
                idx += 1
        return idx

    def _live_index_before_removal(self, obj: _Obj, elem: OpId) -> int:
        # elem just became a tombstone; its live index is the count of live
        # elems before it
        return self._live_index(obj, elem)

    def _materialize_obj(self, obj_id: OpId) -> Any:
        obj = self.objects[obj_id]
        if obj.is_sequence:
            values = []
            for elem in obj.order:
                visible = obj.fields.get(elem)
                if not visible:
                    continue
                winner = max(visible)
                values.append(self._materialize_value(winner, visible[winner]))
            if obj.type == "text":
                return Text([str(v) for v in values])
            return values
        data = {}
        for key, visible in obj.fields.items():
            if not visible:
                continue
            winner = max(visible)
            data[key] = self._materialize_value(winner, visible[winner])
        if obj.type == "table":
            return Table(data)
        return data

    def _materialize_value(self, opid: OpId, op: Op) -> Any:
        if op.action.makes_object:
            return self._materialize_obj(opid)
        value, _, datatype = self._op_value(opid, op)
        if datatype == "counter":
            return Counter(value)
        return value

    def _snapshot_obj(self, obj_id: OpId, diffs: List[Diff]) -> None:
        obj = self.objects[obj_id]
        if obj_id != ROOT:
            diffs.append(
                Diff(action="create", obj=str(obj_id), obj_type=obj.type)
            )
        if obj.is_sequence:
            index = 0
            for elem in obj.order:
                visible = obj.fields.get(elem)
                if not visible:
                    continue
                winner = max(visible)
                op = visible[winner]
                if op.action.makes_object:
                    self._snapshot_obj(winner, diffs)
                value, link, datatype = self._op_value(winner, op)
                conflicts = tuple(
                    Conflict(str(oid), *self._op_value(oid, visible[oid]))
                    for oid in sorted(visible, reverse=True)
                    if oid != winner
                )
                diffs.append(
                    Diff(
                        action="insert",
                        obj=str(obj_id),
                        obj_type=obj.type,
                        index=index,
                        elem_id=str(elem),
                        value=value,
                        link=link,
                        datatype=datatype,
                        conflicts=conflicts,
                    )
                )
                index += 1
        else:
            for key in sorted(obj.fields):
                visible = obj.fields[key]
                if not visible:
                    continue
                winner = max(visible)
                op = visible[winner]
                if op.action.makes_object:
                    self._snapshot_obj(winner, diffs)
                value, link, datatype = self._op_value(winner, op)
                conflicts = tuple(
                    Conflict(str(oid), *self._op_value(oid, visible[oid]))
                    for oid in sorted(visible, reverse=True)
                    if oid != winner
                )
                diffs.append(
                    Diff(
                        action="set",
                        obj=str(obj_id),
                        obj_type=obj.type,
                        key=key,
                        value=value,
                        link=link,
                        datatype=datatype,
                        conflicts=conflicts,
                    )
                )

    def _patch(self, diffs, actor=None, seq=None) -> Patch:
        return Patch(
            clock=dict(self.clock),
            deps=dict(self.clock),
            max_op=self.max_op,
            diffs=tuple(diffs),
            actor=actor,
            seq=seq,
        )
