"""Vector-clock algebra over {actor_id: seq} maps — pure functions.

Maps reference src/Clock.ts:3-113: cmp (GT/LT/CONCUR/EQ), gte, union,
intersection, addTo, equivalent, and the strs wire codec (`"<actor>:<seq>"`
strings, seq omitted when infinite). These are the host-side scalar twins of
the batched device kernels in ops/clock_kernels.py; both must agree — see
tests/test_clock.py truth tables (mirroring reference tests/unit.test.ts).
"""

from __future__ import annotations

import enum
import math
from typing import Dict, Iterable, List, Tuple

Clock = Dict[str, int]  # actor id -> seq (may be math.inf for cursors)

INFINITY_SEQ = 2**53 - 1  # matches reference CursorStore INFINITY_SEQ


class Ordering(enum.Enum):
    GT = "GT"
    LT = "LT"
    CONCUR = "CONCUR"
    EQ = "EQ"


def _norm(seq: float) -> float:
    """math.inf and INFINITY_SEQ both mean 'infinite' — compare them equal."""
    return INFINITY_SEQ if seq >= INFINITY_SEQ else seq


def gte(a: Clock, b: Clock) -> bool:
    """True iff a dominates b: every actor's seq in b is <= its seq in a."""
    return all(_norm(a.get(actor, 0)) >= _norm(seq) for actor, seq in b.items())


def cmp(a: Clock, b: Clock) -> Ordering:
    a_gte = gte(a, b)
    b_gte = gte(b, a)
    if a_gte and b_gte:
        return Ordering.EQ
    if a_gte:
        return Ordering.GT
    if b_gte:
        return Ordering.LT
    return Ordering.CONCUR


def equivalent(a: Clock, b: Clock) -> bool:
    return cmp(a, b) is Ordering.EQ


def union(a: Clock, b: Clock) -> Clock:
    out = dict(a)
    for actor, seq in b.items():
        out[actor] = max(out.get(actor, 0), seq)
    return out


def intersection(a: Clock, b: Clock) -> Clock:
    out: Clock = {}
    for actor, seq in a.items():
        if actor in b:
            m = min(seq, b[actor])
            if m > 0:
                out[actor] = m
    return out


def add_to(acc: Clock, other: Clock) -> None:
    """In-place union (reference Clock.addTo)."""
    for actor, seq in other.items():
        if acc.get(actor, 0) < seq:
            acc[actor] = seq


def clock_to_strs(clock: Clock) -> List[str]:
    """Wire codec: `"<actor>"` for infinite seq, `"<actor>:<seq>"` otherwise
    (reference src/Clock.ts:40-66)."""
    out = []
    for actor, seq in sorted(clock.items()):
        if seq == math.inf or seq >= INFINITY_SEQ:
            out.append(actor)
        else:
            out.append(f"{actor}:{int(seq)}")
    return out


def strs_to_clock(strs: Iterable[str]) -> Clock:
    clock: Clock = {}
    for s in strs:
        actor, sep, seq = s.partition(":")
        clock[actor] = int(seq) if sep else INFINITY_SEQ
    return clock


def actor_axis(clocks: Iterable[Clock]) -> List[str]:
    """Stable union of actor ids across clocks — the dense actor axis used
    when packing clocks into device matrices."""
    seen: Dict[str, None] = {}
    for clock in clocks:
        for actor in clock:
            seen.setdefault(actor)
    return sorted(seen)


def pack(clocks: List[Clock], actors: List[str]) -> List[List[int]]:
    """Dense [n_clocks, n_actors] int rows (host-side; ops/clock_kernels.py
    turns these into device arrays)."""
    index = {a: i for i, a in enumerate(actors)}
    rows = []
    for clock in clocks:
        row = [0] * len(actors)
        for actor, seq in clock.items():
            row[index[actor]] = int(min(seq, INFINITY_SEQ))  # inf-safe clamp
        rows.append(row)
    return rows


def unpack(rows: List[List[int]], actors: List[str]) -> List[Clock]:
    return [
        {actors[i]: int(seq) for i, seq in enumerate(row) if seq > 0}
        for row in rows
    ]
