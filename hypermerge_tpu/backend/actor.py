"""Actor — binds one feed to an in-memory Change list.

Parity: reference src/Actor.ts:44-142 — writes local changes as packed
blocks (seq continuity asserted against feed length), parses downloaded
blocks back into changes, and emits lifecycle events
(ActorInitialized / ActorSync / Download) to the RepoBackend hub.

TPU-first deltas from the reference:
- Block decode is **lazy**: opening an actor does not JSON-decode its
  feed (the reference parses every block on feed ready,
  src/Actor.ts:105-117). The interactive path decodes on first access;
  the bulk cold-start path never decodes at all — it reads the columnar
  sidecar via `columns()`.
- The actor maintains the feed's columnar cache (storage/colcache.py)
  on every append, local or replicated, so cold starts stay vectorized.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..analysis.lockdep import make_rlock
from ..crdt.change import Change
from ..storage import block as blockmod
from ..storage.colcache import (
    FeedColumnCache,
    FeedColumns,
    MemoryColumnStorage,
)
from ..storage.feed import Feed
from ..utils.debug import log

_UNSET = object()  # block present but not yet decoded


class Actor:
    def __init__(
        self,
        feed: Feed,
        notify: Callable[[Dict[str, Any]], None],
        defer_cache: Optional[Callable[["Actor"], None]] = None,
    ) -> None:
        self.id = feed.public_key
        self.feed = feed
        self._notify = notify
        # when set, per-append sidecar encoding moves OFF the write's
        # critical path: defer_cache(self) schedules a debounced
        # sync_cache() instead (the sidecar is derived data — columns()
        # catches up on demand, and blocks rebuild it after a crash)
        self._defer_cache = defer_cache
        self._lock = make_rlock("actor")
        # slot per feed block: _UNSET until decoded; None = corrupt.
        # Lazily sized — feed.length forces the block-log scan, which a
        # bulk cold open wants in its parallel prefetch, not in the
        # serial actor-creation loop.
        self._changes: Optional[List[Any]] = None
        self._colcache: FeedColumnCache = feed.colcache or FeedColumnCache(
            MemoryColumnStorage(), writer=self.id
        )
        feed.on_append(self._on_append)
        feed.on_extended(self._on_extended)
        self._pending_dl = [0, 0.0]  # bytes, ms since last Download event
        self._notify({"type": "ActorInitialized", "actor": self})
        self._notify({"type": "ActorSync", "actor": self, "origin": "init"})

    @property
    def writable(self) -> bool:
        return self.feed.writable

    @property
    def changes(self) -> List[Any]:
        """Slot list sized to the feed's block log, re-checked on EVERY
        read, not just first touch: append_verified fires its listener
        callbacks outside the feed lock, so two concurrent backfill
        batches (multi-source repair after churn) can deliver
        _on_append out of order or drop a callback mid-fan-out. A slot
        list that only grew one-per-callback would stay short forever,
        and every reader that trusts len(changes) — seq_head,
        changes_in_window, the sidecar sync — would clamp to the stale
        head and never serve the tail blocks the feed already holds.
        The block log is authoritative; slots decode lazily from it."""
        n = self.feed.length
        if self._changes is None:
            self._changes = [_UNSET] * n
        elif len(self._changes) < n:
            self._changes.extend([_UNSET] * (n - len(self._changes)))
        return self._changes

    @property
    def seq_head(self) -> int:
        with self._lock:
            return len(self.changes)

    def _get_change(self, index: int) -> Optional[Change]:
        c = self.changes[index]
        if c is _UNSET:
            c = self._parse_block(self.feed.get(index), index)
            self.changes[index] = c
        return c

    def _parse_block(self, data: bytes, index: int) -> Optional[Change]:
        try:
            return Change.from_json(blockmod.unpack(data))
        except (ValueError, KeyError, TypeError) as e:
            log("repo:actor", f"corrupt block {index} in {self.id[:6]}: {e}")
            return None

    def write_change(self, change: Change) -> None:
        """Append a locally-generated change; seq must equal feed length+1
        (per-actor total order invariant, reference src/Actor.ts:73-80)."""
        with self._lock:
            head = len(self.changes)
            if change.seq != head + 1:
                log(
                    "repo:actor",
                    f"seq mismatch on {self.id[:6]}: "
                    f"{change.seq} != {head + 1}",
                )
                return
            self.changes.append(change)
            try:
                self.feed.append(blockmod.pack_change(change.to_json()))
            except BaseException:
                # ENOSPC/EIO mid-append: if the block never landed on
                # the feed (storage only advances on success), the
                # in-memory change list must not run ahead either — a
                # phantom entry would break seq continuity for every
                # later write and push the sidecar ahead of the block
                # log. (If the failure struck AFTER the block landed —
                # e.g. a listener — memory and disk already agree.)
                if self.feed.length < len(self.changes):
                    self.changes.pop()
                raise
            if self._defer_cache is None:
                self._sync_cache_locked()
        if self._defer_cache is not None:
            self._defer_cache(self)
        # local writes don't re-notify sync: the doc already applied it

    def _on_append(self, index: int, data: bytes) -> None:
        t0 = time.perf_counter()
        with self._lock:
            # the property sizes to the feed head, which already counts
            # this block; a callback racing ahead of a batch that
            # appended earlier indices (listeners fire outside the feed
            # lock) still lands in bounds
            cs = self.changes
            if len(cs) <= index:
                cs.extend([_UNSET] * (index + 1 - len(cs)))
            if cs[index] is not _UNSET:
                return  # our own write_change already recorded it
            cs[index] = self._parse_block(data, index)
            if self._defer_cache is None:
                self._sync_cache_locked()
            self._pending_dl[0] += len(data)
            self._pending_dl[1] += (time.perf_counter() - t0) * 1e3
        if self._defer_cache is not None:
            self._defer_cache(self)
        self._notify(
            {"type": "ActorSync", "actor": self, "origin": "append"}
        )

    def _on_extended(self, start: int, end: int) -> None:
        """Every non-local extension is a replicated download: one
        progress event per network chunk (reference hypercore 'download'
        -> ActorBlockDownloadedMsg, src/Actor.ts:120-126 — but chunk-
        granular, so a 100k-block backfill is not 100k doc lookups)."""
        with self._lock:
            size, ms = self._pending_dl
            self._pending_dl = [0, 0.0]
        if size == 0:
            return  # our own write_change (no parse happened)
        self._notify(
            {
                "type": "Download",
                "actor": self,
                "index": end - 1,
                "size": size,
                "time": ms,
            }
        )

    def _sync_cache_locked(self) -> None:
        """Bring the columnar sidecar up to the feed head (decodes only
        the blocks the cache is missing — a fresh cache over an existing
        feed rebuilds here). A sidecar AHEAD of the feed (feed file
        replaced or torn-tail-truncated after the sidecar committed) is
        never trusted: blocks are the source of truth, so the cache is
        discarded and rebuilt from them."""
        cc = self._colcache
        n = cc.n_changes
        head = len(self.changes)
        if n > head:
            log(
                "repo:actor",
                f"colcache ahead of feed {self.id[:6]} "
                f"({n} > {head}): rebuilding from blocks",
            )
            cc.reset()
            n = 0
        for i in range(n, head):
            cc.append_change(self._get_change(i))

    def sync_cache(self) -> None:
        """Catch the columnar sidecar up to the feed head (the deferred
        flush target; idempotent)."""
        with self._lock:
            self._sync_cache_locked()

    def columns(self) -> FeedColumns:
        """The feed as columnar arrays (the bulk cold-start input); the
        sidecar is caught up first if stale."""
        with self._lock:
            self._sync_cache_locked()
            return self._colcache.columns()

    def changes_in_window(
        self, start_seq: int, end_seq: float
    ) -> List[Change]:
        """Changes with seq in (start_seq, end_seq] — the syncChanges
        window (reference src/RepoBackend.ts:513-522). seqs are 1-based;
        change at list index i has seq i+1."""
        with self._lock:
            end = min(len(self.changes), int(min(end_seq, len(self.changes))))
            return [
                c
                for c in (
                    self._get_change(i) for i in range(start_seq, end)
                )
                if c is not None
            ]

    def close(self) -> None:
        pass
