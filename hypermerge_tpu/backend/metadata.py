"""Metadata ledger: a durable feed of file-metadata entries.

Parity: reference src/Metadata.ts:125-262 — a dedicated "ledger" feed
(keypair persisted in the KeyStore, like `self.repo` at
src/RepoBackend.ts:92) whose entries record hyperfile metadata
(bytes, mimeType). Entries are written through (append to the feed,
then apply in-memory, src/Metadata.ts:178-192); on open the ledger is
replayed, skipping corrupt entries rather than failing
(src/Metadata.ts:160-170, src/JsonBuffer.ts:11-22).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..storage.feed import FeedStore
from ..utils import json_buffer
from ..utils.ids import url_to_id


class Metadata:
    LEDGER_KEY_NAME = "self.ledger"

    def __init__(self, feeds: FeedStore, key_store) -> None:
        pair = key_store.get_or_create(self.LEDGER_KEY_NAME)
        self.ledger = feeds.create(pair)
        self.files: Dict[str, dict] = {}
        self._load_ledger()

    def _load_ledger(self) -> None:
        for entry in json_buffer.parse_all_valid(self.ledger.read_all()):
            self._apply(entry)

    def _apply(self, entry: dict) -> None:
        if not isinstance(entry, dict):
            return
        if entry.get("type") == "File" and "fileId" in entry:
            self.files[entry["fileId"]] = {
                "type": "File",
                "bytes": entry.get("bytes", 0),
                "mimeType": entry.get("mimeType", "application/octet-stream"),
            }

    def add_file(self, url: str, size: int, mime_type: str) -> None:
        """Write-through: durable first, then visible. Re-announcing a
        fileId the ledger already holds with identical metadata is not
        re-appended. (Uploads mint a fresh keypair per file, so this
        guards direct re-announcement of a known id, not content-level
        dedup of identical blobs.)"""
        file_id = url_to_id(url)
        entry = {
            "type": "File",
            "fileId": file_id,
            "bytes": size,
            "mimeType": mime_type,
        }
        existing = self.files.get(file_id)
        if existing is not None and (
            existing.get("bytes") == size
            and existing.get("mimeType") == mime_type
        ):
            return
        self.ledger.append(json_buffer.bufferify(entry))
        self._apply(entry)

    def is_file(self, id_: str) -> bool:
        return id_ in self.files

    def file_metadata(self, id_: str) -> Optional[dict]:
        entry = self.files.get(id_)
        return dict(entry) if entry is not None else None
