"""Backend layer: CRDT compute + orchestration (SURVEY.md §1.3)."""
