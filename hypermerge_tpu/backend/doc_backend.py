"""DocBackend — per-document CRDT state holder.

Parity: reference src/DocBackend.ts:46-213 — wraps the CRDT engine
(here: crdt.opset.OpSet), serializes local/remote change application
through single-subscriber queues, tracks the clock and the minimumClock
render gate (don't surface a doc until we've caught up to what peers said
exists, reference src/DocBackend.ts:90-113), and notifies the RepoBackend
hub of Ready/LocalPatch/RemotePatch events.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..analysis.lockdep import make_rlock
from ..crdt import clock as clockmod
from ..crdt.change import Change, ChangeRequest
from ..crdt.opset import OpSet
from ..utils.debug import bench, log
from ..utils.queue import Queue
from . import emission
from .emission import EmissionDomain


class DocBackend:
    def __init__(
        self,
        doc_id: str,
        notify: Callable[[Dict[str, Any]], None],
        opset: Optional[OpSet] = None,
        live=None,
    ) -> None:
        self.id = doc_id
        self._notify = notify
        # which of this class's fields the doc lock guards — and which
        # reads are declared GIL-atomic snapshots (opset/_announced/
        # actor_id) — is manifest data now: analysis/guards.py, checked
        # statically (guarded-attr) and at runtime (HM_RACEDEP=1)
        self._lock = make_rlock("doc")
        # THE doc's emission ordering domain (`doc.emit`,
        # backend/emission.py): every {compute patch -> feed append ->
        # push} pair of THIS doc — live ticks, local echoes, Ready
        # snapshots, the HM_LIVE=0 host path — holds it, and nothing
        # else's. A Ready snapshot can never be overtaken by a patch
        # for a NEWER state of this doc (a pending frontend drops
        # pre-Ready patches), while DISJOINT docs emit (and commit
        # durably) in parallel. Re-entrant for in-process frontends
        # whose on_patch synchronously sends the next change to the
        # SAME doc; cross-doc re-entry defers (emission.defer).
        self.emission = EmissionDomain(doc_id)
        self.opset: Optional[OpSet] = opset
        # live apply engine (backend/live.py): lazy docs' incremental
        # changes batch through per-tick kernel dispatches instead of
        # reconstructing a host OpSet. None = host path (HM_LIVE=0).
        self._live = live
        self._live_adopted = False
        self.actor_id: Optional[str] = None
        # deferred-init state (bulk cold start, repo_backend
        # load_documents_bulk): readiness/clock/snapshot served without a
        # host OpSet; the OpSet reconstructs lazily on first change
        self._lazy_loader: Optional[Callable[[], List[Change]]] = None
        self._lazy_clock: Optional[clockmod.Clock] = None
        self._lazy_len = 0
        self._snapshot_fn: Optional[Callable[[], Any]] = None
        self._snapshot_cache: Optional[Any] = None
        # (serving clock, OpSet) memo for the time-travel replay of a
        # live-adopted doc — scrubbing a history slider must not pay a
        # full feed replay per step
        self._replay_cache: Optional[tuple] = None
        self.ready = Queue(f"doc:{doc_id[:6]}:ready")
        self._announced = False
        self.minimum_clock: Optional[clockmod.Clock] = None
        self.local_q: Queue = Queue(f"doc:{doc_id[:6]}:local")
        self.remote_q: Queue = Queue(f"doc:{doc_id[:6]}:remote")
        self.local_q.subscribe(self._handle_local)
        self.remote_q.subscribe(self._handle_remote)
        if opset is not None:
            self._check_ready()

    # ------------------------------------------------------------------

    @property
    def can_apply(self) -> bool:
        """True once the doc can absorb changes — either a live OpSet or
        the deferred-init state (which reconstructs one on demand)."""
        with self._lock:
            return self.opset is not None or self._lazy_loader is not None

    @property
    def clock(self) -> clockmod.Clock:
        with self._lock:
            if self.opset is not None:
                return dict(self.opset.clock)
            if self._lazy_clock is not None:
                return dict(self._lazy_clock)
            return {}

    @property
    def history_len(self) -> int:
        with self._lock:
            if self.opset is not None:
                return len(self.opset.history)
            return self._lazy_len

    def init(self, changes: List[Change], actor_id: Optional[str]) -> None:
        """Cold-start materialization (reference DocBackend.init — the
        north-star hot loop's per-doc endpoint)."""
        with self._lock:
            if self.opset is None:
                self.opset = OpSet()
            with bench(f"doc:init"):
                self.opset.apply_changes(changes)
            if actor_id is not None:
                self.actor_id = actor_id
        self._check_ready()

    def init_deferred(
        self,
        loader: Callable[[], List[Change]],
        clock: clockmod.Clock,
        history_len: int,
        actor_id: Optional[str],
        snapshot_fn: Callable[[], Any],
        quiet: bool = True,
    ) -> None:
        """Bulk cold start: the device already materialized this doc, so
        readiness, clock, and the Ready snapshot serve without replaying
        the history through the host OpSet. The OpSet reconstructs
        lazily (via `loader`) the first time an incremental change needs
        it — the dual-path seam of SURVEY.md §7.3 item 4."""
        with self._lock:
            if self.opset is not None:
                return  # raced with a normal init: host state wins
            self._lazy_loader = loader
            self._lazy_clock = dict(clock)
            self._lazy_len = history_len
            self._snapshot_fn = snapshot_fn
            if actor_id is not None:
                self.actor_id = actor_id
        self._check_ready(quiet=quiet)

    def _ensure_opset(self) -> None:
        """Reconstruct the host OpSet from feed history (lazy path) —
        only up to the clock this doc has been SERVING: the loader's
        cursor window may already include newer replicated changes, and
        folding those into the replay would make the caller's incremental
        apply a no-op (empty patch -> the frontend never hears about
        them). The newer changes re-arrive through the caller's window
        and produce a real patch."""
        with self._lock:
            if self.opset is not None:
                return
            if self._live_adopted:
                return  # the live engine owns this doc's state
            self.opset = OpSet()
            loader, self._lazy_loader = self._lazy_loader, None
            base_clock, self._lazy_clock = self._lazy_clock, None
            self._snapshot_fn = None
            self._snapshot_cache = None
            self._replay_cache = None
            if loader is not None:
                with bench("doc:lazyReplay"):
                    changes = loader()
                    if base_clock is not None:
                        changes = [
                            c
                            for c in changes
                            if c.seq <= base_clock.get(c.actor, 0)
                        ]
                    self.opset.apply_changes(changes)

    def demote_from_live(
        self,
        clock: clockmod.Clock,
        history_len: int,
        snapshot_fn: Callable[[], Any],
    ) -> None:
        """The live engine demoted this doc back to the lazy path (the
        byte-bounded LRU, backend/live.py): the engine's clock/length
        become the lazy serving state, and every cached artifact of the
        OLD state (bulk-load snapshot, replay memo) is dropped — the
        doc may have changed since they were computed. `snapshot_fn`
        rebuilds a CURRENT Ready/reopen snapshot from the sidecars on
        demand. The lazy loader stays, so the next live change
        re-adopts."""
        with self._lock:
            self._live_adopted = False
            self._lazy_clock = dict(clock)
            self._lazy_len = history_len
            self._snapshot_cache = None
            self._snapshot_fn = snapshot_fn
            self._replay_cache = None

    def set_actor_id(self, actor_id: str) -> None:
        with self._lock:
            self.actor_id = actor_id
        if self._announced:
            self._notify(
                {"type": "ActorId", "doc": self, "actorId": actor_id}
            )

    def apply_remote_changes(self, changes: List[Change]) -> None:
        # cross-doc re-entry guard: a frontend callback running under
        # ANOTHER doc's emission domain must not drag that domain into
        # this doc's handler (no two domains on one thread — the
        # write-plane invariant); the push replays on the deferred-
        # emission worker instead
        if emission.entered_other(self.id):
            items = list(changes)
            emission.defer(lambda: self.remote_q.push(items))
            return
        self.remote_q.push(list(changes))

    def apply_local_request(self, req: ChangeRequest) -> None:
        if emission.entered_other(self.id):
            emission.defer(lambda: self.local_q.push(req))
            return
        self.local_q.push(req)

    def update_minimum_clock(self, clock: clockmod.Clock) -> None:
        """Gate first render until we've caught up to this clock
        (reference updateMinimumClock/testMinimumClockSatisfied)."""
        with self._lock:
            if self._announced:
                return
            self.minimum_clock = clockmod.union(
                self.minimum_clock or {}, clock
            )
        self._check_ready()

    def _replay_opset(self) -> Optional[OpSet]:
        """An OpSet view for the explicit history / time-travel APIs.
        Live-adopted docs build a TEMPORARY replay from the feeds (the
        live engine owns the incremental state; host OpSet
        reconstruction remains only behind these APIs); other lazy docs
        install their OpSet as before."""
        with self._lock:
            if self.opset is not None:
                return self.opset
            if self._live_adopted:
                loader = self._lazy_loader
                base_clock = dict(self._lazy_clock or {})
                cached = self._replay_cache
                if cached is not None and cached[0] == base_clock:
                    return cached[1]
                sub = OpSet()
                if loader is not None:
                    with bench("doc:historyReplay"):
                        sub.apply_changes(
                            [
                                c
                                for c in loader()
                                if c.seq <= base_clock.get(c.actor, 0)
                            ]
                        )
                self._replay_cache = (base_clock, sub)
                return sub
            if self._lazy_loader is None:
                return None
            self._ensure_opset()
            return self.opset

    def materialize_at(self, n: int):
        with self._lock:
            opset = self._replay_opset()
            if opset is None:
                return None
            return opset.materialize_at(n)

    def history_patch(self, n: int):
        """Snapshot patch of the first n history changes (time travel;
        reconstructs the OpSet if this doc was bulk-loaded)."""
        with self._lock:
            opset = self._replay_opset()
            if opset is None:
                return None
            sub = OpSet()
            sub.apply_changes(opset.history[:n])
            return sub.snapshot_patch()

    def snapshot_patch(self):
        live = self._live
        with self._lock:
            adopted = self._live_adopted
        if adopted and live is not None:
            # the emission domain (doc.emit) ranks above the doc lock
            # in the declared hierarchy (analysis/hierarchy.py): never
            # call in with the doc lock held
            patch = live.snapshot_patch(self)
            if patch is not None:
                return patch
        with self._lock:
            if self.opset is not None:
                return self.opset.snapshot_patch()
            if self._snapshot_cache is not None:
                return self._snapshot_cache
            if self._snapshot_fn is not None:
                # Decode once and drop the closure: a bulk-load snapshot_fn
                # pins its slab's device/host lanes, which must not outlive
                # the first Ready it serves (the clock can't move while the
                # doc is still lazy, so the decoded Patch stays valid).
                fn, self._snapshot_fn = self._snapshot_fn, None
                self._snapshot_cache = fn()
                return self._snapshot_cache
            return None

    # ------------------------------------------------------------------

    def _minimum_satisfied(self) -> bool:
        # REQUIRES doc (analysis/guards.py): _check_ready calls in
        # under the doc lock
        if self.opset is None and self._lazy_clock is None:
            return False
        if self.minimum_clock is None:
            return True
        return clockmod.gte(self.clock, self.minimum_clock)

    def _check_ready(self, quiet: bool = False) -> None:
        with self._lock:
            if self._announced or not self._minimum_satisfied():
                return
            self._announced = True
        log("doc:back", self.id[:6], "ready")
        self._notify(
            {"type": "DocReadyQuiet" if quiet else "DocReady", "doc": self}
        )
        self.ready.push(True)

    def _handle_local(self, req: ChangeRequest) -> None:
        live = self._live
        if live is not None and self.opset is None:
            # lazy doc on the live path: resolve against the engine's
            # decoded state — no host OpSet reconstruction. The notify
            # runs inside THIS doc's emission domain (emit=) so the
            # echo patch (feed append included) reaches the frontend
            # queue before any tick's delta on the post-change state.
            def emit(change, patch):
                self._notify(
                    {
                        "type": "LocalPatch",
                        "doc": self,
                        "change": change,
                        "patch": patch,
                    }
                )

            try:
                res = live.apply_local(self, req, emit=emit)
            except ValueError as e:
                log("doc:back", "rejected local change:", e)
                return
            if res is not None:
                self._check_ready()
                return
        with self.emission:
            with self._lock:
                if self.opset is None:
                    self._ensure_opset()
                with bench("doc:applyLocalChange"):
                    try:
                        change, patch = self.opset.apply_local_request(req)
                    except ValueError as e:
                        log("doc:back", "rejected local change:", e)
                        return
            self._notify(
                {
                    "type": "LocalPatch",
                    "doc": self,
                    "change": change,
                    "patch": patch,
                }
            )
        self._check_ready()

    def _handle_remote(self, changes: List[Change]) -> None:
        live = self._live
        if live is not None and self.opset is None:
            # lazy doc on the live path: changes coalesce into the next
            # tick's batched kernel dispatch (backend/live.py); the
            # engine emits the RemotePatch + readiness itself
            if live.submit_remote(self, changes):
                return
        with self.emission:
            with self._lock:
                if self.opset is None:
                    self._ensure_opset()
                with bench("doc:applyRemoteChanges"):
                    patch = self.opset.apply_changes(changes)
            if self._announced and not patch.is_empty:
                self._notify(
                    {"type": "RemotePatch", "doc": self, "patch": patch}
                )
        self._check_ready()
