"""DocBackend — per-document CRDT state holder.

Parity: reference src/DocBackend.ts:46-213 — wraps the CRDT engine
(here: crdt.opset.OpSet), serializes local/remote change application
through single-subscriber queues, tracks the clock and the minimumClock
render gate (don't surface a doc until we've caught up to what peers said
exists, reference src/DocBackend.ts:90-113), and notifies the RepoBackend
hub of Ready/LocalPatch/RemotePatch events.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..crdt import clock as clockmod
from ..crdt.change import Change, ChangeRequest
from ..crdt.opset import OpSet
from ..utils.debug import bench, log
from ..utils.queue import Queue


class DocBackend:
    def __init__(
        self,
        doc_id: str,
        notify: Callable[[Dict[str, Any]], None],
        opset: Optional[OpSet] = None,
    ) -> None:
        self.id = doc_id
        self._notify = notify
        self._lock = threading.RLock()
        self.opset: Optional[OpSet] = opset
        self.actor_id: Optional[str] = None
        # deferred-init state (bulk cold start, repo_backend
        # load_documents_bulk): readiness/clock/snapshot served without a
        # host OpSet; the OpSet reconstructs lazily on first change
        self._lazy_loader: Optional[Callable[[], List[Change]]] = None
        self._lazy_clock: Optional[clockmod.Clock] = None
        self._lazy_len = 0
        self._snapshot_fn: Optional[Callable[[], Any]] = None
        self._snapshot_cache: Optional[Any] = None
        self.ready = Queue(f"doc:{doc_id[:6]}:ready")
        self._announced = False
        self.minimum_clock: Optional[clockmod.Clock] = None
        self.local_q: Queue = Queue(f"doc:{doc_id[:6]}:local")
        self.remote_q: Queue = Queue(f"doc:{doc_id[:6]}:remote")
        self.local_q.subscribe(self._handle_local)
        self.remote_q.subscribe(self._handle_remote)
        if opset is not None:
            self._check_ready()

    # ------------------------------------------------------------------

    @property
    def can_apply(self) -> bool:
        """True once the doc can absorb changes — either a live OpSet or
        the deferred-init state (which reconstructs one on demand)."""
        with self._lock:
            return self.opset is not None or self._lazy_loader is not None

    @property
    def clock(self) -> clockmod.Clock:
        with self._lock:
            if self.opset is not None:
                return dict(self.opset.clock)
            if self._lazy_clock is not None:
                return dict(self._lazy_clock)
            return {}

    @property
    def history_len(self) -> int:
        with self._lock:
            if self.opset is not None:
                return len(self.opset.history)
            return self._lazy_len

    def init(self, changes: List[Change], actor_id: Optional[str]) -> None:
        """Cold-start materialization (reference DocBackend.init — the
        north-star hot loop's per-doc endpoint)."""
        with self._lock:
            if self.opset is None:
                self.opset = OpSet()
            with bench(f"doc:init"):
                self.opset.apply_changes(changes)
            if actor_id is not None:
                self.actor_id = actor_id
        self._check_ready()

    def init_deferred(
        self,
        loader: Callable[[], List[Change]],
        clock: clockmod.Clock,
        history_len: int,
        actor_id: Optional[str],
        snapshot_fn: Callable[[], Any],
        quiet: bool = True,
    ) -> None:
        """Bulk cold start: the device already materialized this doc, so
        readiness, clock, and the Ready snapshot serve without replaying
        the history through the host OpSet. The OpSet reconstructs
        lazily (via `loader`) the first time an incremental change needs
        it — the dual-path seam of SURVEY.md §7.3 item 4."""
        with self._lock:
            if self.opset is not None:
                return  # raced with a normal init: host state wins
            self._lazy_loader = loader
            self._lazy_clock = dict(clock)
            self._lazy_len = history_len
            self._snapshot_fn = snapshot_fn
            if actor_id is not None:
                self.actor_id = actor_id
        self._check_ready(quiet=quiet)

    def _ensure_opset(self) -> None:
        """Reconstruct the host OpSet from feed history (lazy path) —
        only up to the clock this doc has been SERVING: the loader's
        cursor window may already include newer replicated changes, and
        folding those into the replay would make the caller's incremental
        apply a no-op (empty patch -> the frontend never hears about
        them). The newer changes re-arrive through the caller's window
        and produce a real patch."""
        with self._lock:
            if self.opset is not None:
                return
            self.opset = OpSet()
            loader, self._lazy_loader = self._lazy_loader, None
            base_clock, self._lazy_clock = self._lazy_clock, None
            self._snapshot_fn = None
            self._snapshot_cache = None
            if loader is not None:
                with bench("doc:lazyReplay"):
                    changes = loader()
                    if base_clock is not None:
                        changes = [
                            c
                            for c in changes
                            if c.seq <= base_clock.get(c.actor, 0)
                        ]
                    self.opset.apply_changes(changes)

    def set_actor_id(self, actor_id: str) -> None:
        with self._lock:
            self.actor_id = actor_id
        if self._announced:
            self._notify(
                {"type": "ActorId", "doc": self, "actorId": actor_id}
            )

    def apply_remote_changes(self, changes: List[Change]) -> None:
        self.remote_q.push(list(changes))

    def apply_local_request(self, req: ChangeRequest) -> None:
        self.local_q.push(req)

    def update_minimum_clock(self, clock: clockmod.Clock) -> None:
        """Gate first render until we've caught up to this clock
        (reference updateMinimumClock/testMinimumClockSatisfied)."""
        with self._lock:
            if self._announced:
                return
            self.minimum_clock = clockmod.union(
                self.minimum_clock or {}, clock
            )
        self._check_ready()

    def materialize_at(self, n: int):
        with self._lock:
            if self.opset is None and self._lazy_loader is None:
                return None
            self._ensure_opset()
            return self.opset.materialize_at(n)

    def history_patch(self, n: int):
        """Snapshot patch of the first n history changes (time travel;
        reconstructs the OpSet if this doc was bulk-loaded)."""
        with self._lock:
            if self.opset is None and self._lazy_loader is None:
                return None
            self._ensure_opset()
            sub = OpSet()
            sub.apply_changes(self.opset.history[:n])
            return sub.snapshot_patch()

    def snapshot_patch(self):
        with self._lock:
            if self.opset is not None:
                return self.opset.snapshot_patch()
            if self._snapshot_cache is not None:
                return self._snapshot_cache
            if self._snapshot_fn is not None:
                # Decode once and drop the closure: a bulk-load snapshot_fn
                # pins its slab's device/host lanes, which must not outlive
                # the first Ready it serves (the clock can't move while the
                # doc is still lazy, so the decoded Patch stays valid).
                fn, self._snapshot_fn = self._snapshot_fn, None
                self._snapshot_cache = fn()
                return self._snapshot_cache
            return None

    # ------------------------------------------------------------------

    def _minimum_satisfied(self) -> bool:
        if self.opset is None and self._lazy_clock is None:
            return False
        if self.minimum_clock is None:
            return True
        return clockmod.gte(self.clock, self.minimum_clock)

    def _check_ready(self, quiet: bool = False) -> None:
        with self._lock:
            if self._announced or not self._minimum_satisfied():
                return
            self._announced = True
        log("doc:back", self.id[:6], "ready")
        self._notify(
            {"type": "DocReadyQuiet" if quiet else "DocReady", "doc": self}
        )
        self.ready.push(True)

    def _handle_local(self, req: ChangeRequest) -> None:
        with self._lock:
            if self.opset is None:
                self._ensure_opset()
            with bench("doc:applyLocalChange"):
                try:
                    change, patch = self.opset.apply_local_request(req)
                except ValueError as e:
                    log("doc:back", "rejected local change:", e)
                    return
        self._notify(
            {
                "type": "LocalPatch",
                "doc": self,
                "change": change,
                "patch": patch,
            }
        )
        self._check_ready()

    def _handle_remote(self, changes: List[Change]) -> None:
        with self._lock:
            if self.opset is None:
                self._ensure_opset()
            with bench("doc:applyRemoteChanges"):
                patch = self.opset.apply_changes(changes)
        if self._announced and not patch.is_empty:
            self._notify(
                {"type": "RemotePatch", "doc": self, "patch": patch}
            )
        self._check_ready()
