"""DocBackend — per-document CRDT state holder.

Parity: reference src/DocBackend.ts:46-213 — wraps the CRDT engine
(here: crdt.opset.OpSet), serializes local/remote change application
through single-subscriber queues, tracks the clock and the minimumClock
render gate (don't surface a doc until we've caught up to what peers said
exists, reference src/DocBackend.ts:90-113), and notifies the RepoBackend
hub of Ready/LocalPatch/RemotePatch events.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..crdt import clock as clockmod
from ..crdt.change import Change, ChangeRequest
from ..crdt.opset import OpSet
from ..utils.debug import bench, log
from ..utils.queue import Queue


class DocBackend:
    def __init__(
        self,
        doc_id: str,
        notify: Callable[[Dict[str, Any]], None],
        opset: Optional[OpSet] = None,
    ) -> None:
        self.id = doc_id
        self._notify = notify
        self._lock = threading.RLock()
        self.opset: Optional[OpSet] = opset
        self.actor_id: Optional[str] = None
        self.device_snapshot = None  # set by bulk loader before Ready
        self.ready = Queue(f"doc:{doc_id[:6]}:ready")
        self._announced = False
        self.minimum_clock: Optional[clockmod.Clock] = None
        self.local_q: Queue = Queue(f"doc:{doc_id[:6]}:local")
        self.remote_q: Queue = Queue(f"doc:{doc_id[:6]}:remote")
        self.local_q.subscribe(self._handle_local)
        self.remote_q.subscribe(self._handle_remote)
        if opset is not None:
            self._check_ready()

    # ------------------------------------------------------------------

    @property
    def clock(self) -> clockmod.Clock:
        with self._lock:
            return dict(self.opset.clock) if self.opset else {}

    @property
    def history_len(self) -> int:
        with self._lock:
            return len(self.opset.history) if self.opset else 0

    def init(self, changes: List[Change], actor_id: Optional[str]) -> None:
        """Cold-start materialization (reference DocBackend.init — the
        north-star hot loop's per-doc endpoint)."""
        with self._lock:
            if self.opset is None:
                self.opset = OpSet()
            with bench(f"doc:init"):
                self.opset.apply_changes(changes)
            if actor_id is not None:
                self.actor_id = actor_id
        self._check_ready()

    def set_actor_id(self, actor_id: str) -> None:
        with self._lock:
            self.actor_id = actor_id
        if self._announced:
            self._notify(
                {"type": "ActorId", "doc": self, "actorId": actor_id}
            )

    def apply_remote_changes(self, changes: List[Change]) -> None:
        self.remote_q.push(list(changes))

    def apply_local_request(self, req: ChangeRequest) -> None:
        self.local_q.push(req)

    def update_minimum_clock(self, clock: clockmod.Clock) -> None:
        """Gate first render until we've caught up to this clock
        (reference updateMinimumClock/testMinimumClockSatisfied)."""
        with self._lock:
            if self._announced:
                return
            self.minimum_clock = clockmod.union(
                self.minimum_clock or {}, clock
            )
        self._check_ready()

    def materialize_at(self, n: int):
        with self._lock:
            if self.opset is None:
                return None
            return self.opset.materialize_at(n)

    def snapshot_patch(self):
        with self._lock:
            return self.opset.snapshot_patch() if self.opset else None

    # ------------------------------------------------------------------

    def _minimum_satisfied(self) -> bool:
        if self.opset is None:
            return False
        if self.minimum_clock is None:
            return True
        return clockmod.gte(self.opset.clock, self.minimum_clock)

    def _check_ready(self) -> None:
        with self._lock:
            if self._announced or not self._minimum_satisfied():
                return
            self._announced = True
        log("doc:back", self.id[:6], "ready")
        self._notify({"type": "DocReady", "doc": self})
        self.ready.push(True)

    def _handle_local(self, req: ChangeRequest) -> None:
        with self._lock:
            if self.opset is None:
                self.opset = OpSet()
            with bench("doc:applyLocalChange"):
                try:
                    change, patch = self.opset.apply_local_request(req)
                except ValueError as e:
                    log("doc:back", "rejected local change:", e)
                    return
        self._notify(
            {
                "type": "LocalPatch",
                "doc": self,
                "change": change,
                "patch": patch,
            }
        )
        self._check_ready()

    def _handle_remote(self, changes: List[Change]) -> None:
        with self._lock:
            if self.opset is None:
                self.opset = OpSet()
            with bench("doc:applyRemoteChanges"):
                patch = self.opset.apply_changes(changes)
        if self._announced and not patch.is_empty:
            self._notify(
                {"type": "RemotePatch", "doc": self, "patch": patch}
            )
        self._check_ready()
