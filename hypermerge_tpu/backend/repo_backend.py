"""RepoBackend — the orchestration hub.

Parity: reference src/RepoBackend.ts:55-651 — owns storage, doc backends,
actors, cursor/clock stores; routes every event. Message protocol to the
frontend is JSON dicts (msgs.py), so the frontend can live on another
thread/process, and the batched XLA path can slot in behind the same seam
(SURVEY.md §7.1).

Bulk cold-start: `load_documents_bulk` packs many docs' feeds into one
columnar batch and materializes them in a single device dispatch
(ops/materialize.py) — the reference's per-doc loadDocument loop
(src/RepoBackend.ts:238-257) becomes one XLA program.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Callable, Dict, List, Optional

from .. import msgs
from ..crdt import clock as clockmod
from ..crdt.change import Change, ChangeRequest
from ..crdt.opset import OpSet
from ..storage.colcache import (
    file_column_storage_fn,
    memory_column_storage_fn,
)
from ..storage.feed import (
    FeedStore,
    file_storage_fn,
    memory_storage_fn,
)
from ..storage.sql import SqlDatabase
from ..storage.stores import (
    ClockStore,
    CursorStore,
    FeedInfoStore,
    KeyStore,
)
from ..utils import keys as keymod
from ..utils.debug import log
from ..utils.ids import root_actor_id
from ..utils.queue import Queue
from ..files.file_store import FileStore
from .actor import Actor
from .doc_backend import DocBackend
from .metadata import Metadata


class RepoBackend:
    def __init__(
        self, path: Optional[str] = None, memory: bool = False
    ) -> None:
        if not memory and path is None:
            raise ValueError("need a path unless memory=True")
        self.path = path
        self.memory = memory
        if memory:
            storage_fn = memory_storage_fn
            cache_fn = memory_column_storage_fn
            db_path = ":memory:"
        else:
            storage_fn = file_storage_fn(os.path.join(path, "feeds"))
            cache_fn = file_column_storage_fn(os.path.join(path, "feeds"))
            os.makedirs(path, exist_ok=True)
            db_path = os.path.join(path, "repo.db")
        self.db = SqlDatabase(db_path)
        self.clocks = ClockStore(self.db)
        self.cursors = CursorStore(self.db)
        self.key_store = KeyStore(self.db)
        self.feed_info = FeedInfoStore(self.db)
        self.feeds = FeedStore(storage_fn, cache_fn)
        self.id: str = self.key_store.get_or_create("self.repo").public_key
        self.docs: Dict[str, DocBackend] = {}
        self.actors: Dict[str, Actor] = {}
        self._lock = threading.RLock()
        self.to_frontend: Queue = Queue("backend:toFrontend")
        self._query_handlers: Dict[str, Callable] = {}
        self.network = None  # attached by setSwarm (net/, M7)
        self.meta = Metadata(self.feeds, self.key_store)
        self.file_store: Optional[FileStore] = None
        self._file_server = None
        self._closed = False

    # ------------------------------------------------------------------
    # wiring

    def subscribe(self, subscriber: Callable[[Dict[str, Any]], None]) -> None:
        self.to_frontend.subscribe(subscriber)

    def receive(self, msg: Dict[str, Any]) -> None:
        if self._closed:
            return
        t = msg["type"]
        if t == "Create":
            self.create(msg["publicKey"], msg["secretKey"])
        elif t == "Open":
            self.open(msg["id"])
        elif t == "OpenBulk":
            self.load_documents_bulk(msg["ids"])
        elif t == "Request":
            self.handle_request(msg["id"], msg["request"])
        elif t == "Merge":
            self.merge(msg["id"], clockmod.strs_to_clock(msg["actors"]))
        elif t == "Close":
            self.close_doc(msg["id"])
        elif t == "Destroy":
            self.destroy(msg["id"])
        elif t == "DocMessage":
            self.send_doc_message(msg["id"], msg["contents"])
        elif t == "Query":
            self.handle_query(msg["queryId"], msg["query"])
        elif t == "NeedsActorId":
            doc = self.docs.get(msg["id"])
            if doc is not None:
                self._ensure_writable_actor(doc)
        else:
            log("repo:backend", "unknown msg", t)

    # ------------------------------------------------------------------
    # doc lifecycle

    def create(self, public_key: str, secret_key: str) -> DocBackend:
        doc_id = public_key
        doc = DocBackend(doc_id, self._doc_notify, None)
        with self._lock:
            self.docs[doc_id] = doc
        self.cursors.add_actor(self.id, doc_id, root_actor_id(doc_id))
        self._init_actor(keymod.KeyPair(public_key, secret_key))
        doc.init([], doc_id)  # root actor is writable on create
        return doc

    def open(self, doc_id: str) -> DocBackend:
        with self._lock:
            doc = self.docs.get(doc_id)
            if doc is not None:
                if doc._announced:
                    # a (re)opened frontend needs the Ready snapshot again
                    self._send_ready(doc)
                return doc
            doc = DocBackend(doc_id, self._doc_notify, None)
            self.docs[doc_id] = doc
        self.cursors.add_actor(self.id, doc_id, root_actor_id(doc_id))
        if not self._load_document_fast(doc):
            self._load_document(doc)
        return doc

    def merge(self, doc_id: str, clock: clockmod.Clock) -> None:
        """Adopt the target clock's actors into this doc's cursor; actual
        op merge falls out of sync_changes (reference src/RepoBackend.ts:
        213-217)."""
        doc = self.open(doc_id)
        self.cursors.update(self.id, doc_id, clock)
        for actor_id in clock:
            actor = self._get_or_create_actor(actor_id)
            self._sync_changes(actor)
        self._gossip_cursor(doc)

    def close_doc(self, doc_id: str) -> None:
        with self._lock:
            self.docs.pop(doc_id, None)

    def destroy(self, doc_id: str) -> None:
        """Remove doc state from stores (the reference stubs this out —
        src/RepoBackend.ts:632-635; we do the real cleanup)."""
        self.close_doc(doc_id)
        self.db.execute(
            "DELETE FROM clocks WHERE repo_id=? AND doc_id=?",
            (self.id, doc_id),
        )
        self.db.execute(
            "DELETE FROM cursors WHERE repo_id=? AND doc_id=?",
            (self.id, doc_id),
        )

    def handle_request(self, doc_id: str, request_json: Dict) -> None:
        doc = self.docs.get(doc_id)
        if doc is None:
            log("repo:backend", "request for unknown doc", doc_id[:6])
            return
        doc.apply_local_request(ChangeRequest.from_json(request_json))

    # ------------------------------------------------------------------
    # loading

    def _load_document(self, doc: DocBackend) -> None:
        cursor = self.cursors.get(self.id, doc.id)
        changes: List[Change] = []
        writable: Optional[str] = None
        for actor_id, max_seq in cursor.items():
            actor = self._get_or_create_actor(actor_id)
            if actor.writable and writable is None:
                writable = actor_id
            changes.extend(actor.changes_in_window(0, max_seq))
        if writable is None:
            writable = self._create_doc_actor(doc.id)
        root = root_actor_id(doc.id)
        root_actor = self.actors.get(root)
        if not changes and (root_actor is None or not root_actor.writable):
            # Unknown doc with no local history: gate readiness until the
            # root actor's first change replicates in (the reference's
            # minimumClock render gate, src/DocBackend.ts:90-113)
            doc.update_minimum_clock({root: 1})
        doc.init(changes, writable)
        # Feed announcements above can deliver blocks re-entrantly while
        # doc.opset is still None (so _sync_changes skipped them); the
        # cursor may also have grown via CursorMessages. Re-sync every
        # cursor actor now that the doc can apply changes.
        for actor_id in self.cursors.get(self.id, doc.id):
            actor = self.actors.get(actor_id)
            if actor is not None:
                self._sync_changes(actor)

    def _doc_feed_spec(self, doc_id: str, contiguous: Dict[str, bool]):
        """(spec, clock, n_changes, actor_ids, ok) for a doc's cursor:
        sidecar windows per actor feed plus the contiguous-seq clock
        shortcut (clock[actor] = applied count is only sound when the
        feed's seqs are 1..n — gap-y feeds set ok=False and must take
        the safe per-op replay path). `contiguous` memoizes the per-feed
        verification across docs sharing an actor."""
        cursor = self.cursors.get(self.id, doc_id)
        spec = []
        clock: Dict[str, int] = {}
        n_changes = 0
        ok = True
        for actor_id, max_seq in cursor.items():
            actor = self._get_or_create_actor(actor_id)
            fc = actor.columns()
            good = contiguous.get(actor_id)
            if good is None:
                good = fc.seqs_contiguous()
                contiguous[actor_id] = good
                if not good:
                    log(
                        "repo:backend",
                        f"feed {actor_id[:6]} has non-contiguous "
                        "seqs; bulk clock shortcut unsafe",
                    )
            ok = ok and good
            spec.append((fc, 0, max_seq))
            applied = fc.changes_in_window(0, max_seq)
            n_changes += applied
            if applied > 0:
                clock[actor_id] = applied  # seqs contiguous 1..n
        return spec, clock, n_changes, list(cursor), ok

    def _gate_unknown_empty(self, doc: DocBackend) -> None:
        """No local history and no writable root: gate readiness until
        the root actor's first change replicates in (the reference's
        minimumClock render gate, src/DocBackend.ts:90-113)."""
        root = root_actor_id(doc.id)
        root_actor = self.actors.get(root)
        if root_actor is None or not root_actor.writable:
            doc.update_minimum_clock({root: 1})

    def _resync_cursor_actors(self, actor_ids, synced: set) -> None:
        """Blocks replicated while a (bulk or fast) load was in flight
        hit _sync_changes before the doc could apply; re-run now (cheap
        no-op when clocks already match), as _load_document does."""
        for actor_id in actor_ids:
            if actor_id in synced:
                continue
            synced.add(actor_id)
            actor = self.actors.get(actor_id)
            if actor is not None:
                self._sync_changes(actor)

    def _load_document_fast(self, doc: DocBackend) -> bool:
        """Sidecar-backed cold open of ONE doc: pack its feed windows and
        decode through the numpy kernel twin (ops/host_kernel.py) — no
        per-op host replay, no device dispatch/compile. Returns False
        (caller falls back to _load_document's replay) when a feed's
        sidecar can't serve the window (non-contiguous seqs).
        Replaces the reference's per-change Automerge replay for stored
        histories (src/RepoBackend.ts:238-257 -> DocBackend.init)."""
        if os.environ.get("HM_FAST_OPEN", "1") == "0":
            return False
        from ..ops.columnar import pack_docs_columns
        from ..ops.host_kernel import run_batch_host
        from ..ops.materialize import DecodedBatch, decode_patch

        spec, clock, n_changes, actor_ids, ok = self._doc_feed_spec(
            doc.id, {}
        )
        if not ok:
            return False
        writable = self._writable_actor_for(doc.id)
        if n_changes == 0:
            self._gate_unknown_empty(doc)
        batch = pack_docs_columns([spec])
        dec = DecodedBatch(batch, run_batch_host(batch))
        doc.init_deferred(
            loader=self._bulk_history_loader(doc.id),
            clock=clock,
            history_len=n_changes,
            actor_id=writable,
            snapshot_fn=lambda: decode_patch(dec, 0),
            quiet=False,
        )
        self.clocks.update(self.id, doc.id, clock)
        self._resync_cursor_actors(
            self.cursors.get(self.id, doc.id), set()
        )
        return True

    def load_documents_bulk(
        self, doc_ids: List[str], slab: Optional[int] = None
    ) -> None:
        """Cold-start many docs with zero per-op host work (the north
        star, BASELINE config 4): each doc's feed windows come from the
        columnar sidecars (storage/colcache.py), pack vectorized
        (ops/columnar.py pack_docs_columns), and materialize in slab-sized
        device dispatches. Docs come up ready with device-served clocks
        and lazily-decoded snapshot patches; the host OpSet reconstructs
        only when a doc takes its first incremental change
        (DocBackend.init_deferred). Contrast the reference's per-doc
        loadDocument replay loop (src/RepoBackend.ts:238-257)."""
        from ..ops.columnar import pack_docs_columns
        from ..ops.crdt_kernels import run_batch
        from ..ops.materialize import DecodedBatch, decode_patch

        if slab is None:
            slab = int(os.environ.get("HM_BULK_SLAB", "4096"))

        entries = []  # (doc, spec, clock, n_changes, actor_ids)
        contiguous: Dict[str, bool] = {}  # per-actor-feed verification
        fallback_docs: List[DocBackend] = []
        already_ready: List[str] = []  # open docs: frontend may re-read
        with self.db.bulk():  # one commit for thousands of upserts
            for doc_id in doc_ids:
                with self._lock:
                    existing = self.docs.get(doc_id)
                    if existing is not None:
                        if existing._announced:
                            already_ready.append(doc_id)
                        continue
                    doc = DocBackend(doc_id, self._doc_notify, None)
                    self.docs[doc_id] = doc
                self.cursors.add_actor(
                    self.id, doc_id, root_actor_id(doc_id)
                )
                spec, clock, n_changes, actor_ids, ok = (
                    self._doc_feed_spec(doc_id, contiguous)
                )
                if not ok:
                    fallback_docs.append(doc)
                    continue
                if n_changes == 0:
                    self._gate_unknown_empty(doc)
                entries.append(
                    (doc, spec, clock, n_changes, actor_ids)
                )

        ready_ids: List[str] = []
        with self.db.bulk():
            self._load_slabs(
                entries, slab, pack_docs_columns, run_batch, DecodedBatch,
                decode_patch, ready_ids,
            )
        for doc in fallback_docs:
            self._load_document(doc)
        ready_ids.extend(already_ready)
        if ready_ids:
            self.to_frontend.push(msgs.bulk_ready_msg(ready_ids))
        synced: set = set()
        for _doc, _spec, _clock, _n, actor_ids in entries:
            self._resync_cursor_actors(actor_ids, synced)

    def _load_slabs(
        self, entries, slab, pack_docs_columns, run_batch, DecodedBatch,
        decode_patch, ready_ids,
    ) -> None:
        from ..ops.columnar import round_up_pow2
        from ..ops.host_kernel import run_batch_host

        # small loads aren't worth a device dispatch (let alone a fresh
        # per-bucket compile): under this many [D, N] cells the numpy
        # kernel twin wins outright
        min_cells = int(os.environ.get("HM_DEVICE_MIN_CELLS", "131072"))
        for base in range(0, len(entries), slab):
            chunk = entries[base : base + slab]
            # bucket the doc axis (pow2) so every slab of a bulk load —
            # and every later bulk load — reuses one compiled executable
            batch = pack_docs_columns(
                [e[1] for e in chunk], n_docs=round_up_pow2(len(chunk))
            )
            runner = (
                run_batch_host
                if batch.n_docs * batch.n_rows < min_cells
                else run_batch
            )
            dec = DecodedBatch(batch, runner(batch))
            for j, (doc, _spec, clock, n_changes, actor_ids) in enumerate(
                chunk
            ):
                writable = None
                for actor_id in actor_ids:
                    a = self.actors.get(actor_id)
                    if a is not None and a.writable:
                        writable = actor_id
                        break
                doc.init_deferred(
                    loader=self._bulk_history_loader(doc.id),
                    clock=clock,
                    history_len=n_changes,
                    actor_id=writable,
                    snapshot_fn=(
                        lambda dec=dec, j=j: decode_patch(dec.doc_view(j), 0)
                    ),
                )
                self.clocks.update(self.id, doc.id, clock)
                if doc._announced:  # minimum-clock-gated docs wait
                    ready_ids.append(doc.id)

    def _bulk_history_loader(self, doc_id: str):
        """Deferred host replay for a bulk-loaded doc: decode the feed
        windows into Change objects only when the doc's first incremental
        change forces an OpSet to exist."""

        def load() -> List[Change]:
            cursor = self.cursors.get(self.id, doc_id)
            changes: List[Change] = []
            for actor_id, max_seq in cursor.items():
                actor = self._get_or_create_actor(actor_id)
                changes.extend(actor.changes_in_window(0, max_seq))
            return changes

        return load

    def _writable_actor_for(self, doc_id: str) -> str:
        cursor = self.cursors.get(self.id, doc_id)
        for actor_id in cursor:
            actor = self.actors.get(actor_id)
            if actor is not None and actor.writable:
                return actor_id
        return self._create_doc_actor(doc_id)

    def _create_doc_actor(self, doc_id: str) -> str:
        pair = keymod.create()
        self._init_actor(pair)
        self.cursors.add_actor(self.id, doc_id, pair.public_key)
        return pair.public_key

    def _ensure_writable_actor(self, doc: DocBackend) -> None:
        actor_id = self._writable_actor_for(doc.id)
        doc.set_actor_id(actor_id)

    # ------------------------------------------------------------------
    # actors

    def _init_actor(self, pair: keymod.KeyPair) -> Actor:
        feed = self.feeds.create(pair)
        actor = Actor(feed, self._actor_notify)
        with self._lock:
            self.actors[actor.id] = actor
        self.feed_info.save(
            feed.public_key, feed.discovery_id, feed.writable
        )
        if self.network is not None:
            self.network.announce_feed(feed)
        return actor

    def _get_or_create_actor(self, actor_id: str) -> Actor:
        with self._lock:
            actor = self.actors.get(actor_id)
        if actor is None:
            feed = self.feeds.open_feed(actor_id)
            actor = Actor(feed, self._actor_notify)
            with self._lock:
                self.actors[actor_id] = actor
            self.feed_info.save(
                feed.public_key, feed.discovery_id, feed.writable
            )
            if self.network is not None:
                self.network.announce_feed(feed)
        return actor

    def _sync_changes(self, actor: Actor) -> None:
        """Feed caught new blocks: push the admissible window into every
        doc whose cursor includes this actor (reference syncChanges,
        src/RepoBackend.ts:506-531)."""
        for doc_id in self.cursors.docs_with_actor(self.id, actor.id):
            doc = self.docs.get(doc_id)
            if doc is None or not doc.can_apply:
                continue
            start = doc.clock.get(actor.id, 0)
            end = self.cursors.entry(self.id, doc_id, actor.id)
            window = actor.changes_in_window(start, end)
            if window:
                doc.apply_remote_changes(window)

    # ------------------------------------------------------------------
    # notifications from docs / actors

    def _doc_notify(self, event: Dict[str, Any]) -> None:
        t = event["type"]
        doc: DocBackend = event["doc"]
        if t == "DocReady":
            self._send_ready(doc)
        elif t == "LocalPatch":
            change: Change = event["change"]
            actor = self.actors.get(change.actor)
            if actor is not None and actor.writable:
                actor.write_change(change)
            else:
                log("repo:backend", "no writable actor for", change.actor[:6])
            clock = doc.clock
            self.clocks.update(self.id, doc.id, clock)
            self.cursors.update(self.id, doc.id, {change.actor: change.seq})
            self.to_frontend.push(
                msgs.patch_msg(
                    doc.id, event["patch"].to_json(), doc.history_len
                )
            )
            self._gossip_cursor(doc)
        elif t == "RemotePatch":
            self.clocks.update(self.id, doc.id, doc.clock)
            self.to_frontend.push(
                msgs.patch_msg(
                    doc.id, event["patch"].to_json(), doc.history_len
                )
            )
        elif t == "ActorId":
            self.to_frontend.push(
                msgs.actor_id_msg(doc.id, event["actorId"])
            )

    def _send_ready(self, doc: DocBackend) -> None:
        patch = doc.snapshot_patch()
        self.clocks.update(self.id, doc.id, doc.clock)
        self.to_frontend.push(
            msgs.ready_msg(
                doc.id,
                doc.actor_id,
                patch.to_json() if patch else None,
                doc.history_len,
            )
        )

    def _actor_notify(self, event: Dict[str, Any]) -> None:
        t = event["type"]
        actor: Actor = event["actor"]
        if t == "ActorSync":
            self._sync_changes(actor)
        elif t == "Download":
            for doc_id in self.cursors.docs_with_actor(self.id, actor.id):
                self.to_frontend.push(
                    msgs.download_msg(
                        doc_id,
                        actor.id,
                        event["index"],
                        event["size"],
                        event["time"],
                    )
                )
        # ActorInitialized: nothing extra — feeds announce via network hook

    # ------------------------------------------------------------------
    # queries

    def handle_query(self, query_id: int, query: Dict[str, Any]) -> None:
        t = query["type"]
        if t == "Materialize":
            doc = self.docs.get(query["id"])
            patch = (
                doc.history_patch(query["history"])
                if doc is not None
                else None
            )
            payload = patch.to_json() if patch is not None else None
            self.to_frontend.push(msgs.reply_msg(query_id, payload))
        elif t == "Metadata":
            doc = self.docs.get(query["id"])
            if doc is None:
                # Not an open doc: maybe a hyperfile in the ledger
                # (reference src/RepoBackend.ts:560-568 consults Metadata).
                payload = self.meta.file_metadata(query["id"])
            else:
                payload = {
                    "type": "Document",
                    "clock": clockmod.clock_to_strs(doc.clock),
                    "actors": self.cursors.actors_for(self.id, doc.id),
                    "history": doc.history_len,
                }
            self.to_frontend.push(msgs.reply_msg(query_id, payload))
        else:
            self.to_frontend.push(msgs.reply_msg(query_id, None))

    # ------------------------------------------------------------------
    # peer messaging + gossip (fully wired by net/, M7)

    def send_doc_message(self, doc_id: str, contents: Any) -> None:
        if self.network is not None:
            self.network.broadcast_doc_message(doc_id, contents)

    def deliver_doc_message(self, doc_id: str, contents: Any) -> None:
        """Inbound ephemeral message from a peer."""
        self.to_frontend.push(msgs.doc_message_fwd_msg(doc_id, contents))

    def on_cursor_message(
        self,
        peer,
        doc_id: str,
        cursors: clockmod.Clock,
        clocks: clockmod.Clock,
    ) -> None:
        """Peer told us which actors (and how far) a doc includes: expand
        our cursor, gate rendering on their clock, open missing feeds
        (reference src/RepoBackend.ts:394-427). The peer's clock is
        recorded under the SENDER's id — our own clock row only ever
        reflects changes we actually applied (else we'd advertise state we
        can't supply to third parties)."""
        self.cursors.update(self.id, doc_id, cursors)
        self.clocks.update(peer.id, doc_id, clocks)
        doc = self.docs.get(doc_id)
        if doc is not None:
            doc.update_minimum_clock(clocks)
        for actor_id in cursors:
            actor = self._get_or_create_actor(actor_id)
            self._sync_changes(actor)

    def on_discovery(self, public_id: str, peer) -> None:
        """A feed shared with `peer` was discovered: send our cursor +
        clock for every doc that includes that actor (reference
        src/RepoBackend.ts:374-392)."""
        for doc_id in self.cursors.docs_with_actor(self.id, public_id):
            self.network.send_cursor_to(
                peer,
                doc_id,
                self.cursors.get(self.id, doc_id),
                self.clocks.get(self.id, doc_id),
            )

    def _gossip_cursor(self, doc: DocBackend) -> None:
        if self.network is not None:
            self.network.gossip_cursor(
                doc.id,
                self.cursors.get(self.id, doc.id),
                self.clocks.get(self.id, doc.id),
            )

    def start_file_server(self, path: str) -> None:
        from ..files.file_server import FileServer

        if self._file_server is not None:
            raise RuntimeError(
                "file server already listening; one per repo backend"
            )
        self.file_store = FileStore(self.feeds)
        # Completed uploads flow into the durable metadata ledger
        # (reference src/RepoBackend.ts:105-107 → Metadata.addFile).
        self.file_store.write_log.subscribe(
            lambda header: self.meta.add_file(
                header.url, header.size, header.mime_type
            )
        )
        self._file_server = FileServer(self.file_store)
        self._file_server.listen(path)
        self.to_frontend.push(msgs.file_server_ready_msg(path))

    def set_swarm(self, swarm) -> None:
        from ..net.network import Network  # local import: net dep optional

        if self.network is None:
            self.network = Network(self)
        self.network.set_swarm(swarm)

    # ------------------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        if self._file_server is not None:
            self._file_server.close()
            self._file_server = None
        if self.network is not None:
            self.network.close()
        self.feeds.close()
        self.db.close()
