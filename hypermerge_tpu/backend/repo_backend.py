"""RepoBackend — the orchestration hub.

Parity: reference src/RepoBackend.ts:55-651 — owns storage, doc backends,
actors, cursor/clock stores; routes every event. Message protocol to the
frontend is JSON dicts (msgs.py), so the frontend can live on another
thread/process, and the batched XLA path can slot in behind the same seam
(SURVEY.md §7.1).

Bulk cold-start: `load_documents_bulk` packs many docs' feeds into one
columnar batch and materializes them in a single device dispatch
(ops/materialize.py) — the reference's per-doc loadDocument loop
(src/RepoBackend.ts:238-257) becomes one XLA program.
"""

from __future__ import annotations

import functools
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..analysis.lockdep import make_lock, make_rlock, maybe_install_racedep
from .. import msgs
from ..crdt import clock as clockmod
from ..crdt.change import Change, ChangeRequest
from ..crdt.opset import OpSet
from ..storage.colcache import (
    file_column_storage_fn,
    memory_column_storage_fn,
)
from ..storage.feed import (
    FeedStore,
    file_storage_fn,
    memory_storage_fn,
)
from ..storage.sql import SqlDatabase
from ..storage.stores import (
    ClockStore,
    CursorStore,
    FeedInfoStore,
    KeyStore,
)
from ..utils import keys as keymod
from ..utils.debug import log
from ..utils.ids import root_actor_id
from .. import telemetry
from ..utils.queue import Queue
from ..files.file_store import FileStore
from .actor import Actor
from .doc_backend import DocBackend
from .metadata import Metadata

# device->host summary-wire transfer bytes (same series sharded.py's
# collective gather feeds; handle cached — one per-slab bump)
_M_D2H = telemetry.counter("mesh.d2h_bytes")


# actor id -> discovery id is a pure hash of an immutable key: memoize
# it for the telemetry payload's per-poll sweep over every doc's actors
_discovery_id_cached = functools.lru_cache(maxsize=65536)(
    keymod.discovery_id
)


def _merge_store_marks(old, new):
    """Within-window merge for the debounced store flusher's marks:
    clock dicts merge per-actor max-wins (two cursor-gossip frames in
    one window must not drop the older frame's actors), cursor seqs
    take the max. The sqlite upserts are monotonic anyway; this keeps
    the in-window view equally monotonic."""
    if isinstance(old, dict) and isinstance(new, dict):
        out = dict(old)
        for k, v in new.items():
            if v > out.get(k, 0):
                out[k] = v
        return out
    if isinstance(old, int) and isinstance(new, int):
        return max(old, new)
    return new


class RepoBackend:
    def __init__(
        self, path: Optional[str] = None, memory: bool = False
    ) -> None:
        if not memory and path is None:
            raise ValueError("need a path unless memory=True")
        # HM_RACEDEP=1: wrap the guard manifest's declared attributes
        # (analysis/guards.py) in lockset descriptors BEFORE any of
        # the hot concurrent objects below exist — daemons and bench
        # runs get the detector without a test fixture
        maybe_install_racedep()
        self.path = path
        self.memory = memory
        from ..storage.integrity import (
            file_sig_storage_fn,
            memory_sig_storage_fn,
        )

        from ..storage.durability import DurabilityManager

        # durability tiers (HM_FSYNC, storage/durability.py): feed
        # appends either fsync inline (tier 2), group-fsync on this
        # manager's debounced flusher (tier 1), or not at all (tier 0 —
        # crash-safe via recovery, not crash-durable)
        self.durability = DurabilityManager()
        if memory:
            storage_fn = memory_storage_fn
            cache_fn = memory_column_storage_fn
            sig_fn = memory_sig_storage_fn
            db_path = ":memory:"
            self._dirty_marker = None
            was_dirty = False
        else:
            storage_fn = file_storage_fn(
                os.path.join(path, "feeds"), durability=self.durability
            )
            cache_fn = file_column_storage_fn(os.path.join(path, "feeds"))
            sig_fn = file_sig_storage_fn(os.path.join(path, "feeds"))
            os.makedirs(path, exist_ok=True)
            db_path = os.path.join(path, "repo.db")
            # crash detection: the marker exists for exactly the life
            # of a session that may write; close() removes it after
            # every flusher drained. Present at open = the previous
            # session crashed -> run whole-repo recovery below.
            self._dirty_marker = os.path.join(path, "repo.dirty")
            was_dirty = os.path.exists(self._dirty_marker)
        # corpus slab handle (storage/slab.py) when file-backed: the
        # backend owns its lifecycle (compaction on close)
        self._col_slab = getattr(cache_fn, "slab", None)
        self.db = SqlDatabase(db_path)
        self.clocks = ClockStore(self.db)
        self.cursors = CursorStore(self.db)
        self.key_store = KeyStore(self.db)
        self.feed_info = FeedInfoStore(self.db)
        self.feeds = FeedStore(storage_fn, cache_fn, sig_fn)
        self.id: str = self.key_store.get_or_create("self.repo").public_key
        # every secret key this repo ever persisted, by PUBLIC key —
        # one query, not one per actor. Writable actors stay writable
        # across restarts (the reference persists keys the same way):
        # without this, a crashed session's lazily-signed feed tail
        # could never be re-signed (sealed) OR replicated again.
        self._actor_keys = {
            p.public_key: p
            for p in self.key_store.all_pairs().values()
            if p.secret_key
        }
        # whole-repo crash recovery (storage/scrub.py): audit/truncate
        # torn tails, repair the sig chains, reset sidecars that ran
        # ahead, reconcile sqlite clock rows with feed reality. Runs
        # BEFORE the clock mirror seeds and before any doc opens.
        self.recovery_report: Optional[Dict] = None
        recovery_skipped = False
        if was_dirty and os.environ.get("HM_RECOVER", "1") != "0":
            from ..storage.scrub import recover_repo

            self.recovery_report = recover_repo(self)
        elif was_dirty:
            recovery_skipped = True
        # shared group-commit journal (storage/wal.py): created AFTER
        # recovery consumed the crashed session's journal. With
        # recovery explicitly skipped (HM_RECOVER=0 — tools/scrub.py
        # drives it manually) the crashed journal must survive for
        # that manual pass, so this session runs journal-less and
        # durable appends take the legacy per-feed path. Same when
        # recovery RAN but a replayed feed's fsync failed: the old
        # journal is the only durable copy of those records, and a
        # fresh WriteAheadLog at the same path would truncate it.
        wal_rep = (self.recovery_report or {}).get("wal") or {}
        replay_incomplete = bool(wal_rep.get("replay_sync_failed"))
        if not memory and not recovery_skipped and not replay_incomplete:
            from ..storage.wal import WriteAheadLog, wal_enabled

            if wal_enabled():
                try:
                    self.durability.attach_wal(
                        WriteAheadLog(
                            os.path.join(path, "wal.log"),
                            self.durability.tier,
                        )
                    )
                except OSError as e:
                    log("repo:backend", f"no write-ahead journal: {e}")
        if recovery_skipped and self._dirty_marker is not None:
            # the preserved stamp bounds a FUTURE recovery's scan to
            # the crashed session's dirty ledger — sound only while
            # that ledger covers all damage. The first journal-less
            # feed write of THIS session breaks that: invalidate the
            # stamp then (not at open — a read-only manual-scrub
            # session must leave it byte-for-byte intact).
            self.durability.journalless_write_cb = (
                self._invalidate_recovery_stamp
            )
        if self._dirty_marker is not None and not recovery_skipped:
            from ..storage.faults import io_fsync, io_open

            # the marker must be DURABLE: if a power cut erased it,
            # reopen would silently skip recovery — and tier 0 depends
            # on recovery-on-open to reconcile clocks with feeds. Its
            # CONTENT is the journal's session id (the generation
            # stamp): recovery bounds its scan to the journal's dirty
            # ledger only when marker and journal header agree. With
            # recovery explicitly skipped (HM_RECOVER=0) the CRASHED
            # session's marker+stamp must survive untouched, or the
            # manual tools/scrub.py pass would lose both the crash
            # evidence and the scan bounding.
            with io_open(self._dirty_marker, "wb") as fh:
                if self.durability.wal is not None:
                    fh.write(
                        self.durability.wal.session.encode("utf-8")
                    )
                io_fsync(fh)
            self._fsync_dir(path)
        if os.environ.get("HM_CLOCK_MIRROR", "1") != "0":
            # device-resident ClockStore query twin (ops/clock_mirror.py):
            # writes buffer host-side, so this costs nothing until the
            # first bulk union/dominated query
            from ..ops.clock_mirror import DeviceClockMirror

            self.clocks.attach_mirror(self.id, DeviceClockMirror())
        self.docs: Dict[str, DocBackend] = {}
        self.actors: Dict[str, Actor] = {}
        self._lock = make_rlock("repo")
        # many-writer plane (hub mode): Create/Open/NeedsActorId arrive
        # tagged with a per-connection writer token; each writing
        # connection gets its OWN actor per doc so N frontends can write
        # one hot doc without sharing (and corrupting) a seq counter.
        # (doc_id, token) -> actor_id; doc_id -> tokens awaiting Ready.
        self._writer_actors: Dict[Any, str] = {}
        self._pending_ready: Dict[str, set] = {}
        self.to_frontend: Queue = Queue("backend:toFrontend")
        self._query_handlers: Dict[str, Callable] = {}
        self.network = None  # attached by setSwarm (net/, M7)
        self.meta = Metadata(self.feeds, self.key_store)
        self.file_store: Optional[FileStore] = None
        self._file_server = None
        self._closed = False
        # bulk-load state: deferred per-actor work (one executemany / one
        # resync instead of per-feed sqlite + sync queries), and the
        # device summary refs the materialization barrier fetches
        self._bulk_deferred_syncs: Optional[set] = None
        self._bulk_feed_rows: Optional[List] = None
        self._bulk_mutex = make_lock("repo.bulk")  # serializes bulk loads:
        # the deferral accumulators above are per-load state
        self._pending_summaries: List = []
        self._pending_memo: List = []
        # streaming-pipeline state: stage threads add stage timings
        # concurrently, and the async fetch worker of the most recent
        # load is joined by the materialization barrier
        self._stats_lock = make_lock("repo.stats")
        self._fetch_ctx = None
        self._bulk_t0: Optional[float] = None
        self._rr_cached = False  # round-robin scheduler, built lazily
        self._rr_value = None
        # per-doc summary memo: doc_id -> last fetched summary row + the
        # clock it was fetched at. A later bulk load of a doc whose
        # clock has not moved (the same clock rows the device-resident
        # ClockStore mirror tracks) is CLEAN: it skips pack, dispatch,
        # and the summary transfer entirely — only dirty docs ride the
        # wire. Bounded LRU by BYTES (HM_SUMMARY_MEMO_MB, 0 disables) —
        # entries scale with the doc's row bucket, so an entry-count cap
        # would let large buckets pin gigabytes.
        from collections import OrderedDict

        self._summary_memo: "OrderedDict[str, Dict]" = OrderedDict()
        self._summary_memo_bytes = 0
        self.last_bulk_stats: Dict[str, int] = {}
        # cursor/clock gossip is a latest-state broadcast: debounce it
        # so a burst of local changes to one doc costs one frame
        from ..utils.debounce import Debouncer

        self._gossip = Debouncer(
            self._flush_gossip,
            window_s=float(os.environ.get("HM_GOSSIP_FLUSH_MS", "10"))
            / 1e3,
            name="gossip",
        )
        # inbound-sync application is idempotent window-polling: under
        # edit load many small extensions coalesce into one
        # _sync_changes pass per actor
        self._syncs = Debouncer(
            self._flush_syncs,
            window_s=float(os.environ.get("HM_SYNC_FLUSH_MS", "2"))
            / 1e3,
            name="syncs",
        )
        # sidecar encoding rides OFF the interactive write path: the
        # columnar cache is derived data, caught up by this flusher (or
        # on demand by columns())
        self._cache_syncs = Debouncer(
            lambda actors: [a.sync_cache() for a in actors],
            window_s=float(os.environ.get("HM_CACHE_FLUSH_MS", "5"))
            / 1e3,
            name="colcache",
        )
        # clock/cursor rows are monotonic latest-state: a burst of live
        # patches coalesces into one executemany per window instead of
        # a per-change upsert + read-back (the in-memory doc clock is
        # authoritative; rows rebuild from feeds after a crash)
        self._stores = Debouncer(
            self._flush_store_rows,
            window_s=float(os.environ.get("HM_STORE_FLUSH_MS", "5"))
            / 1e3,
            merge=_merge_store_marks,
            name="stores",
        )
        # read once: _mark_clock_row/_mark_cursor_row run per patch,
        # _flush_gossip per debounce window
        self._store_debounce = (
            os.environ.get("HM_STORE_DEBOUNCE", "1") != "0"
        )
        self._gossip_fresh = (
            os.environ.get("HM_GOSSIP_FRESH", "1") != "0"
        )
        # live apply engine (backend/live.py): incremental changes on
        # lazy docs batch through per-tick kernel dispatches. HM_LIVE=0
        # keeps the host-OpSet path as the correctness twin.
        self.live = None
        if os.environ.get("HM_LIVE", "1") != "0":
            from .live import LiveApplyEngine

            self.live = LiveApplyEngine(self)
        # read-serving tier (serve/): reads answer from HBM-resident
        # summary columns through batched query kernels. HM_SERVE=0
        # keeps per-request host materialization as the bit-identical
        # twin; a tier that cannot come up (no usable jax backend)
        # degrades to the same twin rather than failing the repo.
        self.serve = None
        if os.environ.get("HM_SERVE", "1") != "0":
            try:
                from ..serve import ServeTier

                self.serve = ServeTier(self)
            except Exception as e:
                log("repo:backend", f"no serve tier: {e}")
        # service plane (serve/overload.py): the brownout ladder
        # watching this backend's own signals — serve read p99,
        # admission-queue occupancy, WAL fsync debt — and enforcing
        # at the read front door (read_doc) and the WAL ack path.
        # HM_SERVICE=0 removes the controller entirely.
        self.overload = None
        if os.environ.get("HM_SERVICE", "1") != "0":
            from ..serve.overload import (
                HistogramWindow,
                OverloadController,
            )

            self._serve_p99 = (
                HistogramWindow(self.serve._hist)
                if self.serve is not None
                else None
            )
            self.overload = OverloadController(
                signals=self._service_signals
            )
            wal = self.durability.wal
            if wal is not None:
                # SHED backpressure: the group-commit leader stretches
                # its gather window — acks pace down, nothing acked is
                # ever dropped
                wal.ack_pacer = self.overload.ack_extra_s
            self.overload.start()

    @staticmethod
    def _fsync_dir(path: str) -> None:
        """Durably record a directory entry (marker create). Advisory:
        platforms without O_DIRECTORY fsync just skip it."""
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _invalidate_recovery_stamp(self) -> None:
        """First feed write of a journal-less HM_RECOVER=0 session
        (storage/durability.py journalless_write_cb): the crashed
        session's marker+journal were preserved for a manual scrub,
        but this session's writes are OUTSIDE that journal's dirty
        ledger — append a suffix so the stamp stops matching the
        journal header. A crash of THIS session then recovers with
        the full sidecar scan (and still replays the old journal,
        which is session-match independent) instead of trusting a
        ledger that never saw the new damage. The marker itself — the
        crash evidence — survives."""
        if self._dirty_marker is None:
            return
        from ..storage.faults import io_fsync, io_open

        try:
            prev = b""
            try:
                with open(self._dirty_marker, "rb") as fh:
                    prev = fh.read()
            except OSError:
                pass
            if prev.endswith(b"+journalless"):
                return
            with io_open(self._dirty_marker, "wb") as fh:
                fh.write(prev + b"+journalless")
                io_fsync(fh)
        except OSError as e:
            log("repo:backend", f"stamp invalidation failed: {e}")

    def hydrate_feeds(self) -> int:
        """Open every feed the repo has on record (the feeds table) so
        a daemon ANNOUNCES and SERVES all its docs without waiting for
        a doc open — the fleet posture (net/ipc.py --dht joins the
        swarm before any frontend attaches; an unopened feed would
        neither join discovery nor answer DiscoveryIds). Persisted
        secret keys re-bind writability exactly as in
        _get_or_create_actor; opening a feed is storage-light (no CRDT
        materialization). Returns the number of feeds on record."""
        n = 0
        for pk in self.feed_info.all_public_ids():
            pair = self._actor_keys.get(pk)
            if pair is not None:
                self.feeds.create(pair)
            else:
                self.feeds.open_feed(pk)
            n += 1
        return n

    def identity_seed(self) -> Optional[bytes]:
        """The repo's static ed25519 seed for transport authentication
        (net/secure.py auth frames), or None for readonly repos."""
        from ..utils import base58

        pair = self.key_store.get_or_create("self.repo")
        if pair.secret_key is None:
            return None
        return base58.decode(pair.secret_key)

    # ------------------------------------------------------------------
    # wiring

    def subscribe(self, subscriber: Callable[[Dict[str, Any]], None]) -> None:
        self.to_frontend.subscribe(subscriber)

    def receive(self, msg: Dict[str, Any]) -> None:
        if self._closed:
            return
        t = msg["type"]
        if t == "Create":
            self.create(
                msg["publicKey"], msg["secretKey"],
                writer=msg.get("writer"),
            )
        elif t == "Open":
            self.open(msg["id"], writer=msg.get("writer"))
        elif t == "OpenBulk":
            self.load_documents_bulk(msg["ids"])
        elif t == "Request":
            self.handle_request(msg["id"], msg["request"])
        elif t == "Merge":
            self.merge(msg["id"], clockmod.strs_to_clock(msg["actors"]))
        elif t == "Close":
            self.close_doc(msg["id"])
        elif t == "Destroy":
            self.destroy(msg["id"])
        elif t == "DocMessage":
            self.send_doc_message(msg["id"], msg["contents"])
        elif t == "Query":
            self.handle_query(msg["queryId"], msg["query"])
        elif t == "NeedsActorId":
            doc = self.docs.get(msg["id"])
            if doc is not None:
                writer = msg.get("writer")
                if writer is None:
                    self._ensure_writable_actor(doc)
                else:
                    self._grant_writer_actor(doc, writer)
        elif t == "WriterGone":
            self._drop_writer(msg["writer"])
        else:
            log("repo:backend", "unknown msg", t)

    # ------------------------------------------------------------------
    # doc lifecycle

    def create(
        self,
        public_key: str,
        secret_key: str,
        writer: Optional[int] = None,
    ) -> DocBackend:
        doc_id = public_key
        doc = DocBackend(doc_id, self._doc_notify, None, live=self.live)
        with self._lock:
            self.docs[doc_id] = doc
            if writer is not None:
                # the creating connection claims the root actor (its
                # frontend already assumed actor_id == doc_id); later
                # writers mint fresh actors via NeedsActorId
                self._writer_actors[(doc_id, writer)] = root_actor_id(
                    doc_id
                )
                self._pending_ready.setdefault(doc_id, set()).add(writer)
        self.cursors.add_actor(self.id, doc_id, root_actor_id(doc_id))
        self._init_actor(keymod.KeyPair(public_key, secret_key))
        doc.init([], doc_id)  # root actor is writable on create
        return doc

    def open(
        self, doc_id: str, writer: Optional[int] = None
    ) -> DocBackend:
        with self._lock:
            doc = self.docs.get(doc_id)
            if doc is None:
                doc = DocBackend(
                    doc_id, self._doc_notify, None, live=self.live
                )
                self.docs[doc_id] = doc
                existing = None
            else:
                existing = doc
            if writer is not None and (
                existing is None or not existing._announced
            ):
                # doc still loading: park the token; the DocReady-time
                # _send_ready pops it and emits this writer's Ready
                self._pending_ready.setdefault(doc_id, set()).add(writer)
        if existing is not None:
            if existing._announced:
                # a (re)opened frontend needs the Ready snapshot again.
                # OUTSIDE self._lock: the snapshot takes the live-engine
                # lock, and live.engine ranks ABOVE repo in the declared
                # hierarchy (analysis/hierarchy.py; adoption opens
                # actors under self._lock) — holding repo->engine here
                # would deadlock against a tick. The lint rule
                # `lock-order` flags engine entrypoints called under
                # repo/doc/store locks.
                self._send_ready(existing, writer=writer)
            return existing
        try:
            # a doc closed with store rows still in the debouncer must
            # not reload from the stale rows (load reads cursor/clock
            # directly)
            self._settle_store_rows(doc_id)
            self.cursors.add_actor(self.id, doc_id, root_actor_id(doc_id))
            if not self._load_document_fast(doc):
                self._load_document(doc)
        except BaseException:
            # a failed load must not leave the blank doc registered:
            # every later open() would return it as-is (never loaded,
            # never Ready) even after the failure clears
            with self._lock:
                if self.docs.get(doc_id) is doc:
                    del self.docs[doc_id]
            if self.live is not None:
                self.live.drop(doc_id)
            raise
        return doc

    def merge(self, doc_id: str, clock: clockmod.Clock) -> None:
        """Adopt the target clock's actors into this doc's cursor; actual
        op merge falls out of sync_changes (reference src/RepoBackend.ts:
        213-217)."""
        doc = self.open(doc_id)
        self.cursors.update(self.id, doc_id, clock)
        for actor_id in clock:
            actor = self._get_or_create_actor(actor_id)
            self._sync_changes(actor)
        self._gossip_cursor(doc)

    def close_doc(self, doc_id: str) -> None:
        with self._lock:
            self.docs.pop(doc_id, None)
            self._pending_ready.pop(doc_id, None)
            for key in [
                k for k in self._writer_actors if k[0] == doc_id
            ]:
                del self._writer_actors[key]
        if self.live is not None:
            self.live.drop(doc_id)
        if self.serve is not None:
            self.serve.drop(doc_id)

    def destroy(self, doc_id: str) -> None:
        """Remove ALL doc state: store rows AND the on-disk feeds
        (block logs, columnar sidecars, signature records) of every
        actor exclusive to this doc. Actors shared with other docs keep
        their feeds. (The reference stubs destroy out —
        src/RepoBackend.ts:632-635; here it reclaims disk for real.)"""
        self.close_doc(doc_id)
        # pending debounced rows flushed after the delete would
        # resurrect the destroyed doc's rows — land them first
        self._settle_store_rows(doc_id)
        actors = list(self.cursors.get(self.id, doc_id))
        self.clocks.delete_doc(doc_id)  # peers' rows included
        self.cursors.delete_doc(self.id, doc_id)
        for actor_id in actors:
            others = self.cursors.docs_with_actor(self.id, actor_id)
            if others:  # shared with surviving docs: keep the feed
                continue
            with self._lock:
                self.actors.pop(actor_id, None)
            self._actor_keys.pop(actor_id, None)
            self.key_store.clear(actor_id)
            self.feed_info.remove(actor_id)
            self.feeds.remove(actor_id)

    def handle_request(self, doc_id: str, request_json: Dict) -> None:
        doc = self.docs.get(doc_id)
        if doc is None:
            log("repo:backend", "request for unknown doc", doc_id[:6])
            return
        doc.apply_local_request(ChangeRequest.from_json(request_json))

    # ------------------------------------------------------------------
    # loading

    def _load_document(self, doc: DocBackend) -> None:
        cursor = self.cursors.get(self.id, doc.id)
        changes: List[Change] = []
        writable: Optional[str] = None
        for actor_id, max_seq in cursor.items():
            actor = self._get_or_create_actor(actor_id)
            if actor.writable and writable is None:
                writable = actor_id
            changes.extend(actor.changes_in_window(0, max_seq))
        if writable is None:
            writable = self._create_doc_actor(doc.id)
        root = root_actor_id(doc.id)
        root_actor = self.actors.get(root)
        if not changes and (root_actor is None or not root_actor.writable):
            # Unknown doc with no local history: gate readiness until the
            # root actor's first change replicates in (the reference's
            # minimumClock render gate, src/DocBackend.ts:90-113)
            doc.update_minimum_clock({root: 1})
        doc.init(changes, writable)
        # Feed announcements above can deliver blocks re-entrantly while
        # doc.opset is still None (so _sync_changes skipped them); the
        # cursor may also have grown via CursorMessages. Re-sync every
        # cursor actor now that the doc can apply changes.
        for actor_id in self.cursors.get(self.id, doc.id):
            actor = self.actors.get(actor_id)
            if actor is not None:
                self._sync_changes(actor)

    def _doc_feed_spec(
        self,
        doc_id: str,
        contiguous: Dict[str, bool],
        cursor: Optional[Dict[str, int]] = None,
    ):
        """(spec, clock, n_changes, actor_ids, ok) for a doc's cursor:
        sidecar windows per actor feed plus the contiguous-seq clock
        shortcut (clock[actor] = applied count is only sound when the
        feed's seqs are 1..n — gap-y feeds set ok=False and must take
        the safe per-op replay path). `contiguous` memoizes the per-feed
        verification across docs sharing an actor. Bulk callers pass the
        pre-fetched `cursor` (one SELECT for the whole load)."""
        if cursor is None:
            cursor = self.cursors.get(self.id, doc_id)
        spec = []
        clock: Dict[str, int] = {}
        n_changes = 0
        ok = True
        for actor_id, max_seq in cursor.items():
            actor = self._get_or_create_actor(actor_id)
            fc = actor.columns()
            good = contiguous.get(actor_id)
            if good is None:
                good = fc.seqs_contiguous()
                contiguous[actor_id] = good
                if not good:
                    log(
                        "repo:backend",
                        f"feed {actor_id[:6]} has non-contiguous "
                        "seqs; bulk clock shortcut unsafe",
                    )
            ok = ok and good
            spec.append((fc, 0, max_seq))
            applied = fc.changes_in_window(0, max_seq)
            n_changes += applied
            if applied > 0:
                clock[actor_id] = applied  # seqs contiguous 1..n
        return spec, clock, n_changes, list(cursor), ok

    def _gate_unknown_empty(self, doc: DocBackend) -> None:
        """No local history and no writable root: gate readiness until
        the root actor's first change replicates in (the reference's
        minimumClock render gate, src/DocBackend.ts:90-113)."""
        root = root_actor_id(doc.id)
        root_actor = self.actors.get(root)
        if root_actor is None or not root_actor.writable:
            doc.update_minimum_clock({root: 1})

    def _resync_cursor_actors(self, actor_ids, synced: set) -> None:
        """Blocks replicated while a (bulk or fast) load was in flight
        hit _sync_changes before the doc could apply; re-run now (cheap
        no-op when clocks already match), as _load_document does."""
        for actor_id in actor_ids:
            if actor_id in synced:
                continue
            synced.add(actor_id)
            actor = self.actors.get(actor_id)
            if actor is not None:
                self._sync_changes(actor)

    def _load_document_fast(self, doc: DocBackend) -> bool:
        """Sidecar-backed cold open of ONE doc: pack its feed windows and
        decode through the numpy kernel twin (ops/host_kernel.py) — no
        per-op host replay, no device dispatch/compile. Returns False
        (caller falls back to _load_document's replay) when a feed's
        sidecar can't serve the window (non-contiguous seqs).
        Replaces the reference's per-change Automerge replay for stored
        histories (src/RepoBackend.ts:238-257 -> DocBackend.init)."""
        if os.environ.get("HM_FAST_OPEN", "1") == "0":
            return False
        from ..ops.columnar import pack_docs_columns
        from ..ops.host_kernel import run_batch_host
        from ..ops.materialize import DecodedBatch, decode_patch

        spec, clock, n_changes, actor_ids, ok = self._doc_feed_spec(
            doc.id, {}
        )
        if not ok:
            return False
        writable = self._writable_actor_for(doc.id)
        if n_changes == 0:
            self._gate_unknown_empty(doc)
        batch = pack_docs_columns([spec])
        dec = DecodedBatch(batch, run_batch_host(batch))
        doc.init_deferred(
            loader=self._bulk_history_loader(doc.id),
            clock=clock,
            history_len=n_changes,
            actor_id=writable,
            snapshot_fn=lambda: decode_patch(dec, 0),
            quiet=False,
        )
        self.clocks.update(self.id, doc.id, clock)
        self._resync_cursor_actors(
            self.cursors.get(self.id, doc.id), set()
        )
        return True

    def load_documents_bulk(
        self, doc_ids: List[str], slab: Optional[int] = None,
        pad_docs: Optional[int] = None, pad_rows: Optional[int] = None,
    ) -> None:
        """Cold-start many docs with zero per-op host work (the north
        star, BASELINE config 4): each doc's feed windows come from the
        columnar sidecars (storage/colcache.py), pack vectorized
        (ops/columnar.py pack_docs_columns), and materialize in slab-sized
        device dispatches. Docs come up ready with host-verified clocks
        and lazily-decoded snapshot patches; the host OpSet reconstructs
        only when a doc takes its first incremental change
        (DocBackend.init_deferred). Contrast the reference's per-doc
        loadDocument replay loop (src/RepoBackend.ts:238-257).

        Host-side work is batched, not per-doc: one cursor upsert + one
        SELECT for all docs, one feed-registry executemany, one clock
        executemany, parallel sidecar loads, and per-actor syncs deferred
        to a single pass at the end. Device dispatches are async — the
        materialization barrier is `fetch_bulk_summaries`.

        `pad_docs`/`pad_rows` override the slab's jit bucket (benchmarks
        prime a [4096, N] executable with a small load)."""
        with telemetry.span(
            "pipeline.bulk_load", "pipeline", docs=len(doc_ids)
        ):
            return self._load_documents_bulk(
                doc_ids, slab, pad_docs, pad_rows
            )

    def _load_documents_bulk(
        self, doc_ids: List[str], slab: Optional[int],
        pad_docs: Optional[int], pad_rows: Optional[int],
    ) -> None:
        if slab is None:
            slab = int(os.environ.get("HM_BULK_SLAB", "4096"))
        with self._bulk_mutex:  # concurrent open_many calls serialize
            self._load_documents_bulk_locked(
                doc_ids, slab, pad_docs, pad_rows
            )

    def _load_documents_bulk_locked(
        self, doc_ids, slab, pad_docs, pad_rows
    ) -> None:
        from ..ops.columnar import pack_docs_columns
        from ..ops.materialize import DecodedBatch, decode_patch
        from .pipeline import pipeline_enabled

        # summaries are for the latest load: drop refs nobody fetched so
        # repeated open_many calls can't pin old slabs' host+device memory
        self._pending_summaries = []
        self._pending_memo = []
        stale = self._fetch_ctx
        self._fetch_ctx = None
        if stale is not None:
            # nobody ran the barrier for the previous load: settle its
            # fetch worker before dispatching a new pipeline (and don't
            # let a fetch error vanish with the discarded context)
            try:
                stale.join()
            except Exception as e:
                log("repo:backend", f"unfetched bulk load's fetch: {e}")

        now = time.perf_counter
        self._bulk_t0 = now()
        pipelined = pipeline_enabled()

        # -- phase 1: register docs + one bulk cursor upsert/select -----
        t0 = now()
        new_docs: List[DocBackend] = []
        already_ready: List[str] = []  # open docs: frontend may re-read
        with self._lock:
            for doc_id in doc_ids:
                existing = self.docs.get(doc_id)
                if existing is not None:
                    if existing._announced:
                        already_ready.append(doc_id)
                    continue
                doc = DocBackend(
                    doc_id, self._doc_notify, None, live=self.live
                )
                self.docs[doc_id] = doc
                new_docs.append(doc)
        # docs closed with store rows still in the debouncer must not
        # bulk-reload from the stale rows (same guard as open/destroy)
        self._settle_store_rows({d.id for d in new_docs})
        with self.db.bulk():
            self.cursors.add_actors(
                self.id, [(d.id, root_actor_id(d.id)) for d in new_docs]
            )
        cursor_map = self.cursors.get_multiple(
            self.id, [d.id for d in new_docs]
        )
        # stage breakdown (seconds; VERDICT r5 item 1). Serial mode:
        # each stage's wall time (they run back-to-back, so they sum to
        # the wall clock). Pipeline mode: each stage's BUSY time — the
        # stages overlap, so the wall clock is `wall_critical_path`,
        # ~max(stage) rather than sum(stages). t_fetch lands when the
        # materialization barrier runs.
        # rebinding the stats dict holds repo.stats (guard manifest,
        # analysis/guards.py): stage threads _stat_add concurrently
        # once the load streams, and bench/tools read the dict after
        with self._stats_lock:
            self.last_bulk_stats = {
                "docs": len(new_docs),
                "fast": 0,
                "memo": 0,
                "fallback": 0,
                "pipeline": 1 if pipelined else 0,
                "pack_workers": 0,  # serial twin: pack inline, no pool
                "t_sql": round(now() - t0, 3),
                "t_io": 0.0,
                "t_spec": 0.0,
                "t_pack": 0.0,
                "t_narrow": 0.0,
                "t_upload": 0.0,
                "t_dispatch": 0.0,
            }

        ready_ids: List[str] = []
        clock_rows: Dict[str, Dict[str, int]] = {}
        self._begin_bulk_actors()
        try:
            # -- phases 2-4: io -> spec -> pack -> dispatch, streamed
            # per slab (pipeline) or strictly staged (serial twin) -----
            load = (
                self._load_slabs_pipelined
                if pipelined
                else self._load_slabs_serial
            )
            memo_hits, fallback_docs = load(
                new_docs, cursor_map, slab, pack_docs_columns,
                DecodedBatch, decode_patch, ready_ids, clock_rows,
                pad_docs, pad_rows,
            )
            stats = self.last_bulk_stats
            stats["memo"] = len(memo_hits)
            stats["fallback"] = len(fallback_docs)
            stats["fast"] = len(new_docs) - len(fallback_docs)
            for (doc, spec, clock, n_changes, actor_ids), m in memo_hits:
                self._init_bulk_doc(
                    doc, clock, n_changes, actor_ids,
                    self._doc_snapshot_fn(spec, clock),
                    ready_ids, clock_rows,
                )
                self._pending_memo.append((doc.id, m))
            t0 = now()
            with self.db.bulk():
                self.clocks.update_many(self.id, clock_rows)
            self._stat_add("t_sql", now() - t0)
            for doc in fallback_docs:
                self._load_document(doc)
            if fallback_docs:
                log(
                    "repo:backend",
                    f"bulk load: {len(fallback_docs)}/{len(new_docs)} "
                    "docs fell back to per-op host replay "
                    "(non-contiguous feed seqs)",
                )
        except Exception:
            # a failed load must not pin device refs, leave the fetch
            # worker running unjoined, or hand the barrier a
            # half-fetched pending list. (A failure AFTER pipe.run —
            # clock write, fallback replay — still has a live fetch
            # worker; join it so no hm-pipe thread outlives the load
            # and any fetch error isn't silently dropped with it.)
            ctx = self._fetch_ctx
            self._pending_summaries = []
            self._pending_memo = []
            self._fetch_ctx = None
            self._bulk_t0 = None  # a later barrier must not stamp
            # wall_critical_path with this dead load's idle time
            if ctx is not None:
                try:
                    ctx.join()
                except Exception:
                    pass  # the load's own error is the one to raise
            raise
        finally:
            self._end_bulk_actors()
        if pipelined:
            # busy aliases: explicit names for consumers (bench JSON)
            # that want both views without knowing the mode
            with self._stats_lock:
                for k in (
                    "t_io", "t_spec", "t_pack", "t_narrow", "t_upload",
                    "t_dispatch",
                ):
                    self.last_bulk_stats[k + "_busy"] = (
                        self.last_bulk_stats.get(k, 0.0)
                    )
        # provisional: the barrier extends this through the fetch
        with self._stats_lock:
            self.last_bulk_stats["wall_critical_path"] = round(
                now() - self._bulk_t0, 3
            )
        ready_ids.extend(already_ready)
        if ready_ids:
            self.to_frontend.push(msgs.bulk_ready_msg(ready_ids))

    def _stat_add(self, key: str, dt: float) -> None:
        """Accumulate a stage timing into last_bulk_stats (pipeline
        stage threads add concurrently). Microsecond precision: the
        pipeline adds per-doc slivers (tens of µs from classify), and
        rounding each addition to ms would floor a whole stage to 0."""
        with self._stats_lock:
            s = self.last_bulk_stats
            s[key] = round(s.get(key, 0.0) + dt, 6)

    def _collect_cursor_actors(self, docs, cursor_map) -> List[str]:
        needed: List[str] = []
        seen: set = set()
        for d in docs:
            for actor_id in cursor_map[d.id]:
                if actor_id not in seen:
                    seen.add(actor_id)
                    needed.append(actor_id)
        return needed

    def _load_slabs_serial(
        self, new_docs, cursor_map, slab, pack_docs_columns,
        DecodedBatch, decode_patch, ready_ids, clock_rows,
        pad_docs, pad_rows,
    ):
        """The correctness twin (HM_PIPELINE=0): every stage finishes
        for ALL docs before the next begins — wall clock = sum(stages).
        Returns (memo_hits, fallback_docs)."""
        now = time.perf_counter

        # -- phase 2: open every cursor actor, per-feed work deferred ---
        t0 = now()
        needed = self._collect_cursor_actors(new_docs, cursor_map)
        actors = [self._get_or_create_actor(a) for a in needed]
        self._prefetch_columns(actors)
        self._stat_add("t_io", now() - t0)

        # -- phase 3: per-doc feed specs --------------------------------
        t0 = now()
        entries = []  # (doc, spec, clock, n_changes, actor_ids)
        contiguous: Dict[str, bool] = {}
        fallback_docs: List[DocBackend] = []
        for doc in new_docs:
            spec, clock, n_changes, actor_ids, ok = self._doc_feed_spec(
                doc.id, contiguous, cursor_map[doc.id]
            )
            if not ok:
                fallback_docs.append(doc)
                continue
            if n_changes == 0:
                self._gate_unknown_empty(doc)
            entries.append((doc, spec, clock, n_changes, actor_ids))
        self._stat_add("t_spec", now() - t0)

        # -- phase 3.5: clean docs (summary memo holds a row fetched
        # at this exact clock) skip pack/dispatch/transfer --------------
        memo_hits = []
        if self._summary_memo:
            fresh = []
            for e in entries:
                m = self._summary_memo.get(e[0].id)
                if m is not None and m["clock"] == e[2]:
                    memo_hits.append((e, m))
                else:
                    fresh.append(e)
            entries = fresh

        # -- phase 4: slab dispatches -----------------------------------
        self._load_slabs(
            entries, slab, pack_docs_columns, DecodedBatch,
            decode_patch, ready_ids, clock_rows, pad_docs, pad_rows,
        )
        return memo_hits, fallback_docs

    def _load_slabs_pipelined(
        self, new_docs, cursor_map, slab, pack_docs_columns,
        DecodedBatch, decode_patch, ready_ids, clock_rows,
        pad_docs, pad_rows,
    ):
        """Streamed phases 2-4: slab N+1's sidecar IO and native pack
        proceed while slab N is on-device and slab N-1's summary is in
        flight to host (backend/pipeline.py). Entry-group composition
        matches the serial twin exactly (slab-sized chunks of the
        post-memo-filter entry stream, in doc order), so both paths
        produce bit-identical summaries."""
        from ..ops.columnar import round_up_pow2
        from .pipeline import (
            FetchContext,
            SlabPipeline,
            pack_worker_count,
        )

        now = time.perf_counter
        contiguous: Dict[str, bool] = {}

        def prefetch(doc_chunk):
            t0 = now()
            needed = self._collect_cursor_actors(doc_chunk, cursor_map)
            actors = [self._get_or_create_actor(a) for a in needed]
            self._prefetch_columns(actors)
            self._stat_add("t_io", now() - t0)

        def classify(doc):
            t0 = now()
            try:
                spec, clock, n_changes, actor_ids, ok = (
                    self._doc_feed_spec(
                        doc.id, contiguous, cursor_map[doc.id]
                    )
                )
                if not ok:
                    return ("fallback", doc)
                if n_changes == 0:
                    self._gate_unknown_empty(doc)
                e = (doc, spec, clock, n_changes, actor_ids)
                m = self._summary_memo.get(doc.id)
                if m is not None and m["clock"] == clock:
                    return ("memo", (e, m))
                return ("entry", e)
            finally:
                self._stat_add("t_spec", now() - t0)

        def pack(chunk, seq):
            # rr / rr_cursor0 bind below, before the pipeline runs.
            # The device hint places a device pack (HM_DEVICE_PACK=1)
            # on the chip strict round-robin will dispatch slab `seq`
            # to, so the packed columns never cross chips; host packs
            # ignore it. Runs on a pack-pool worker (HM_PACK_WORKERS).
            t0 = now()
            batch = pack_docs_columns(
                [e[1] for e in chunk],
                n_docs=pad_docs or round_up_pow2(len(chunk)),
                n_rows=pad_rows,
                device=(
                    rr.pack_device_for(seq, rr_cursor0)
                    if rr is not None
                    else None
                ),
            )
            self._stat_add("t_pack", now() - t0)
            return batch

        def dispatch(chunk, batch):
            return self._dispatch_slab(
                chunk, batch, DecodedBatch, decode_patch,
                ready_ids, clock_rows,
            )

        stats = self.last_bulk_stats  # captured: the fetch worker can
        # outlive this load; its timings belong to THIS load's stats

        # mesh-aware accounting: the scheduler (built here, before any
        # dispatch, so the fetch stage can size itself) accumulates
        # per-chip dispatch busy time across loads — snapshot now, diff
        # after the run, so the stats carry THIS load's per-chip times
        rr = self._slab_rr()
        disp0 = list(rr.t_dispatch_chip) if rr is not None else None
        slabs0 = list(rr.slabs_per_chip) if rr is not None else None
        # round-robin cursor snapshot: with strict round-robin the chip
        # for slab seq is fully determined by (cursor at load start +
        # seq), so pack workers can place device packs ahead of dispatch
        rr_cursor0 = rr.cursor() if rr is not None else 0

        def fetch(entry):
            t0 = now()
            wire = entry[3]
            self._fetch_slab(entry)
            dt = now() - t0
            chip = None
            if rr is not None and hasattr(wire, "devices"):
                try:
                    chip = rr.device_index(next(iter(wire.devices())))
                except Exception:  # non-jax wire / foreign device
                    chip = None
            with self._stats_lock:
                stats["t_fetch_busy"] = round(
                    stats.get("t_fetch_busy", 0.0) + dt, 6
                )
                if chip is not None:
                    per = stats.setdefault(
                        "t_fetch_chips", [0.0] * len(rr.devices)
                    )
                    per[chip] = round(per[chip] + dt, 6)

        # fetch overlaps across chips: one worker per device (bounded —
        # each worker is host-side parse + one transfer at a time)
        workers = 1
        if rr is not None:
            workers = max(
                1,
                min(
                    len(rr.devices),
                    int(os.environ.get("HM_FETCH_WORKERS", "4")),
                ),
            )
        pipe = SlabPipeline(
            new_docs,
            prefetch=prefetch,
            classify=classify,
            pack=pack,
            dispatch=dispatch,
            fetch=fetch,
            slab=slab,
            fetch_workers=workers,
            pack_workers=pack_worker_count(),
        )
        ctx = FetchContext()
        try:
            memo_hits, fallbacks = pipe.run(ctx)
        finally:
            if self._rr_value is not None:
                # dispatching done: drop backpressure refs
                self._rr_value.release()
        with self._stats_lock:
            # pool shape + per-worker busy lanes: sum(busy) can exceed
            # the wall once packs overlap — profile_cold draws one lane
            # per worker and bench computes speedup = sum(busy)/wall
            stats["pack_workers"] = pipe.pack_workers
            stats["t_pack_busy_per_worker"] = [
                round(b, 6) for b in pipe.pack_busy
            ]
            stats["t_pack_wall"] = round(pipe.pack_wall(), 6)
        if rr is not None:
            with self._stats_lock:
                stats["t_dispatch_chips"] = [
                    round(b - a, 6)
                    for a, b in zip(disp0, rr.t_dispatch_chip)
                ]
                stats["slabs_per_chip"] = [
                    b - a for a, b in zip(slabs0, rr.slabs_per_chip)
                ]
        self._fetch_ctx = ctx
        return memo_hits, fallbacks

    def _fetch_slab(self, entry) -> None:
        """Transfer + parse one slab's summary wire (the fetch stage:
        runs on the pipeline's fetch worker so the barrier finds host
        arrays already decoded; idempotent for host-kernel slabs).

        This runs even for loads whose caller never hits the barrier
        (the frontend OpenBulk path) — deliberately: the parse swaps
        the pinned DEVICE wire buffer for a compact host dict, so a
        barrier-less cold open releases its device memory as the
        worker drains instead of pinning every slab's wire until the
        next load, and a late barrier is nearly free."""
        from ..ops.materialize import fetch_summary

        _ids, batch, _dec, wire, lean = entry
        if wire is None or isinstance(wire, dict):
            return
        nbytes = getattr(wire, "nbytes", 0)
        entry[3] = fetch_summary(wire, batch, lean)
        if nbytes:
            _M_D2H.add(nbytes)

    def _begin_bulk_actors(self) -> None:
        """Defer per-feed sqlite writes and actor syncs for the duration
        of a bulk load (each would otherwise be a per-feed round trip)."""
        with self._lock:
            self._bulk_feed_rows = []
            self._bulk_deferred_syncs = set()

    def _end_bulk_actors(self) -> None:
        with self._lock:
            rows = self._bulk_feed_rows or []
            deferred = self._bulk_deferred_syncs or set()
            self._bulk_feed_rows = None
            self._bulk_deferred_syncs = None
        if rows:
            with self.db.bulk():
                self.feed_info.save_many(
                    (f.public_key, f.discovery_id, f.writable)
                    for f in rows
                )
        for actor_id in deferred:
            actor = self.actors.get(actor_id)
            if actor is not None:
                self._sync_changes(actor)

    def _prefetch_columns(self, actors: List[Actor]) -> None:
        """Load every actor's columnar sidecar in parallel — the bulk of
        cold-start IO; file reads drop the GIL so threads overlap it."""
        from concurrent.futures import ThreadPoolExecutor

        if self._col_slab is not None:
            # hint the corpus slab's extents into the page cache first:
            # the decode loop below then slices warm pages (and, under
            # the pipeline, the NEXT chunk's hint overlaps this chunk's
            # pack)
            self._col_slab.prefetch([a.id for a in actors])
        big = [a for a in actors if a.feed.colcache is not None]
        if len(big) < 2:
            for a in actors:
                a.columns()
            return
        workers = min(16, int(os.environ.get("HM_LOAD_THREADS", "8")))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            list(pool.map(lambda a: a.columns(), actors))

    def _mesh(self):
        """The device mesh the bulk loader shards over, when >1 device is
        visible (HM_MESH=0 forces single-device). Cached per backend."""
        if getattr(self, "_mesh_cached", False):
            return self._mesh_value
        self._mesh_cached = True
        self._mesh_value = None
        if os.environ.get("HM_MESH", "1") != "0":
            try:
                import jax

                if len(jax.devices()) > 1:
                    from ..parallel.mesh import make_mesh

                    self._mesh_value = make_mesh()
            except Exception as e:  # no usable backend: host path only
                log("repo:backend", f"no mesh: {e}")
        return self._mesh_value

    def _load_slabs(
        self, entries, slab, pack_docs_columns, DecodedBatch,
        decode_patch, ready_ids, clock_rows, pad_docs=None, pad_rows=None,
    ) -> None:
        from ..ops.columnar import round_up_pow2

        # NOTE: in this serial twin, slab packing stays strictly
        # in-order on the calling thread. The streaming pipeline
        # (HM_PIPELINE=1, the default) runs the same pack on a worker
        # thread whose native hm_pack_prefix call drops the GIL, so it
        # overlaps the next slab's sidecar IO and the previous slab's
        # device work instead.
        for base in range(0, len(entries), slab):
            chunk = entries[base : base + slab]
            # bucket the doc axis (pow2) so every slab of a bulk load —
            # and every later bulk load — reuses one compiled executable
            t0 = time.perf_counter()
            batch = pack_docs_columns(
                [e[1] for e in chunk],
                n_docs=pad_docs or round_up_pow2(len(chunk)),
                n_rows=pad_rows,
            )
            self._stat_add("t_pack", time.perf_counter() - t0)
            self._dispatch_slab(
                chunk, batch, DecodedBatch, decode_patch,
                ready_ids, clock_rows,
            )

    def _dispatch_slab(
        self, chunk, batch, DecodedBatch, decode_patch,
        ready_ids, clock_rows,
    ):
        """One packed slab -> async device dispatch + deferred doc init.
        Returns the pending-summary entry (a mutable list: the pipeline
        fetch worker replaces its wire slot with parsed host arrays).
        Shared by the serial twin and the streaming pipeline, which
        only differ in WHEN stages run, never in what they compute."""
        from ..ops.crdt_kernels import run_batch_full
        from ..ops.host_kernel import run_batch_host

        # small loads aren't worth a device dispatch (let alone a fresh
        # per-bucket compile): under this many [D, N] cells the numpy
        # kernel twin wins outright
        min_cells = int(os.environ.get("HM_DEVICE_MIN_CELLS", "131072"))
        stats = self.last_bulk_stats
        # host clocks (authoritative, from sidecar metadata) for
        # every doc in the slab, padded docs empty — lets the device
        # path skip the seq wire entirely
        slab_clocks = [e[2] for e in chunk] + [{}] * (
            batch.n_docs - len(chunk)
        )
        t0 = time.perf_counter()
        lean = False
        if batch.n_docs * batch.n_rows < min_cells:
            out = run_batch_host(batch)
            summary = None
            self._stat_add("t_dispatch", time.perf_counter() - t0)
        else:
            from ..crdt.change import Action
            import numpy as np

            # no INC ops + host clocks in hand -> skip the seq and
            # value wires (~4 of 14 bytes/op on the tunnel) AND the
            # summary wire's clock section
            lean = not bool(
                np.any(batch.cols["action"] == int(Action.INC))
            )
            rr = self._slab_rr()
            mesh = self._mesh() if rr is None else None
            if rr is not None:
                # pipelined multi-chip: successive WHOLE slabs land on
                # successive devices (bounded in-flight queues per
                # device) — chips run independent programs instead of
                # lockstep sharded dispatches
                out, summary = rr.dispatch(batch, lean=lean)
                with self._stats_lock:
                    stats["rr_slabs"] = stats.get("rr_slabs", 0) + 1
                    stats.setdefault("rr_devices", len(rr.devices))
            elif mesh is not None:
                # multi-chip: THE same kernel, doc-sharded over dp
                # (parallel/sharded.py) — this is the v5e-8 path
                from ..parallel.sharded import sharded_full

                out, summary = sharded_full(batch, mesh, lean=lean)
                with self._stats_lock:
                    stats["sharded_slabs"] = (
                        stats.get("sharded_slabs", 0) + 1
                    )
            else:
                out, summary = run_batch_full(batch, lean=lean)
            from ..ops import crdt_kernels as _ck

            slab_narrow = _ck.last_args_timings.get("narrow", 0.0)
            slab_upload = _ck.last_args_timings.get("upload", 0.0)
            self._stat_add("t_narrow", slab_narrow)
            self._stat_add("t_upload", slab_upload)
            self._stat_add(
                "t_dispatch",
                time.perf_counter() - t0 - slab_narrow - slab_upload,
            )
            if os.environ.get("HM_ASYNC_SUMMARY_COPY", "1") != "0":
                # start the device->host copy of the ONE fused wire
                # buffer now so the barrier (fetch_bulk_summaries)
                # overlaps the transfer with later slabs' pack +
                # compute
                try:
                    summary.copy_to_host_async()
                except AttributeError:  # non-device backend
                    pass
        dec = DecodedBatch(batch, out, host_clocks=slab_clocks)
        entry = [[e[0].id for e in chunk], batch, dec, summary, lean]
        self._pending_summaries.append(entry)
        for j, (doc, _spec, clock, n_changes, actor_ids) in enumerate(
            chunk
        ):
            self._init_bulk_doc(
                doc, clock, n_changes, actor_ids,
                lambda dec=dec, j=j: decode_patch(dec.doc_view(j), 0),
                ready_ids, clock_rows,
            )
        return entry

    def _slab_rr(self):
        """Round-robin slab scheduler across visible devices (pipeline
        mode only; HM_SLAB_RR=0 restores mesh-sharded dispatch). The
        MODE gates re-evaluate on every call — the serial twin
        (HM_PIPELINE=0) must never round-robin even on a backend that
        already ran pipelined, and vice versa; only the device
        discovery / scheduler construction is cached (like _mesh).
        None when <2 devices or disabled."""
        from .pipeline import pipeline_enabled

        if (
            os.environ.get("HM_SLAB_RR", "1") == "0"
            or os.environ.get("HM_MESH", "1") == "0"
            or not pipeline_enabled()
        ):
            return None
        if self._rr_cached:
            return self._rr_value
        self._rr_cached = True
        self._rr_value = None
        try:
            import jax

            devices = jax.devices()
            if len(devices) > 1:
                from ..parallel.sharded import (
                    MeshBulkScheduler,
                    SlabRoundRobin,
                )

                try:
                    from ..parallel.mesh import make_mesh

                    # the mesh scheduler: identical streaming dispatch
                    # (whole slabs per chip, same kernels). Resident
                    # tracking OFF: the product barrier fetches per
                    # slab on the overlapped fetch workers, so the
                    # collective-reduction refs would pin every slab's
                    # device wire with no consumer.
                    self._rr_value = MeshBulkScheduler(
                        make_mesh(), track_resident=False
                    )
                except Exception:
                    self._rr_value = SlabRoundRobin(devices)
        except Exception as e:  # no usable backend: host path only
            log("repo:backend", f"no slab round-robin: {e}")
        return self._rr_value

    def fetch_bulk_summaries(self) -> "BulkSummaries":
        """The materialization barrier for the preceding bulk load(s):
        transfers every slab's fused summary wire buffer (winner/liveness
        masks bit-packed, element order at ceil(log2 N) bits/entry,
        narrow counts; clock section only on non-lean runs) to host —
        ONE device buffer per slab — and returns the decoded summaries.
        Docs the summary memo served (clock unchanged since their last
        fetch) transfer nothing. After this, any doc in the load renders
        host-side with no further device work. Clears the pending refs
        and refreshes the memo with the freshly fetched rows.

        Under the streaming pipeline (HM_PIPELINE=1) the fetch worker
        already transferred + parsed each slab's wire while later slabs
        were packing/dispatching; this barrier joins that worker (re-
        raising any fetch failure) and assembles host-side only —
        `t_fetch` records the residual (non-overlapped) wait, while
        `t_fetch_busy` holds the worker's busy time.

        Runs under `repo.bulk` (the guard of the pending accumulators,
        analysis/guards.py): a barrier racing a new load would
        otherwise swap the pending lists out from under each other —
        the load's stale-join path still covers barrier-less loads."""
        from ..ops.materialize import BulkSummaries

        with self._bulk_mutex:
            pending = self._pending_summaries
            memo_pending = self._pending_memo
            fetch_ctx = self._fetch_ctx
            wall_t0 = self._bulk_t0
            self._pending_summaries = []
            self._pending_memo = []
            self._fetch_ctx = None
            # one barrier per load — cleared up front so neither a
            # fetch failure below nor a later (empty) barrier call can
            # restamp the critical path with idle wall time
            self._bulk_t0 = None
            t0 = time.perf_counter()
            if fetch_ctx is not None:
                fetch_ctx.join()  # raises PipelineError on fetch failure
            out = BulkSummaries(
                pending, memo_slabs=self._memo_slabs(memo_pending)
            )
            self._memoize_summaries(out, pending, memo_pending)
        with self._stats_lock:
            self.last_bulk_stats["t_fetch"] = round(
                time.perf_counter() - t0, 3
            )
            if wall_t0 is not None:
                self.last_bulk_stats["wall_critical_path"] = round(
                    time.perf_counter() - wall_t0, 3
                )
        return out

    @staticmethod
    def _memo_cap_bytes() -> int:
        return (
            int(os.environ.get("HM_SUMMARY_MEMO_MB", "256")) * 1024 * 1024
        )

    @staticmethod
    def _memo_entry_bytes(m: Dict) -> int:
        return (
            m["mw_bits"].nbytes
            + m["el_bits"].nbytes
            + m["order"].nbytes
            + m["clock_row"].nbytes
            + 512  # dict/key overhead estimate
        )

    def _memo_slabs(self, memo_pending):
        """Memo-served docs as BulkSummaries memo groups (grouped by N
        so rows stack into one arrays dict per bucket)."""
        if not memo_pending:
            return []
        import numpy as np

        groups: Dict[tuple, List] = {}
        for doc_id, m in memo_pending:
            key = (m["N"], len(m["clock_row"]))
            groups.setdefault(key, []).append((doc_id, m))
        out = []
        from ..ops.crdt_kernels import unpack_bits_le

        for (N, _A), items in groups.items():
            def bits(key):
                return unpack_bits_le(
                    np.stack([m[key] for _d, m in items]), N
                )

            arrays = {
                "map_winner": bits("mw_bits"),
                "elem_live": bits("el_bits"),
                "elem_order": np.stack(
                    [m["order"] for _d, m in items]
                ).astype(np.int64),
                "n_live_elems": np.asarray(
                    [m["n_live"] for _d, m in items], np.int64
                ),
                "n_map_entries": np.asarray(
                    [m["n_map"] for _d, m in items], np.int64
                ),
                # the real [A_loc] local-slot clock rows, same columnar
                # contract as fetched slabs (arrays()['clock'])
                "clock": np.stack([m["clock_row"] for _d, m in items]),
            }
            out.append((
                [d for d, _m in items],
                arrays,
                [m["clock"] for _d, m in items],
            ))
        return out

    def _memoize_summaries(self, summaries, pending, memo_pending) -> None:
        """Refresh the per-doc summary memo from freshly fetched slab
        rows (byte-bounded LRU)."""
        cap = self._memo_cap_bytes()
        if cap <= 0:
            return
        import numpy as np

        memo = self._summary_memo
        for doc_id, m in memo_pending:  # served rows stay warm
            if doc_id in memo:
                memo.move_to_end(doc_id)
        for i, (doc_ids, batch, dec, _wire, _lean) in enumerate(pending):
            if dec.host_clocks is None:
                continue  # no authoritative clock: not memoizable
            arrays = summaries.slabs[i][2]
            N = batch.n_rows
            mwb = np.packbits(
                arrays["map_winner"], axis=1, bitorder="little"
            )
            elb = np.packbits(
                arrays["elem_live"], axis=1, bitorder="little"
            )
            odt = np.int16 if N < 2**15 else np.int32
            order = arrays["elem_order"].astype(odt)
            clock_arr = np.asarray(arrays["clock"], np.int32)
            for j, doc_id in enumerate(doc_ids):
                old = memo.pop(doc_id, None)
                if old is not None:
                    self._summary_memo_bytes -= self._memo_entry_bytes(
                        old
                    )
                entry = {
                    "clock": dict(dec.host_clocks[j]),
                    "N": N,
                    "n_live": int(arrays["n_live_elems"][j]),
                    "n_map": int(arrays["n_map_entries"][j]),
                    "mw_bits": mwb[j].copy(),
                    "el_bits": elb[j].copy(),
                    "order": order[j].copy(),
                    "clock_row": clock_arr[j].copy(),
                }
                memo[doc_id] = entry
                self._summary_memo_bytes += self._memo_entry_bytes(entry)
        while memo and self._summary_memo_bytes > cap:
            _d, old = memo.popitem(last=False)
            self._summary_memo_bytes -= self._memo_entry_bytes(old)

    def _init_bulk_doc(
        self, doc, clock, n_changes, actor_ids, snapshot_fn,
        ready_ids, clock_rows,
    ) -> None:
        """Shared deferred-init tail of the bulk load: resolve the
        writable actor, hand the doc its lazy snapshot, record its clock
        row, and mark it ready (minimum-clock-gated docs wait)."""
        writable = None
        for actor_id in actor_ids:
            a = self.actors.get(actor_id)
            if a is not None and a.writable:
                writable = actor_id
                break
        doc.init_deferred(
            loader=self._bulk_history_loader(doc.id),
            clock=clock,
            history_len=n_changes,
            actor_id=writable,
            snapshot_fn=snapshot_fn,
        )
        clock_rows[doc.id] = clock
        if doc._announced:
            ready_ids.append(doc.id)

    def _doc_snapshot_fn(self, spec, clock):
        """Lazy one-doc snapshot decode through the numpy kernel twin —
        memo-served docs have no slab DecodedBatch to decode from."""

        def snap():
            from ..ops.columnar import pack_docs_columns
            from ..ops.host_kernel import run_batch_host
            from ..ops.materialize import DecodedBatch, decode_patch

            batch = pack_docs_columns([spec])
            dec = DecodedBatch(
                batch, run_batch_host(batch), host_clocks=[dict(clock)]
            )
            return decode_patch(dec, 0)

        return snap

    def _bulk_history_loader(self, doc_id: str):
        """Deferred host replay for a bulk-loaded doc: decode the feed
        windows into Change objects only when the doc's first incremental
        change forces an OpSet to exist."""

        def load() -> List[Change]:
            cursor = self.cursors.get(self.id, doc_id)
            changes: List[Change] = []
            for actor_id, max_seq in cursor.items():
                actor = self._get_or_create_actor(actor_id)
                changes.extend(actor.changes_in_window(0, max_seq))
            return changes

        return load

    def _demoted_snapshot_fn(self, doc_id: str, clock: Dict[str, int]):
        """Ready/reopen snapshot closure for a doc the live engine
        DEMOTED back to lazy: decode the feed windows at the doc's
        serving clock through the numpy kernel twin — no host OpSet,
        no engine state. Falls back to a clamped OpSet replay when a
        sidecar can no longer serve the window (e.g. the feed was
        truncated out-of-band after demotion)."""

        def snap():
            from ..ops.columnar import pack_docs_columns
            from ..ops.host_kernel import run_batch_host
            from ..ops.materialize import DecodedBatch, decode_patch

            spec = self._serveable_spec(clock)
            if spec is not None:
                batch = pack_docs_columns([spec] if spec else [[]])
                dec = DecodedBatch(
                    batch,
                    run_batch_host(batch),
                    host_clocks=[dict(clock)],
                )
                return decode_patch(dec, 0)
            sub = OpSet()
            sub.apply_changes(
                [
                    c
                    for c in self._bulk_history_loader(doc_id)()
                    if c.seq <= clock.get(c.actor, 0)
                ]
            )
            return sub.snapshot_patch()

        return snap

    def _writable_actor_for(self, doc_id: str) -> str:
        cursor = self.cursors.get(self.id, doc_id)
        for actor_id in cursor:
            actor = self.actors.get(actor_id)
            if actor is not None and actor.writable:
                return actor_id
        return self._create_doc_actor(doc_id)

    def _create_doc_actor(self, doc_id: str) -> str:
        pair = keymod.create()
        self._init_actor(pair)
        self.cursors.add_actor(self.id, doc_id, pair.public_key)
        return pair.public_key

    def _ensure_writable_actor(self, doc: DocBackend) -> None:
        actor_id = self._writable_actor_for(doc.id)
        doc.set_actor_id(actor_id)

    def _grant_writer_actor(self, doc: DocBackend, writer: int) -> None:
        """Many-writer NeedsActorId: mint ONE fresh actor per writing
        connection (never claim an existing writable actor — after a
        worker respawn a reconnecting frontend may still be appending
        to it) and answer only that connection with a tagged ActorId.
        Does NOT call doc.set_actor_id — that fires an UNTAGGED
        broadcast ActorId event which every connection's frontend
        would adopt."""
        with self._lock:
            actor_id = self._writer_actors.get((doc.id, writer))
        if actor_id is None:
            minted = self._create_doc_actor(doc.id)
            with self._lock:
                # first mint wins a NeedsActorId race for the same
                # token; the loser's fresh actor stays registered but
                # unused (frontends send one NeedsActorId per doc)
                actor_id = self._writer_actors.setdefault(
                    (doc.id, writer), minted
                )
        msg = msgs.actor_id_msg(doc.id, actor_id)
        msg["writer"] = writer
        self.to_frontend.push(msg)

    def _drop_writer(self, writer: int) -> None:
        """A writing connection went away (hub detach): forget its
        per-doc actor grants and any parked Ready tokens. The actors
        themselves stay — their feeds hold acked history."""
        with self._lock:
            for key in [
                k for k in self._writer_actors if k[1] == writer
            ]:
                del self._writer_actors[key]
            for tokens in self._pending_ready.values():
                tokens.discard(writer)

    # ------------------------------------------------------------------
    # actors

    def _save_feed_info(self, feed) -> None:
        with self._lock:
            if self._bulk_feed_rows is not None:
                self._bulk_feed_rows.append(feed)  # row built at end
                return
        self.feed_info.save(
            feed.public_key, feed.discovery_id, feed.writable
        )

    def _save_actor_key(self, pair: keymod.KeyPair) -> None:
        """Persist a writable actor's keypair (keys table, by public
        key) so the feed stays writable across restarts — reopened
        docs keep appending to THEIR actor, and crash recovery can
        re-sign (seal) an orphaned unsigned tail."""
        if self._actor_keys.get(pair.public_key) is not None:
            return
        self.key_store.set(pair.public_key, pair)
        self._actor_keys[pair.public_key] = pair

    def _init_actor(self, pair: keymod.KeyPair) -> Actor:
        if pair.secret_key is not None:
            self._save_actor_key(pair)
        feed = self.feeds.create(pair)
        actor = Actor(
            feed, self._actor_notify, defer_cache=self._cache_syncs.mark
        )
        with self._lock:
            self.actors[actor.id] = actor
        self._save_feed_info(feed)
        if self.network is not None:
            self.network.announce_feed(feed)
        return actor

    def _peek_actor(self, actor_id: str) -> Optional[Actor]:
        """An actor by id WITHOUT materializing storage for unknown
        keys: unlike _get_or_create_actor this never registers or
        announces an EMPTY feed — a refused live adoption (missing /
        short / non-contiguous feed) must not pollute the store with
        phantom actor feeds. Returns None when no feed exists; a feed
        that DOES exist wraps through _get_or_create_actor (same
        construction, same race semantics — open_if_present has
        already registered it in the FeedStore, so no new storage is
        created)."""
        with self._lock:
            actor = self.actors.get(actor_id)
        if actor is not None:
            return actor
        if self.feeds.open_if_present(actor_id) is None:
            return None
        return self._get_or_create_actor(actor_id)

    def _serveable_spec(self, clock: Dict[str, int]):
        """[(FeedColumns, 0, end), ...] feed windows able to serve
        `clock` from the columnar sidecars, or None when any actor
        feed is absent, short, or non-contiguous. Non-creating
        (_peek_actor). THE shared serveability rule: live adoption,
        demotion eligibility, and the demoted snapshot closure all
        call this, so they can never disagree about what the sidecars
        can rebuild."""
        spec = []
        for actor_id, end in clock.items():
            if end <= 0:
                continue
            actor = self._peek_actor(actor_id)
            fc = actor.columns() if actor is not None else None
            if (
                fc is None
                or not fc.seqs_contiguous()
                or fc.n_changes < end
            ):
                return None
            spec.append((fc, 0, end))
        return spec

    def _get_or_create_actor(self, actor_id: str) -> Actor:
        with self._lock:
            actor = self.actors.get(actor_id)
        if actor is None:
            pair = self._actor_keys.get(actor_id)
            # a persisted secret key re-binds writability on reopen
            feed = (
                self.feeds.create(pair)
                if pair is not None
                else self.feeds.open_feed(actor_id)
            )
            actor = Actor(
                feed, self._actor_notify, defer_cache=self._cache_syncs.mark
            )
            with self._lock:
                self.actors[actor_id] = actor
            self._save_feed_info(feed)
            if self.network is not None:
                self.network.announce_feed(feed)
        return actor

    def _sync_changes(self, actor: Actor) -> None:
        """Feed caught new blocks: push the admissible window into every
        doc whose cursor includes this actor (reference syncChanges,
        src/RepoBackend.ts:506-531)."""
        for doc_id in self.cursors.docs_with_actor(self.id, actor.id):
            doc = self.docs.get(doc_id)
            if doc is None or not doc.can_apply:
                continue
            start = doc.clock.get(actor.id, 0)
            end = self.cursors.entry(self.id, doc_id, actor.id)
            window = actor.changes_in_window(start, end)
            if window:
                doc.apply_remote_changes(window)

    # ------------------------------------------------------------------
    # notifications from docs / actors

    def _settle_store_rows(self, doc_ids) -> None:
        """Block until the named docs' debounced store rows are durable
        (single id or a collection — bulk reopens settle in one pass).
        Cheap no-op unless a doc actually has rows in flight, so
        open/destroy don't stall behind unrelated traffic. A wedged
        flusher raises instead of returning: proceeding would reload
        from stale rows (open) or let a late flush resurrect rows the
        caller is about to delete (destroy)."""
        if isinstance(doc_ids, str):
            doc_ids = {doc_ids}
        deadline = time.monotonic() + 30.0
        while any(k[1] in doc_ids for k in self._stores.pending()):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    "store flusher failed to drain rows for docs "
                    f"{sorted(doc_ids)[:3]} within 30s"
                )
            # a False return only means the GLOBAL queue didn't drain;
            # this doc's rows may have landed — the loop re-checks
            self._stores.flush_now(timeout=min(remaining, 1.0))

    def _overlay_pending_rows(self, doc_id: str, cursor, clock, pend=None):
        """Overlay rows still inside the store debouncer onto values
        read back from the store, so advertisement paths (gossip,
        discovery) are read-your-writes: a gossip flush racing ahead of
        the store flush must NOT advertise a stale cursor — a peer that
        believes the stale seq never requests the newer blocks, and if
        no later change re-gossips, replication stalls permanently.
        Multi-doc callers pass one `pend` snapshot for the whole loop
        (pending() copies the dict under the debouncer cv each call)."""
        if pend is None:
            pend = self._stores.pending()
        if not pend:
            return cursor, clock
        cursor = dict(cursor)
        clock = dict(clock)
        for key, val in pend.items():
            if key[0] == "c" and key[1] == doc_id:
                for actor, seq in val.items():
                    if seq > clock.get(actor, 0):
                        clock[actor] = seq
            elif key[0] == "u" and key[1] == doc_id:
                actor = key[2]
                if val > cursor.get(actor, 0):
                    cursor[actor] = val
        return cursor, clock

    def _mark_clock_row(self, doc: DocBackend) -> None:
        """Queue the doc's (in-memory, authoritative) clock for the
        debounced store flush — a burst of patches costs one upsert."""
        if not self._store_debounce:
            self.clocks.update(self.id, doc.id, doc.clock)
            return
        self._stores.mark(("c", doc.id), doc.clock)

    def _mark_cursor_row(
        self, doc: DocBackend, actor_id: str, seq: int
    ) -> None:
        """Cursor twin of _mark_clock_row: HM_STORE_DEBOUNCE=0 must
        restore the synchronous write here too, or the 'debounce off'
        twin still flushes cursor rows asynchronously."""
        if not self._store_debounce:
            self.cursors.update(self.id, doc.id, {actor_id: seq})
            return
        self._stores.mark(("u", doc.id, actor_id), seq)

    def _flush_store_rows(self, batch: Dict) -> None:
        clocks: Dict[str, Dict[str, int]] = {}
        cursor_rows = []
        # remote peers' clock rows (cursor-gossip ingest), grouped by
        # the SENDER repo id the row is recorded under
        remote: Dict[str, Dict[str, Dict[str, int]]] = {}
        for key, val in batch.items():
            if key[0] == "c":
                clocks[key[1]] = val
            elif key[0] == "r":
                remote.setdefault(key[1], {})[key[2]] = val
            else:
                cursor_rows.append((key[1], key[2], val))
        # durability ordering: a clock row must never COMMIT ahead of
        # the feed bytes it describes (HM_FSYNC>=1 syncs dirty feed
        # logs here; tier 0 relies on recovery-on-open clamping
        # instead — storage/durability.py)
        with telemetry.span(
            "storage.store_flush", "storage", rows=len(batch)
        ):
            self.durability.barrier()
            with self.db.bulk():
                if clocks:
                    self.clocks.update_many(self.id, clocks)
                if cursor_rows:
                    self.cursors.update_many_rows(self.id, cursor_rows)
                for rid, docs in remote.items():
                    self.clocks.update_many(rid, docs)

    def _doc_notify(self, event: Dict[str, Any]) -> None:
        t = event["type"]
        doc: DocBackend = event["doc"]
        if t in ("LocalPatch", "RemotePatch") and self.serve is not None:
            # serving invalidation hook: every patch emission — host
            # paths AND live-engine ticks (_emit_tick notifies through
            # here) — moves the doc's serving clock, so its resident
            # read entry can never serve again. Bookkeeping only
            # (this runs under the emission lock).
            self.serve.note_clock_moved(doc.id)
        if t == "DocReady":
            self._send_ready(doc)
        elif t == "LocalPatch":
            change: Change = event["change"]
            actor = self.actors.get(change.actor)
            if actor is not None and actor.writable:
                actor.write_change(change)
                if self.durability.tier == 1 and (
                    self.durability.ack_durable
                ):
                    # HM_ACK_DURABLE=1: the echo below is a DURABLE
                    # ack — wait for the WAL group commit covering the
                    # append. Runs under THIS doc's emission domain
                    # only (doc.emit may block); concurrent writers'
                    # waits share the leader's one fsync per HM_WAL_MS
                    # window.
                    self.durability.commit_ack()
            else:
                log("repo:backend", "no writable actor for", change.actor[:6])
            self._mark_clock_row(doc)
            self._mark_cursor_row(doc, change.actor, change.seq)
            self.to_frontend.push(
                msgs.patch_msg(
                    doc.id, event["patch"].to_json(), doc.history_len
                )
            )
            self._gossip_cursor(doc)
        elif t == "RemotePatch":
            self._mark_clock_row(doc)
            self.to_frontend.push(
                msgs.patch_msg(
                    doc.id, event["patch"].to_json(), doc.history_len
                )
            )
            # our applied clock advanced: re-gossip so peers BEYOND the
            # source learn it too (relay re-serving — a passive middle
            # repo must propagate actor knowledge, reference
            # src/RepoBackend.ts:394-427). Monotone, so it terminates.
            self._gossip_cursor(doc)
        elif t == "ActorId":
            self.to_frontend.push(
                msgs.actor_id_msg(doc.id, event["actorId"])
            )

    def _send_ready(
        self, doc: DocBackend, writer: Optional[int] = None
    ) -> None:
        def push(patch) -> None:
            self._mark_clock_row(doc)
            patch_json = patch.to_json() if patch else None
            # many-writer plane: serve every parked writer token (plus
            # the direct re-opener) a PER-CONNECTION Ready carrying the
            # actor granted to THAT connection (None -> the frontend
            # opens read-mode and mints via NeedsActorId on first
            # write). Rank-legal under doc.emission: doc.emit ranks
            # below repo in analysis/hierarchy.py.
            with self._lock:
                tokens = self._pending_ready.pop(doc.id, set())
                if writer is not None:
                    tokens.add(writer)
                grants = {
                    t: self._writer_actors.get((doc.id, t))
                    for t in tokens
                }
            for token, actor_id in sorted(grants.items()):
                msg = msgs.ready_msg(
                    doc.id, actor_id, patch_json, doc.history_len
                )
                msg["writer"] = token
                self.to_frontend.push(msg)
            if tokens:
                # tagged mode: an extra UNTAGGED Ready would broadcast
                # doc.actor_id to every connection (actor collision)
                return
            self.to_frontend.push(
                msgs.ready_msg(
                    doc.id,
                    doc.actor_id,
                    patch_json,
                    doc.history_len,
                )
            )

        # Ready atomicity is PER DOC since the write-plane split:
        # holding this doc's emission domain across {snapshot -> push}
        # means no tick, local echo, or remote handler can slip a patch
        # for a NEWER state of THIS doc ahead of the Ready in the
        # frontend queue (a pending frontend drops pre-Ready patches).
        # Both the engine path (live.snapshot_patch re-enters the same
        # re-entrant domain) and the host twin hold only this one
        # domain — disjoint docs' Readys and emissions run in parallel.
        # Cross-doc re-entry (a frontend callback dispatched from doc
        # A's patch push Opens doc B on the same thread) must NOT nest
        # B's domain under A's: park the Ready on the deferred-emission
        # worker. Safe to delay — the frontend stays pending and drops
        # pre-Ready patches, so the deferred Ready still delivers a
        # full snapshot.
        from . import emission

        if emission.entered_other(doc.id):
            emission.defer(lambda: self._send_ready(doc, writer=writer))
            return
        with doc.emission:
            if self.live is not None:
                patch = self.live.snapshot_patch(doc)
                if patch is not None:
                    push(patch)
                    return
            push(doc.snapshot_patch())

    def _actor_notify(self, event: Dict[str, Any]) -> None:
        t = event["type"]
        actor: Actor = event["actor"]
        if t == "ActorSync":
            with self._lock:
                if self._bulk_deferred_syncs is not None:
                    # Bulk load in flight. Doc windows pack AFTER actor
                    # creation, so creation-time syncs have nothing to
                    # deliver — drop them instead of a per-feed query
                    # storm. Appends landing mid-load (replication) are
                    # deferred to one pass at the end.
                    if event.get("origin") == "append":
                        self._bulk_deferred_syncs.add(actor.id)
                    return
            if event.get("origin") == "append":
                # replicated appends arrive in bursts: coalesce the
                # idempotent window-application per actor
                self._syncs.mark(actor.id)
            else:
                self._sync_changes(actor)
        elif t == "Download":
            for doc_id in self.cursors.docs_with_actor(self.id, actor.id):
                self.to_frontend.push(
                    msgs.download_msg(
                        doc_id,
                        actor.id,
                        event["index"],
                        event["size"],
                        event["time"],
                    )
                )
        # ActorInitialized: nothing extra — feeds announce via network hook

    # ------------------------------------------------------------------
    # queries

    def _service_signals(self) -> Dict[str, float]:
        """The overload controller's pressure feed, all from numbers
        the repo already measures: serve read p99 over the tick
        window, admission-queue occupancy, WAL fsync debt over its
        rotation budget. Runs on the controller ticker (~20 Hz)."""
        sig = {"p99_s": 0.0, "queue_frac": 0.0, "debt_frac": 0.0}
        serve = self.serve
        if serve is not None:
            if self._serve_p99 is not None:
                sig["p99_s"] = self._serve_p99.quantile(0.99)
            b = serve._batcher
            if b._cap > 0:
                sig["queue_frac"] = b.depth / b._cap
        wal = self.durability.wal
        if wal is not None:
            sig["debt_frac"] = wal.fsync_debt() / max(1, wal._max_bytes)
        return sig

    def read_doc(
        self, doc_id: str, query: Dict[str, Any], cb: Callable[[Any], None]
    ) -> None:
        """One read through the serving tier (HM_SERVE=1) or the
        per-request host twin (HM_SERVE=0). `cb(payload)` may fire on
        the tier's batcher thread; payload None = unknown doc / not
        ready. A read NEVER creates state: a doc id with no stored
        cursor answers None instead of materializing a phantom doc.
        The service plane's front door is HERE — every read, IPC or
        in-process, passes the same admission check; a refused read
        answers the typed {"overload": ...} payload, never an error
        and never silence."""
        if self.overload is not None:
            refusal = self.overload.admit_read(query.get("tenant"))
            if refusal is not None:
                cb(refusal)
                return
        doc = self.docs.get(doc_id)
        if doc is None:
            if not self.cursors.get(self.id, doc_id):
                cb(None)
                return
            try:
                doc = self.open(doc_id)
            except Exception as e:
                log("repo:backend", f"read open {doc_id[:6]}: {e}")
                cb(None)
                return
        if self.serve is not None:
            self.serve.read_async(doc, query, cb)
            return
        from ..serve.tier import host_read

        cb(host_read(doc, query))

    def telemetry_payload(self) -> Dict[str, Any]:
        """The Telemetry query's reply — ONE assembly for every seam
        that answers it (handle_query here, tools/serve.py's --ipc
        QueryServer): the process-wide registry snapshot + trace state
        (tools/top.py's rate feed) plus THIS backend's per-doc
        read-serving residency block (tools/ls.py's residency=
        column)."""
        payload = telemetry.query_payload()
        if self.serve is not None:
            payload["serve"] = self.serve.residency_report()
        if self.overload is not None:
            # the service plane's attributable state: ladder rung,
            # pressure, per-tenant quota table (tools/top.py
            # [service], tools/ls.py service=, bench gating)
            payload["service"] = self.overload.report()
        if self.network is not None:
            # DHT introspection (DhtSwarm.discovery_report: node id,
            # bucket occupancy, records, joined posture) for
            # tools/meta.py --dht and the tools/ls.py header
            dht = self.network.discovery_report()
            if dht is not None:
                payload["dht"] = dht
            # per-doc swarm view for the tools/ls.py peers=/announce=
            # columns: connected peers replicating each open doc, and
            # whether the doc's feeds are joined (announced/looked-up).
            # Built entirely from the cursor MIRROR + memoized
            # discovery ids: Telemetry is polled ~1/s by tools/top.py,
            # and a per-doc SQL query + per-actor sha1 would put
            # O(docs x peers) work on every poll of a fleet daemon.
            docs_net: Dict[str, Any] = {}
            joined = self.network.joined
            repl = self.network.replication
            # docs on RECORD, not just open ones: a fleet daemon
            # (hydrate_feeds) serves docs no frontend ever opened
            doc_ids = set(self.docs.keys())
            doc_ids.update(self.clocks.all_doc_ids(self.id))
            for doc_id in doc_ids:
                dids = [
                    _discovery_id_cached(a)
                    for a in self.cursors.get(self.id, doc_id)
                ]
                peers: set = set()
                for d in dids:
                    peers.update(repl.peers_with_feed(d))
                docs_net[doc_id] = {
                    "peers": len(peers),
                    "announced": any(d in joined for d in dids),
                }
            payload["net"] = {"docs": docs_net}
        return payload

    def handle_query(self, query_id: int, query: Dict[str, Any]) -> None:
        t = query["type"]
        if t == "Read":
            # async: the tier's batcher thread pushes the Reply, so a
            # steady-state read never stalls the backend message pump
            # (queue callbacks are serialized) while a batch
            # coalesces. At admission overflow (HM_SERVE_QUEUE full)
            # the refused read IS answered inline on this thread —
            # deliberate backpressure: the overloading reader pays
            # the host-path cost instead of growing an unbounded
            # queue.
            self.read_doc(
                query["id"],
                query.get("query") or {},
                lambda payload: self.to_frontend.push(
                    msgs.reply_msg(query_id, payload)
                ),
            )
            return
        if t == "Materialize":
            doc = self.docs.get(query["id"])
            patch = (
                doc.history_patch(query["history"])
                if doc is not None
                else None
            )
            payload = patch.to_json() if patch is not None else None
            self.to_frontend.push(msgs.reply_msg(query_id, payload))
        elif t == "Metadata":
            doc = self.docs.get(query["id"])
            if doc is None:
                # Not an open doc: maybe a hyperfile in the ledger
                # (reference src/RepoBackend.ts:560-568 consults Metadata).
                payload = self.meta.file_metadata(query["id"])
            else:
                payload = {
                    "type": "Document",
                    "clock": clockmod.clock_to_strs(doc.clock),
                    "actors": self.cursors.actors_for(self.id, doc.id),
                    "history": doc.history_len,
                }
            self.to_frontend.push(msgs.reply_msg(query_id, payload))
        elif t == "Telemetry":
            self.to_frontend.push(
                msgs.reply_msg(query_id, self.telemetry_payload())
            )
        else:
            self.to_frontend.push(msgs.reply_msg(query_id, None))

    # ------------------------------------------------------------------
    # peer messaging + gossip (fully wired by net/, M7)

    def send_doc_message(self, doc_id: str, contents: Any) -> None:
        if self.network is not None:
            self.network.broadcast_doc_message(doc_id, contents)

    def deliver_doc_message(self, doc_id: str, contents: Any) -> None:
        """Inbound ephemeral message from a peer."""
        self.to_frontend.push(msgs.doc_message_fwd_msg(doc_id, contents))

    def on_cursor_message(
        self,
        peer,
        doc_id: str,
        cursors: clockmod.Clock,
        clocks: clockmod.Clock,
    ) -> None:
        """Peer told us which actors (and how far) a doc includes: expand
        our cursor, gate rendering on their clock, open missing feeds
        (reference src/RepoBackend.ts:394-427). The peer's clock is
        recorded under the SENDER's id — our own clock row only ever
        reflects changes we actually applied (else we'd advertise state we
        can't supply to third parties)."""
        before = self.cursors.get(self.id, doc_id)
        if self._store_debounce:
            # hot ingest path (a fleet doc gossips one actor per
            # peer): merge the write-through MIRROR now, ride the
            # debounced flusher for the sqlite rows — one executemany
            # per window instead of O(actors) per inbound frame
            after = self.cursors.merge_mem(self.id, doc_id, cursors)
            for a, s in cursors.items():
                self._stores.mark(("u", doc_id, a), s)
            self._stores.mark(("r", peer.id, doc_id), dict(clocks))
        else:
            after = self.cursors.update(self.id, doc_id, cursors)
            self.clocks.update(peer.id, doc_id, clocks)
        doc = self.docs.get(doc_id)
        if doc is not None:
            doc.update_minimum_clock(clocks)
        for actor_id in cursors:
            actor = self._get_or_create_actor(actor_id)
            self._sync_changes(actor)
        if after != before:
            # our cursor EXPANDED from remote knowledge: relay it to
            # the other peers (strictly monotone — no gossip loop)
            self._gossip.mark(doc_id)

    def on_discovery(self, public_id: str, peer) -> None:
        """A feed shared with `peer` was discovered: send our cursor +
        clock for every doc that includes that actor (reference
        src/RepoBackend.ts:374-392)."""
        pend = self._stores.pending()  # one snapshot for the loop
        for doc_id in self.cursors.docs_with_actor(self.id, public_id):
            # an open doc's in-memory clock is authoritative (and
            # fresher than its debounced store row); the store read is
            # the cold-doc fallback only — discovery fires once per
            # (feed, peer) and a fleet doc has O(peers) feeds, so a
            # SQL query here lands on the hottest wiring path
            doc = self.docs.get(doc_id)
            clock = (
                dict(doc.clock) if doc is not None
                else self.clocks.get(self.id, doc_id)
            )
            cursor, clock = self._overlay_pending_rows(
                doc_id,
                self.cursors.get(self.id, doc_id),
                clock,
                pend=pend,
            )
            self.network.send_cursor_to(peer, doc_id, cursor, clock)

    def send_sweep_cursors(self, peer, public_ids) -> None:
        """Anti-entropy cursor repair (ReplicationManager.on_sweep):
        re-send our cursor+clock for every doc sharing an actor with
        `peer` — ONE cursor frame per doc per sweep, iterated doc-side
        (O(docs) store reads) rather than feed-side (a fleet doc
        carries one placeholder actor per peer, so per-feed iteration
        is O(peers) SQL per sweep). Idempotent latest-state: this is
        what bounds the staleness of a bounded-fanout cursor gossip
        the peer wasn't sampled into (net/discovery/gossip.py)."""
        if self.network is None or self._closed:
            return
        pks = set(public_ids)
        pend = self._stores.pending()
        doc_ids = set(self.docs.keys())
        doc_ids.update(self.clocks.all_doc_ids(self.id))
        for doc_id in doc_ids:
            cursor = self.cursors.get(self.id, doc_id)
            if not pks.intersection(cursor):
                continue
            doc = self.docs.get(doc_id)
            clock = (
                dict(doc.clock) if doc is not None
                else self.clocks.get(self.id, doc_id)
            )
            cursor, clock = self._overlay_pending_rows(
                doc_id, cursor, clock, pend=pend,
            )
            self.network.send_cursor_to(peer, doc_id, cursor, clock)

    def _gossip_cursor(self, doc: DocBackend) -> None:
        self._gossip.mark(doc.id)

    def _flush_gossip(self, doc_ids) -> None:
        if self.network is None or self._closed:
            return
        fresh = self._gossip_fresh
        pend = self._stores.pending()  # one snapshot for the loop
        for doc_id in doc_ids:
            # an open doc's in-memory clock is fresher than its store
            # row (clock rows flush debounced — _flush_store_rows)
            doc = self.docs.get(doc_id) if fresh else None
            clock = (
                doc.clock if doc is not None
                else self.clocks.get(self.id, doc_id)
            )
            cursor, clock = self._overlay_pending_rows(
                doc_id, self.cursors.get(self.id, doc_id), clock,
                pend=pend,
            )
            self.network.gossip_cursor(doc_id, cursor, clock)

    def _announce_file_feed(self, feed) -> None:
        """File feeds replicate like any feed (reference
        src/ReplicationManager.ts:71-89): persist + join + announce so
        peers holding (or wanting) the file can sync it."""
        self._save_feed_info(feed)
        if self.network is not None:
            self.network.announce_feed(feed)

    def _forget_file_feed(self, feed) -> None:
        """Undo _announce_file_feed for a speculative remote open that
        fetched nothing (the FeedStore entry is already removed)."""
        self.feed_info.delete(feed.public_key)
        if self.network is not None:
            self.network.leave(feed.discovery_id)

    def get_file_store(self) -> FileStore:
        """The repo's FileStore, swarm-wired for remote fetch; created
        on first use (with or without an HTTP file server)."""
        if self.file_store is None:
            self.file_store = FileStore(
                self.feeds,
                announce=self._announce_file_feed,
                forget=self._forget_file_feed,
                remote_capable=lambda: self.network is not None,
            )
            # Completed uploads flow into the durable metadata ledger
            # (reference src/RepoBackend.ts:105-107 → Metadata.addFile).
            self.file_store.write_log.subscribe(
                lambda header: self.meta.add_file(
                    header.url, header.size, header.mime_type
                )
            )
        return self.file_store

    def start_file_server(self, path: str) -> None:
        from ..files.file_server import FileServer

        if self._file_server is not None:
            raise RuntimeError(
                "file server already listening; one per repo backend"
            )
        self.get_file_store()
        self._file_server = FileServer(self.file_store)
        self._file_server.listen(path)
        self.to_frontend.push(msgs.file_server_ready_msg(path))

    def set_swarm(self, swarm, join_options=None) -> None:
        from ..net.network import Network  # local import: net dep optional

        if self.network is None:
            self.network = Network(self)
        self.network.set_swarm(swarm, join_options)

    # ------------------------------------------------------------------

    def _flush_syncs(self, actor_ids) -> None:
        if self._closed:
            return
        for actor_id in actor_ids:
            actor = self.actors.get(actor_id)
            if actor is not None:
                self._sync_changes(actor)

    def close(self) -> None:
        self._closed = True
        # a barrier-less bulk load (frontend OpenBulk) may still have a
        # fetch worker draining device buffers: settle it before the
        # feeds / slab mmap / sqlite it indirectly depends on go away,
        # and surface (as a log) any error nobody barriered to see
        with self._bulk_mutex:
            ctx = self._fetch_ctx
            self._fetch_ctx = None
        if ctx is not None:
            try:
                ctx.join()
            except Exception as e:
                log("repo:backend", f"bulk fetch at close: {e}")
        if self.overload is not None:
            self.overload.close()  # stop the ticker before the tier
        if self.serve is not None:
            self.serve.close()  # drains: in-flight reads answer first
        if self.live is not None:
            self.live.close()  # drains: final tick patches still emit
        self._gossip.close()
        self._syncs.close()
        self._cache_syncs.close()  # drains: sidecars durable on close
        self._stores.close()  # drains AFTER patch sources: last rows land
        if self._file_server is not None:
            self._file_server.close()
            self._file_server = None
        if self.network is not None:
            self.network.close()
        self.feeds.close()
        # final group fsync while files exist; a FAILED final sync
        # leaves the crash marker in place so the next open recovers
        durable = self.durability.close()
        if self._col_slab is not None:
            self._col_slab.close()
        self.db.close()
        if (
            durable
            and self._dirty_marker is not None
            and os.path.exists(self._dirty_marker)
        ):
            # clean close: every flusher drained, every store closed —
            # the next open skips crash recovery
            from ..storage.faults import io_remove

            io_remove(self._dirty_marker)
