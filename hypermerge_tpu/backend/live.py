"""Live apply engine — incremental changes as per-tick device batches.

The reference applies every incoming change through the pure-Python
CRDT backend, one doc at a time — and a bulk-loaded doc first pays a
FULL host replay of its history the moment one live edit arrives
(DocBackend._ensure_opset). This module routes the live path through
the same batching argument the cold open already won: each hot doc's
packed columnar op history stays cached host-side (ops/columnar.py
LiveColumns — appendable, no feed IO, no repack), and a short tick
coalesces all dirty docs' newly arrived changes into ONE padded,
shape-bucketed, vmapped kernel dispatch (ops/crdt_kernels.py
materialize_live_device, or its numpy twin below the device-min-cells
threshold). A burst of N edits across M docs costs O(ticks) device
programs, not O(N) Python replays.

Adoption (a bulk-loaded doc going hot) is lock-free: the O(doc) build
(pack from sidecars, exact-size host kernel, lane-driven vectorized
decode, winner-lane reachability) runs WITHOUT the engine lock —
other hot docs keep ticking — and installs under it with a recheck
(opset still None, serving clock unmoved, doc still open).

Since the write-plane split (backend/emission.py) the engine lock is
tick/dirty-set COORDINATION only. Emission ordering is PER DOC: every
{compute patch -> feed append -> push} pair holds its own doc's
`doc.emit` emission domain and nothing else ordered — disjoint docs'
edits (and their durable WAL commits) proceed in parallel on
different writer threads, and `lock.held_blocking_ms.live_engine`
reads zero at every HM_FSYNC tier. The tick resolves each dirty doc
with a GIL-atomic table snapshot and takes ONE domain at a time;
catch-up kernel groups batch ACROSS docs with no locks held (the
per-doc install-and-recheck discards a result the doc outran).
HM_LIVE_MAX_BYTES byte-bounds resident LiveColumns: least-recently-
ticked idle docs demote back to the lazy path after a tick and
re-adopt from the sidecars on their next live change (demotion
refuses docs whose state the sidecars cannot rebuild).

Twin semantics (HM_LIVE=0 keeps the host-OpSet path):
- causal admission (seq continuity + deps) mirrors OpSet's pending set
  change-for-change, so clocks are bit-identical;
- local changes resolve intents against the engine's decoded state and
  emit patches bit-identical to OpSet.apply_local_request (a local op
  always wins: its lamport counter is the doc maximum);
- remote changes surface as ONE state-delta patch per tick per doc —
  the same final frontend state as the host path's per-window patches
  (per-op intermediate diffs are coalesced away), pinned by the fuzz
  twin test (tests/test_live.py);
- snapshot patches (Ready, reopen) diff the decoded state against an
  empty doc and are bit-identical to OpSet.snapshot_patch.

Host OpSet reconstruction remains only behind the explicit history /
time-travel APIs (DocBackend.materialize_at / history_patch).
"""

from __future__ import annotations

import gc
import os
import threading
import time
from contextlib import contextmanager
from itertools import repeat
from typing import Any, Dict, List, NamedTuple, Optional, Set, Tuple

import numpy as np

from ..analysis.lockdep import make_lock, make_rlock
from ..crdt.change import (
    HEAD,
    OBJ_TYPE_BY_MAKE,
    ROOT,
    Action,
    Change,
    ChangeRequest,
    Op,
    OpId,
)
from ..crdt.patch import Conflict, Diff, Patch
from ..ops.columnar import LiveColumns
from ..utils.debounce import Debouncer
from ..utils.debug import log
from .. import telemetry

ROOT_ID = "0@_root"

# engine stats series (telemetry registry, labeled per engine). The
# key lists drive both the handle table and the `stats` property, so
# the dict shape bench.py/tests read stays exactly the pre-telemetry
# one: event counts first, then the resident gauges, then seconds.
_LIVE_COUNTS = (
    "adopted", "refused", "ticks", "tick_docs", "tick_changes",
    "inc_changes", "kernel_runs", "device_dispatches",
    "local_changes", "adopt_retries", "demoted", "readopted",
)
_LIVE_GAUGES = ("live_bytes", "live_docs")
_LIVE_TIMES = (
    "t_live_append", "t_live_apply", "t_live_kernel",
    "t_live_decode", "t_live_diff",
    "t_adopt_pack", "t_adopt_kernel", "t_adopt_decode",
    "t_adopt_reach", "t_adopt_lock_free", "t_adopt_lock_held",
)


def _tick_window_s() -> float:
    return float(os.environ.get("HM_LIVE_TICK_MS", "2")) / 1e3


def _tick_window_max_s() -> float:
    return float(os.environ.get("HM_LIVE_TICK_MAX_MS", "25")) / 1e3


def _device_min_cells() -> int:
    return int(os.environ.get("HM_DEVICE_MIN_CELLS", "131072"))


def _inc_budget_cells() -> int:
    """Incremental-vs-kernel crossover for one doc's tick: apply
    directly when tick_ops x doc_rows stays under this (the per-op
    live-index scans cost O(rows); the kernel's vectorized rebuild has
    a fixed overhead that only amortizes on big catch-ups)."""
    return int(os.environ.get("HM_LIVE_INC_BUDGET", "2000000"))


# ---------------------------------------------------------------------------
# decoded doc state (OpId space — stable across repacks/ticks)


class _Val(NamedTuple):
    """One visible value op at a location. A NamedTuple: the decode
    builds one per visible row (hundreds of thousands on adoption) and
    tuple construction runs in C — same argument as OpId."""

    base: Any
    link: bool
    datatype: Any


class _Obj:
    __slots__ = ("type", "fields", "order")

    def __init__(self, type_: str) -> None:
        self.type = type_
        # map/table: key -> {OpId: _Val}; list/text: elem OpId -> {...}
        # (an elem whose dict is empty is a TOMBSTONE — it stays in
        # `order` and `fields`, exactly like OpSet, because remote RGA
        # inserts may reference it and the skip-scan walks it)
        self.fields: Dict[Any, Dict[OpId, _Val]] = {}
        self.order: List[OpId] = []  # ALL elems in RGA order

    @property
    def is_sequence(self) -> bool:
        return self.type in ("list", "text")

    def live(self) -> List[OpId]:
        return [e for e in self.order if self.fields.get(e)]


class _DocState:
    __slots__ = ("objs", "inc", "reachable")

    def __init__(self) -> None:
        self.objs: Dict[OpId, _Obj] = {ROOT: _Obj("map")}
        self.inc: Dict[OpId, int] = {}
        # objects whose CURRENT contents the frontend holds (emitted as
        # winner links). An object re-attached after mutating while
        # detached re-emits create + full contents (create resets the
        # frontend's copy), keeping frontends self-healing.
        self.reachable: Set[OpId] = set()


def _op_value(state: _DocState, opid: OpId, val: _Val):
    """(display value, link, datatype) — OpSet._op_value twin."""
    if val.link:
        return str(opid), True, None
    if val.datatype == "counter":
        base = val.base or 0
        return base + state.inc.get(opid, 0), False, "counter"
    return val.base, False, val.datatype


def _conflicts(state: _DocState, cell: Dict[OpId, _Val], winner: OpId):
    return tuple(
        Conflict(str(oid), *_op_value(state, oid, cell[oid]))
        for oid in sorted(cell, reverse=True)
        if oid != winner
    )


def _display(state: _DocState, cell: Dict[OpId, _Val]):
    """(winner, value, link, datatype, conflicts) for a visible set."""
    winner = max(cell)
    value, link, datatype = _op_value(state, winner, cell[winner])
    return winner, value, link, datatype, _conflicts(state, cell, winner)


# ---------------------------------------------------------------------------
# state decode from kernel lanes

_DT_NAME = (None, "counter", "timestamp")
_OBJ_TYPE_BY_CODE = tuple(
    OBJ_TYPE_BY_MAKE[Action(a)] for a in range(4)
)


def _decode_state(lv: LiveColumns, lanes) -> _DocState:
    """Rebuild the decoded doc state from one kernel run over `lv`'s
    rows (visible/elem_live/rank/inc_total lanes, [n]).

    Lane-driven: np.nonzero/lexsort batch passes plus the vectorized
    value decode (`LiveColumns.decode_values`) replace the old per-row
    Python loops — one _Val is pre-built per visible row (each row
    contributes to exactly one cell), containers resolve through a
    memo, and element order lands as one run-sliced list per container
    instead of an append per row. Bit-identical to the row-loop
    decode it replaced (pinned against OpSet in tests/test_live.py)."""
    n = lv.n
    state = _DocState()
    if n == 0:
        return state
    c = lv.cols
    action = c["action"][:n]
    opids = lv.opids
    obj_col = c["obj"][:n]
    key_col = c["key"][:n]
    ref_col = c["ref"][:n]
    insert_col = c["insert"][:n]
    dt_col = c["dt"][:n]
    visible = np.asarray(lanes.visible[:n]).astype(bool, copy=False)
    rank = lanes.rank[:n]
    inc_total = lanes.inc_total[:n]

    # objects (dead MAKEs included — OpSet retains them)
    objs = state.objs
    make_rows = np.nonzero(action <= 3)[0]
    if len(make_rows):
        types = _OBJ_TYPE_BY_CODE
        for r, a in zip(
            make_rows.tolist(), action[make_rows].tolist()
        ):
            objs[opids[r]] = _Obj(types[a])

    inc_rows = np.nonzero(inc_total != 0)[0]
    if len(inc_rows):
        state.inc = dict(
            zip(
                [opids[r] for r in inc_rows.tolist()],
                inc_total[inc_rows].tolist(),
            )
        )

    # full element order FIRST (descending rank within each container,
    # tombstones INCLUDED — OpSet keeps dead elems in `order`: remote
    # RGA inserts reference them and the skip-scan walks them), with
    # the per-elem cell dicts prefilled so the visible-row pass below
    # assigns straight into them. lexsort is stable, so within a
    # container ties keep row order — the same sequence the global
    # stable -rank argsort + per-row append produced.
    ins_rows = np.nonzero(insert_col == 1)[0]
    if len(ins_rows):
        o_ins = obj_col[ins_rows]
        order = np.lexsort((-rank[ins_rows], o_ins))
        sorted_rows = ins_rows[order].tolist()
        o_sorted = o_ins[order]
        bounds = np.nonzero(o_sorted[1:] != o_sorted[:-1])[0] + 1
        starts = np.concatenate(([0], bounds)).tolist()
        ends = np.concatenate((bounds, [len(sorted_rows)])).tolist()
        o_list = o_sorted.tolist()
        for s, e in zip(starts, ends):
            o = o_list[s]
            obj = objs[ROOT] if o < 0 else objs[opids[o]]
            elems = [opids[r] for r in sorted_rows[s:e]]
            obj.order = elems
            fields = obj.fields
            if fields:
                for el in elems:
                    if el not in fields:
                        fields[el] = {}
            else:
                obj.fields = {el: {} for el in elems}

    vis_rows = np.nonzero(visible)[0]
    if len(vis_rows):
        # one _Val per visible row, built in a single batch pass (each
        # row contributes to exactly one cell)
        bases = lv.decode_values(vis_rows)
        dts = dt_col[vis_rows]
        link_rows = np.nonzero(action[vis_rows] <= 3)[0]
        if dts.any() or len(link_rows):
            dt_name = _DT_NAME
            vals = list(
                map(
                    _Val._make,
                    zip(
                        bases,
                        repeat(False),
                        map(dt_name.__getitem__, dts.tolist()),
                    ),
                )
            )
            link_val = _Val(None, True, None)
            for j in link_rows.tolist():
                vals[j] = link_val
        else:  # no datatypes, no links: the dominant value shape
            vals = list(
                map(_Val._make, zip(bases, repeat(False), repeat(None)))
            )
        # container per visible row (memoized: rows repeat containers)
        root_obj = objs[ROOT]
        cont_of: Dict[int, _Obj] = {}
        conts: List[_Obj] = []
        ap = conts.append
        for o in obj_col[vis_rows].tolist():
            co = cont_of.get(o)
            if co is None:
                co = root_obj if o < 0 else objs[opids[o]]
                cont_of[o] = co
            ap(co)

        vr = vis_rows.tolist()
        kv = key_col[vis_rows]
        iv = insert_col[vis_rows]
        rv = ref_col[vis_rows]
        kvl = kv.tolist()
        rvl = rv.tolist()
        # map cells: visible ops with a key, grouped by (container, key)
        keys_items = lv.keys.items
        for j in np.nonzero(kv >= 0)[0].tolist():
            conts[j].fields.setdefault(keys_items[kvl[j]], {})[
                opids[vr[j]]
            ] = vals[j]
        # element cells: own insert values (their cell dicts exist —
        # every insert row is in its container's prefilled order) +
        # non-insert elem updates
        for j in np.nonzero(iv == 1)[0].tolist():
            e = opids[vr[j]]
            conts[j].fields[e][e] = vals[j]
        for j in np.nonzero(
            (iv == 0) & (kv < 0) & (rv >= 0)
        )[0].tolist():
            conts[j].fields.setdefault(opids[rvl[j]], {})[
                opids[vr[j]]
            ] = vals[j]
    return state


_gc_pause_lock = make_lock("live.gc")
_gc_pause_depth = 0
_gc_pause_was_on = False


@contextmanager
def _gc_paused():
    """Pause the cyclic GC across a bulk decode: building a doc's
    state allocates O(rows) small objects (_Vals, cell dicts) and the
    gen0 scans those allocations trigger were ~half the decode wall
    time. Depth-counted so concurrent lock-free adoption builds nest;
    never re-enables a GC the application had off."""
    global _gc_pause_depth, _gc_pause_was_on
    with _gc_pause_lock:
        _gc_pause_depth += 1
        if _gc_pause_depth == 1:
            _gc_pause_was_on = gc.isenabled()
            gc.disable()
    try:
        yield
    finally:
        with _gc_pause_lock:
            _gc_pause_depth -= 1
            if _gc_pause_depth == 0 and _gc_pause_was_on:
                gc.enable()


def _reachable_from_lanes(lv: LiveColumns, out) -> Set[OpId]:
    """Winner-link closure from ROOT, straight from the kernel's
    map_winner/elem_winner lanes (adoption has the host kernel's full
    lane set in hand): a MAKE row that wins its cell is a link edge
    container->child, every row wins at most one cell, so the edges
    form a forest walked in O(makes). Bit-identical to
    _compute_reachable's state walk (pinned in tests/test_live.py)."""
    n = lv.n
    if n == 0:
        return {ROOT}
    action = lv.cols["action"][:n]
    winner = (
        np.asarray(out.map_winner)[:n]
        | np.asarray(out.elem_winner)[:n]
    )
    link_rows = np.nonzero(winner & (action <= 3))[0]
    children: Dict[int, List[int]] = {}
    obj_col = lv.cols["obj"][:n]
    for r, p in zip(link_rows.tolist(), obj_col[link_rows].tolist()):
        children.setdefault(p, []).append(r)
    seen: Set[int] = set()
    stack = [-1]  # obj sentinel for ROOT
    while stack:
        for r in children.get(stack.pop(), ()):
            if r not in seen:
                seen.add(r)
                stack.append(r)
    opids = lv.opids
    reach = {opids[r] for r in seen}
    reach.add(ROOT)
    return reach


def _compute_reachable(state: _DocState) -> None:
    """Set `state.reachable` to the winner-link closure from ROOT —
    exactly the set `_diff_states(_DocState(), state)` would record,
    without building any Diff/Conflict objects (the adoption path only
    needs the baseline reachability; the full snapshot diff walk was
    the single biggest adoption cost)."""
    objs = state.objs
    reach: Set[OpId] = {ROOT}
    stack: List[OpId] = [ROOT]
    while stack:
        obj = objs[stack.pop()]
        if obj.is_sequence:
            fields = obj.fields
            cells = [
                c_ for c_ in (fields.get(e) for e in obj.order) if c_
            ]
        else:
            cells = [c_ for c_ in obj.fields.values() if c_]
        for cell in cells:
            winner = max(cell)
            if (
                cell[winner].link
                and winner not in reach
                and winner in objs
            ):
                reach.add(winner)
                stack.append(winner)
    state.reachable = reach


# ---------------------------------------------------------------------------
# state diffing (delta patches + snapshots)


def _diff_states(old: _DocState, new: _DocState) -> List[Diff]:
    """Diffs transforming a frontend at `old` into `new`, walking the
    reachable object graph exactly as OpSet._snapshot_obj does (so a
    diff against the empty state is bit-identical to snapshot_patch).
    Updates new.reachable as a side effect."""
    diffs: List[Diff] = []
    new.reachable = set()
    visited: Set[OpId] = set()

    def emit_obj(opid: OpId, fresh: bool) -> None:
        if opid in visited:
            return
        visited.add(opid)
        new.reachable.add(opid)
        obj = new.objs[opid]
        oid = ROOT_ID if opid == ROOT else str(opid)
        old_obj = None
        if not fresh:
            old_obj = old.objs.get(opid)
        if obj.is_sequence:
            _emit_seq(opid, oid, obj, old_obj, fresh)
        else:
            _emit_map(oid, obj, old_obj, fresh)

    def recurse_link(winner: OpId, link: bool) -> None:
        if not link:
            return
        if winner in old.reachable and winner in old.objs:
            emit_obj(winner, fresh=False)
        else:
            obj = new.objs[winner]
            diffs.append(
                Diff(action="create", obj=str(winner), obj_type=obj.type)
            )
            emit_obj(winner, fresh=True)

    def _emit_map(oid, obj, old_obj, fresh) -> None:
        old_fields = old_obj.fields if old_obj is not None else {}
        for key in sorted(set(obj.fields) | set(old_fields)):
            cell = obj.fields.get(key)
            if not cell:
                if old_fields.get(key):
                    diffs.append(
                        Diff(
                            action="remove",
                            obj=oid,
                            obj_type=obj.type,
                            key=key,
                        )
                    )
                continue
            winner, value, link, datatype, conflicts = _display(new, cell)
            changed = True
            old_cell = old_fields.get(key)
            if not fresh and old_cell:
                changed = _display(old, old_cell)[1:] != (
                    value, link, datatype, conflicts
                )
            recurse_link(winner, link)
            if changed:
                diffs.append(
                    Diff(
                        action="set",
                        obj=oid,
                        obj_type=obj.type,
                        key=key,
                        value=value,
                        link=link,
                        datatype=datatype,
                        conflicts=conflicts,
                    )
                )

    def _emit_seq(opid, oid, obj, old_obj, fresh) -> None:
        old_live = old_obj.live() if old_obj is not None else []
        new_live = obj.live()
        new_set = set(new_live)
        old_set = set(old_live)
        kept = 0
        for e in old_live:
            if e in new_set:
                kept += 1
            else:
                diffs.append(
                    Diff(
                        action="remove",
                        obj=oid,
                        obj_type=obj.type,
                        index=kept,
                        elem_id=str(e),
                    )
                )
        for j, e in enumerate(new_live):
            cell = obj.fields[e]
            winner, value, link, datatype, conflicts = _display(new, cell)
            is_new = fresh or e not in old_set
            changed = True
            if not is_new:
                old_cell = (
                    old_obj.fields.get(e) if old_obj is not None else None
                )
                changed = not old_cell or _display(old, old_cell)[1:] != (
                    value, link, datatype, conflicts
                )
            recurse_link(winner, link)
            if is_new:
                diffs.append(
                    Diff(
                        action="insert",
                        obj=oid,
                        obj_type=obj.type,
                        index=j,
                        elem_id=str(e),
                        value=value,
                        link=link,
                        datatype=datatype,
                        conflicts=conflicts,
                    )
                )
            elif changed:
                diffs.append(
                    Diff(
                        action="set",
                        obj=oid,
                        obj_type=obj.type,
                        index=j,
                        elem_id=str(e),
                        value=value,
                        link=link,
                        datatype=datatype,
                        conflicts=conflicts,
                    )
                )

    emit_obj(ROOT, fresh=False)
    # objects the frontend still holds that are now DETACHED: the host
    # path streams their mutations too (FrontendDoc retains detached
    # objects and applies diffs addressed to them), so a later
    # re-attach links a CURRENT copy — dropping them here would leave
    # the frontend's copy stale and diverge from the HM_LIVE=0 twin.
    # Keeping them in new.reachable keeps successive ticks streaming.
    for opid in sorted(old.reachable):
        if opid in visited or opid not in new.objs or opid not in old.objs:
            continue
        emit_obj(opid, fresh=False)
    return diffs


# ---------------------------------------------------------------------------
# per-doc live state


class _LiveDoc:
    # no __slots__: the HM_RACEDEP=1 lockset descriptors wrap these
    # fields (analysis/guards.py declares them under doc.emit — the
    # relocated engine-lock guard rows of the write-plane split)

    def __init__(self, doc, cols, state, clock, max_op, history_len):
        self.doc = doc
        self.cols: LiveColumns = cols
        self.state: _DocState = state
        self.clock: Dict[str, int] = clock
        self.max_op: int = max_op
        self.history_len: int = history_len
        self.pending: Dict[Tuple[str, int], Change] = {}
        self.queued: List[Change] = []
        # rows appended to `cols` but not yet decoded into `state`
        # (tick phase 1 defers big catch-ups to the shared batched
        # kernel; any reader under the domain catches up first)
        self.undecoded: bool = False
        self.tick_rows: int = 0  # phase-3 install-and-recheck token
        self.last_use: int = 0  # engine use-clock (LRU demotion order)
        # demotability memo: (serving clock at last check, verdict) —
        # the sidecar serveability scan costs IO under the emission
        # domain, so it runs at most once per clock value
        self.demotable_at: Optional[Tuple[Dict[str, int], bool]] = None

    def resident_bytes(self) -> int:
        """Host bytes this hot doc pins: the packed columns plus an
        estimate of the decoded state (~one _Val + dict slot per
        row)."""
        return self.cols.nbytes + self.cols.n * 120


class _AdoptGate:
    """In-flight adoption marker: the builder thread constructs the
    doc's live state OUTSIDE the engine lock; other threads submitting
    changes for the same doc wait on `event` instead of replaying the
    doc host-side (and instead of serializing behind the engine lock,
    which stays free for other docs' ticks)."""

    __slots__ = ("thread", "event", "outcome")

    def __init__(self) -> None:
        self.thread = threading.current_thread()
        self.event = threading.Event()
        self.outcome = "refused"


def _live_max_bytes() -> int:
    """HM_LIVE_MAX_BYTES: resident-bytes cap across all adopted docs'
    LiveColumns (0 / unset = unbounded). Read per enforcement pass so
    tests and operators can adjust it live."""
    return int(os.environ.get("HM_LIVE_MAX_BYTES", "0"))


class LiveApplyEngine:
    """Dirty set + tick loop + shape-bucketed batch dispatch over the
    live docs' cached columns. One engine per RepoBackend."""

    def __init__(self, backend) -> None:
        self._back = backend
        self._lock = make_rlock("live.engine")
        # `live.engine` — tick/dirty-set COORDINATION only since the
        # write-plane split: the doc table and adoption/demotion
        # bookkeeping mutate under it, and it is NEVER held across a
        # feed append, fsync, or frontend push (those run under the
        # per-doc emission domains, backend/emission.py, which rank
        # ABOVE it). It stays a no-block class: any blocking call
        # under it is a lint + lockdep violation, and bench
        # config_lockdebt pins lock.held_blocking_ms.live_engine at
        # zero for every HM_FSYNC tier.
        self._docs: Dict[str, _LiveDoc] = {}
        self._refused: Set[str] = set()  # adoption failed: host path
        # in-flight adoptions (doc_id -> gate). Builds run OUTSIDE the
        # engine lock; the gate both blocks same-doc submitters and
        # guards the recursive window (opening a cursor actor during
        # adoption can replay a window back into the same doc on the
        # builder thread before its _LiveDoc is registered).
        self._adopting: Dict[str, _AdoptGate] = {}
        self._demoted_ids: Set[str] = set()  # for the readopted stat
        self._use_clock = 0  # monotone LRU counter — guarded by
        # live.engine like every field of this class: THE guard map
        # is analysis/guards.py (machine-checked by the guarded-attr
        # lint rule and the HM_RACEDEP=1 lockset detector)
        # stats live on the PROCESS telemetry registry (ISSUE 9): one
        # labeled series per engine so concurrent repos stay exact,
        # per-thread sharded adds so no bump needs the engine lock,
        # and the `stats` property rebuilds the historical dict shape
        # bench.py and the tests read.
        inst = str(telemetry.next_instance())
        reg = telemetry.REGISTRY
        self._m: Dict[str, Any] = {
            k: reg.counter("live." + k, inst=inst)
            for k in _LIVE_COUNTS + _LIVE_TIMES
        }
        for k in _LIVE_GAUGES:
            self._m[k] = reg.gauge("live." + k, inst=inst)
        self._ticker = Debouncer(
            self._on_tick,
            window_s=_tick_window_s(),
            max_window_s=_tick_window_max_s(),
            name="live-tick",
            # work-conserving: under a sustained stream the next tick
            # starts the moment the previous one ends (its duration IS
            # the coalescing window); the 2ms window only pads the
            # leading edge of a burst
            eager=True,
        )

    @property
    def stats(self) -> Dict[str, Any]:
        """The engine's stats as the historical dict (registry-backed;
        read-only — a write to the returned dict mutates a copy)."""
        m = self._m
        out: Dict[str, Any] = {}
        for k in _LIVE_COUNTS:
            out[k] = int(m[k].value())
        for k in _LIVE_GAUGES:
            out[k] = int(m[k].value())
        for k in _LIVE_TIMES:
            out[k] = round(m[k].value(), 6)
        return out

    # ------------------------------------------------------------------
    # seams (called by DocBackend)

    def submit_remote(self, doc, changes: List[Change]) -> bool:
        """Admit + queue remote changes for the next tick. False when
        the doc cannot be live-managed (caller takes the host path).
        Adoption (if needed) builds outside every ordered lock."""
        while True:
            if self._ensure_doc(doc) is None:
                return False
            with doc.emission:
                with self._lock:
                    if self._docs.get(doc.id) is None:
                        continue  # demoted in the gap: re-adopt
                    ld = self._docs[doc.id]
                    ld.last_use = self._bump_use()
                if self._admit(ld, changes):
                    self._sync_doc_meta(ld)
                    self._ticker.mark(doc.id)
                break
        doc._check_ready()
        return True

    def apply_local(
        self, doc, req: ChangeRequest, emit=None
    ) -> Optional[Tuple[Change, Patch]]:
        """Resolve + apply a local change against the live state
        (OpSet.apply_local_request twin). None when the doc cannot be
        live-managed; raises ValueError on an out-of-order seq.

        `emit(change, patch)` runs while the doc's EMISSION DOMAIN is
        still held: the patch's diffs are relative to the state just
        before this change, so its push (feed append included) must
        reach the frontend queue before any tick emits a delta on the
        post-change state. Only THIS doc's domain is held — disjoint
        docs' local changes run concurrently."""
        while True:
            if self._ensure_doc(doc) is None:
                return None
            with doc.emission:
                with self._lock:
                    ld = self._docs.get(doc.id)
                    if ld is None:
                        continue  # demoted in the gap: re-adopt
                    ld.last_use = self._bump_use()
                # pending admitted remotes apply (and notify) first, so
                # the local resolution sees the same state the host
                # path would. The catch-up may evict the doc to the
                # host path (range overflow) — the caller retries
                # host-side.
                if not self._catch_up_locked(ld):
                    return None
                expected = ld.clock.get(req.actor, 0) + 1
                if req.seq != expected:
                    raise ValueError(
                        f"out-of-order local change: seq {req.seq} != "
                        f"{expected}"
                    )
                change, patch = self._apply_local_locked(ld, req)
                self._sync_doc_meta(ld)
                self._m["local_changes"].add(1)
                if emit is not None:
                    emit(change, patch)
            return change, patch

    def snapshot_patch(self, doc) -> Optional[Patch]:
        """From-scratch patch of the live state (OpSet.snapshot_patch
        twin — served for Ready / reopen on adopted docs). Holding the
        doc's emission domain across {snapshot -> push} is the Ready
        atomicity contract: no tick can slip a newer delta ahead of
        the Ready in the frontend queue, because every tick emission
        of this doc needs this same domain."""
        with doc.emission:
            ld = self._docs.get(doc.id)
            if ld is None:
                return None
            if not self._catch_up_locked(ld):
                return None  # evicted to the host path mid-flush
            # diff against an empty doc WITHOUT touching the tracked
            # reachability (this is a read, not an emission to the
            # incremental patch stream)
            saved = ld.state.reachable
            diffs = _diff_states(_DocState(), ld.state)
            ld.state.reachable = saved
            return Patch(
                clock=dict(ld.clock),
                deps=dict(ld.clock),
                max_op=ld.max_op,
                diffs=tuple(diffs),
            )

    def drop(self, doc_id: str) -> None:
        """Forget a doc's live state (close/destroy)."""
        with self._lock:
            self._docs.pop(doc_id, None)
            self._refused.discard(doc_id)
            self._demoted_ids.discard(doc_id)

    def flush_now(self, timeout: float = 5.0) -> bool:
        return self._ticker.flush_now(timeout)

    def close(self) -> None:
        self._ticker.close()
        # fold this engine's labeled series into the closed aggregate:
        # repos open/close freely without growing the registry a label
        # set per lifecycle (stats stays readable — it is handle-based)
        telemetry.REGISTRY.retire(*self._m.values())

    # ------------------------------------------------------------------
    # adoption (lock-free build + install-and-recheck)

    def _bump_use(self) -> int:
        """Next LRU use-clock value. REQUIRES live.engine
        (analysis/guards.py) — callers hold the engine lock."""
        self._use_clock += 1
        return self._use_clock

    def _ensure_doc(self, doc) -> Optional[_LiveDoc]:
        """The doc's live state, adopting it if needed. MUST be called
        WITHOUT the engine lock held: the adoption build (pack + kernel
        + decode, O(doc)) runs lock-FREE so other hot docs keep ticking
        through the window, then installs under the lock with a recheck
        (opset still None, serving clock unmoved, doc still open). The
        emission-ordering invariant holds because the build never
        computes or pushes a patch — only the install takes the
        engine lock, and every emission takes the doc's domain.
        Returns None for the host path (refused, recursive adoption
        window, engine-lock re-entry, or doc closed)."""
        # a thread that already HOLDS the engine lock must neither
        # build here (an O(doc) build under the coordination lock
        # stalls every tick) nor wait on another thread's gate (that
        # builder needs this lock to install/finish — waiting with it
        # held deadlocks the engine). Host path instead, the same
        # answer as the recursive-window case below. Holding this
        # doc's own EMISSION DOMAIN is fine: the builder never takes
        # another doc's domain.
        held = getattr(self._lock, "_is_owned", lambda: False)()
        while True:
            with self._lock:
                ld = self._docs.get(doc.id)
                if ld is not None:
                    return ld
                if doc.id in self._refused:
                    return None
                if held:
                    return None
                gate = self._adopting.get(doc.id)
                if gate is None:
                    gate = self._adopting[doc.id] = _AdoptGate()
                elif gate.thread is threading.current_thread():
                    # recursive window during our own build (opening a
                    # cursor actor can replay into this doc): host path
                    return None
            if gate.thread is threading.current_thread():
                break  # we are the builder
            gate.event.wait()
            if gate.outcome == "dropped":
                return None  # doc closed mid-build
            # else loop: reads installed/refused state (or re-adopts
            # if a demotion raced the install)

        outcome = "refused"
        ld = None
        now = time.perf_counter
        t0 = now()
        held0 = self._m["t_adopt_lock_held"].value()
        sp = telemetry.begin("live.adopt", cat="live")
        try:
            for _attempt in range(3):
                built = self._adopt_build(doc)
                if built is None:
                    break
                status, ld = self._install_adoption(doc, *built)
                if status == "retry":
                    # serving clock moved during the build (a host-path
                    # emission raced in): discard and rebuild
                    self._m["adopt_retries"].add(1)
                    continue
                outcome = status
                break
        finally:
            sp.end(outcome=outcome)
            with self._lock:
                self._adopting.pop(doc.id, None)
                gate.outcome = outcome
                if outcome == "refused":
                    self._refused.add(doc.id)
                    self._m["refused"].add(1)
                    # doc._live stays SET (harmless): the host path is
                    # still taken — the opset the fallback installs
                    # short-circuits the live branch, and _refused
                    # rejects re-adoption. Emission ordering is the
                    # doc's own domain either way.
                # the install window is lock-HELD: keep the two stats
                # disjoint so lock_free + lock_held = build wall
                self._m["t_adopt_lock_free"].add(
                    (now() - t0)
                    - (self._m["t_adopt_lock_held"].value() - held0)
                )
            gate.event.set()
        return ld if outcome == "ok" else None

    def _adopt_build(self, doc) -> Optional[Tuple[_LiveDoc, Dict]]:
        """Build a doc's cached columns + decoded state from its feed
        sidecars at its SERVING clock — no host OpSet replay, and NO
        engine lock. Returns (_LiveDoc, clock) ready for the install
        recheck, or None to refuse (missing/short/non-contiguous feed,
        kernel range overflow, or a host OpSet already appeared)."""
        from ..ops.columnar import pack_docs_columns

        back = self._back
        now = time.perf_counter
        with doc._lock:
            if doc.opset is not None or doc._lazy_loader is None:
                return None
            clock = dict(doc._lazy_clock or {})
            history_len = doc._lazy_len
        t0 = now()
        # the shared serveability rule (non-creating: a refused
        # adoption must not materialize an empty actor feed on disk)
        spec = back._serveable_spec(clock)
        if spec is None:
            return None
        with _gc_paused():
            batch = pack_docs_columns([spec] if spec else [[]])
            lv = LiveColumns.from_batch(batch, 0)
            t1 = now()
            if not self._ranges_ok(lv):
                return None  # refuse BEFORE paying the kernel run
            # kernel over the UNPADDED rows (the tick path's per-doc
            # host kernel): adoption sizes sit just under a pow2
            # bucket, so the padded batch kernel does ~2x the work
            lanes = self._host_lanes(lv)
            t2 = now()
            state = _decode_state(lv, lanes)
            t3 = now()
            # the frontend's baseline is the Ready snapshot of this
            # exact state: record what that snapshot walk can reach
            # (winner-link closure from the kernel lanes — no Diff
            # emission needed)
            state.reachable = _reachable_from_lanes(lv, lanes)
            t4 = now()  # inside the pause: the deferred gen0 sweep at
            # re-enable charges the build total, not the reach stage
        # sharded counters: no engine lock needed for stats anymore
        m = self._m
        m["t_adopt_pack"].add(t1 - t0)
        m["t_adopt_kernel"].add(t2 - t1)
        m["t_adopt_decode"].add(t3 - t2)
        m["t_adopt_reach"].add(t4 - t3)
        ld = _LiveDoc(
            doc, lv, state, clock,
            int(batch.cols["ctr"][0].max(initial=0)), history_len,
        )
        return ld, clock

    def _install_adoption(self, doc, ld, clock):
        """Install a built _LiveDoc under the engine lock, rechecking
        the state the build was derived from. Returns (status, ld):
        'ok' (installed), 'retry' (serving clock moved — rebuild),
        'refused' (a host OpSet won the race), or 'dropped' (the doc
        was closed/destroyed mid-build)."""
        now = time.perf_counter
        t0 = now()
        with self._lock:
            with doc._lock:
                if doc.opset is not None:
                    return "refused", None  # host-side init won
                if self._back.docs.get(doc.id) is not doc:
                    return "dropped", None
                if dict(doc._lazy_clock or {}) != clock:
                    return "retry", None
                doc._live_adopted = True
            ld.last_use = self._bump_use()
            self._docs[doc.id] = ld
            self._m["adopted"].add(1)
            if doc.id in self._demoted_ids:
                self._demoted_ids.discard(doc.id)
                self._m["readopted"].add(1)
            self._m["t_adopt_lock_held"].add(now() - t0)
        # budget enforcement OUTSIDE the engine lock: a demotion takes
        # {domain -> engine}, so running it with the engine held would
        # invert the declared order
        self._enforce_budget()
        return "ok", ld

    # ------------------------------------------------------------------
    # byte-bounded LRU demotion (HM_LIVE_MAX_BYTES)

    def _enforce_budget(self) -> None:
        """Demote least-recently-used idle docs until resident bytes
        fit HM_LIVE_MAX_BYTES (0 = unbounded — the pass costs O(1)
        then; `live_bytes` only refreshes while a cap is set). The
        most recently used doc is never demoted by this pass — a
        single hot doc larger than the cap must not thrash an O(doc)
        adopt/demote cycle on every tick — so the effective floor is
        one doc's bytes. Dirty docs (queued/pending/undecoded) wait
        for their tick."""
        cap = _live_max_bytes()
        if cap <= 0:
            with self._lock:
                self._m["live_docs"].set(len(self._docs))
            return
        self._demote_over(cap, protect_mru=True)

    def demote_idle(self, max_bytes: Optional[int] = None) -> int:
        """Demote idle adopted docs (LRU-first) until resident bytes
        fit `max_bytes` (default: the HM_LIVE_MAX_BYTES cap — a no-op
        when unset; pass 0 to demote every idle doc). Unlike the
        automatic budget pass this may demote the most recently used
        doc too. Returns the number demoted — docs with un-ticked
        changes, or whose state cannot be rebuilt from the sidecars,
        stay resident."""
        if max_bytes is not None:
            cap = max_bytes
        else:
            cap = _live_max_bytes()
            if cap <= 0:
                return 0  # unbounded cap: nothing to enforce
        return self._demote_over(cap, protect_mru=False)

    def _demote_over(self, cap: int, protect_mru: bool) -> int:
        """ONE LRU demotion sweep shared by the per-tick budget pass
        (protect_mru=True) and the explicit demote_idle hook; returns
        the number demoted. Candidates snapshot under the engine
        lock; each demotion re-locks {domain -> engine} and rechecks
        — the domain-before-engine order means the sweep can never
        hold the engine lock while waiting on a busy writer."""
        with self._lock:
            candidates, sizes, total, mru = (
                self._demote_candidates_locked(protect_mru)
            )
        n0 = self._m["demoted"].value()
        if total > cap:
            for ld in candidates:
                if total <= cap:
                    break
                if ld is mru:
                    continue
                if self._demote_one(ld):
                    total -= sizes[ld.doc.id]
        self._m["live_bytes"].set(total)
        with self._lock:
            self._m["live_docs"].set(len(self._docs))
        return int(self._m["demoted"].value() - n0)

    def _demote_candidates_locked(self, protect_mru: bool):
        """LRU-ordered demotion candidates + byte accounting.
        REQUIRES live.engine (analysis/guards.py)."""
        docs = self._docs
        sizes = {i: ld.resident_bytes() for i, ld in docs.items()}
        total = sum(sizes.values())
        mru = (
            max(docs.values(), key=lambda l: l.last_use)
            if (docs and protect_mru)
            else None
        )
        order = sorted(docs.values(), key=lambda l: l.last_use)
        return order, sizes, total, mru

    def _demote_one(self, ld: _LiveDoc) -> bool:
        """Demote one candidate if it is still present, idle, and
        rebuildable — under its domain (no emission can be mid-flight)
        plus the engine lock (table mutation)."""
        doc = ld.doc
        with doc.emission:
            with self._lock:
                if self._docs.get(doc.id) is not ld:
                    return False
                if ld.queued or ld.pending or ld.undecoded:
                    return False
                if not self._demotable(ld):
                    return False
                self._demote_locked(ld)
                return True

    def _demotable(self, ld: _LiveDoc) -> bool:
        """Re-adoption must be able to rebuild this exact state from
        the feed sidecars (the shared _serveable_spec rule — the same
        check adoption and the demoted snapshot closure run). Changes
        injected straight into the engine with no backing feed
        (synthetic peers, tests) pin the doc resident — demoting would
        silently lose them. The verdict memoizes per serving clock
        (either way), so over-budget ticks do not re-pay the sidecar
        scans — the scan runs under the doc's emission domain. If a
        sidecar regresses OUT-OF-BAND after a positive memo,
        re-adoption still re-checks serveability and falls back to
        the host path, so a stale verdict degrades, not corrupts."""
        doc = ld.doc
        with doc._lock:
            if doc._lazy_loader is None:
                return False
        memo = ld.demotable_at
        if memo is not None and memo[0] == ld.clock:
            return memo[1]
        verdict = self._back._serveable_spec(ld.clock) is not None
        ld.demotable_at = (dict(ld.clock), verdict)
        return verdict

    def _demote_locked(self, ld: _LiveDoc) -> None:
        """Hand an idle adopted doc back to the lazy path: the serving
        clock/length sync to the doc (they already do, per admission),
        the engine forgets its LiveColumns + decoded state, and the
        doc's next live change re-adopts from the sidecars (cheap: the
        vectorized decode). Reads keep working — a fresh lazy snapshot
        closure replaces the engine's state for Ready/reopen. Caller
        holds the doc's emission domain AND the engine lock
        (REQUIRES live.engine, analysis/guards.py)."""
        doc = ld.doc
        log("live", f"demoting {doc.id[:6]} to lazy (LRU)")
        telemetry.instant("live.demote", cat="live")
        snap = self._back._demoted_snapshot_fn(doc.id, dict(ld.clock))
        doc.demote_from_live(dict(ld.clock), ld.history_len, snap)
        self._docs.pop(doc.id, None)
        self._demoted_ids.add(doc.id)
        self._m["demoted"].add(1)

    @staticmethod
    def _ranges_ok(lv: LiveColumns) -> bool:
        A = max(1, len(lv.actors.items))
        K = max(1, len(lv.keys.items))
        n = lv.n
        max_ctr = int(lv.cols["ctr"][:n].max(initial=0)) if n else 0
        return (
            max_ctr * A + A < 2**30 and (n + 1) * (K + 1) + K < 2**31
        )

    # ------------------------------------------------------------------
    # causal admission (OpSet _enqueue/_drain_pending twin)

    def _admit(self, ld: _LiveDoc, changes: List[Change]) -> bool:
        for c in changes:
            if c.seq <= ld.clock.get(c.actor, 0):
                continue  # duplicate / already applied
            ld.pending.setdefault((c.actor, c.seq), c)
        progressed = True
        admitted = False
        while progressed and ld.pending:
            progressed = False
            for key in list(ld.pending):
                c = ld.pending[key]
                if c.seq != ld.clock.get(c.actor, 0) + 1:
                    continue
                if any(
                    ld.clock.get(a, 0) < s for a, s in c.deps.items()
                ):
                    continue
                del ld.pending[key]
                ld.clock[c.actor] = c.seq
                ld.max_op = max(ld.max_op, c.max_op)
                ld.history_len += 1
                ld.queued.append(c)
                progressed = True
                admitted = True
        return admitted

    def _sync_doc_meta(self, ld: _LiveDoc) -> None:
        doc = ld.doc
        with doc._lock:
            doc._lazy_clock = dict(ld.clock)
            doc._lazy_len = ld.history_len

    # ------------------------------------------------------------------
    # the tick

    def _on_tick(self, marked: Dict) -> None:
        with telemetry.span("live.tick", cat="live"):
            m = self._m
            kernel_docs: List[_LiveDoc] = []
            ticked = 0
            for doc_id in list(marked):
                # GIL-atomic table snapshot: the tick NEVER holds the
                # engine lock while acquiring a doc's domain (and
                # never two domains at once — the no-cross-doc
                # invariant of the write plane)
                ld = self._docs.get(doc_id)
                if ld is None:
                    continue
                with ld.doc.emission:
                    with self._lock:
                        if self._docs.get(doc_id) is not ld:
                            continue  # demoted/evicted before we got in
                        ld.last_use = self._bump_use()
                    res = self._tick_doc_locked(ld)
                    if res:
                        ticked += 1
                    if res == 2:
                        kernel_docs.append(ld)
            if ticked:
                m["ticks"].add(1)
                m["tick_docs"].add(ticked)
            if kernel_docs:
                # shape buckets: docs whose row counts share a pow2
                # bucket ride one padded dispatch (and successive
                # ticks reuse its program)
                from ..ops.crdt_kernels import LIVE_MIN_ROWS, live_bucket

                groups: Dict[int, List[_LiveDoc]] = {}
                for ld in kernel_docs:
                    groups.setdefault(
                        live_bucket(ld.tick_rows, LIVE_MIN_ROWS), []
                    ).append(ld)
                for bucket_n, lds in sorted(groups.items()):
                    self._run_group(bucket_n, lds)
            self._enforce_budget()

    def _tick_doc_locked(self, ld: _LiveDoc) -> int:
        """Tick phase 1 for ONE doc, under its emission domain: append
        its queued changes and either apply them incrementally (small
        ticks — O(tick ops) through the OpSet-twin _apply_op_state —
        complete here, patch emitted) or mark the doc `undecoded` for
        the shared batched kernel: phase 2 dispatches across docs with
        NO locks held, phase 3 installs per doc back under this
        domain. Returns 0 = no work, 1 = done inline, 2 = joined the
        kernel group. REQUIRES doc.emit (analysis/guards.py)."""
        now = time.perf_counter
        m = self._m
        changes = ld.queued
        if not changes and not ld.undecoded:
            return 0
        if changes:
            ld.queued = []
            m["tick_changes"].add(len(changes))
            t0 = now()
            ld.cols.append_changes(changes)
            m["t_live_append"].add(now() - t0)
            if not self._ranges_ok(ld.cols):
                self._evict_to_host(ld)
                return 1
        n_ops = sum(len(c.ops) for c in changes)
        if not ld.undecoded and (
            n_ops <= 8 or n_ops * max(ld.cols.n, 1) <= _inc_budget_cells()
        ):
            t1 = now()
            diffs: List[Diff] = []
            for c in changes:
                for i, op in enumerate(c.ops):
                    self._apply_op_state(ld.state, c.op_id(i), op, diffs)
            m["inc_changes"].add(len(changes))
            m["t_live_apply"].add(now() - t1)
            self._emit_tick(ld, diffs)
            return 1
        ld.undecoded = True
        ld.tick_rows = ld.cols.n
        return 2

    def _catch_up_locked(self, ld: _LiveDoc) -> bool:
        """Bring ld.state current under its emission domain: apply the
        queued changes and decode any appended-but-undecoded rows,
        emitting the coalesced delta patch — the per-doc successor of
        the old engine-locked _flush_ids. Returns False when the doc
        was evicted to the host path (the caller retries host-side).
        REQUIRES doc.emit (analysis/guards.py)."""
        state = self._tick_doc_locked(ld)
        if state == 1 and self._docs.get(ld.doc.id) is not ld:
            return False  # _evict_to_host handed it to the host path
        if not ld.undecoded:
            return True
        # single-doc catch-up: the same bucketed kernel the tick group
        # uses (device when the padded shape clears the min-cells bar)
        from ..ops.crdt_kernels import LIVE_MIN_ROWS, live_bucket

        now = time.perf_counter
        t0 = now()
        lanes = self._kernel(
            live_bucket(ld.cols.n, LIVE_MIN_ROWS), [ld]
        )[0]
        self._m["t_live_kernel"].add(now() - t0)
        self._decode_install_locked(ld, lanes)
        return True

    def _decode_install_locked(self, ld: _LiveDoc, lanes) -> None:
        """Decode kernel lanes into a fresh state, diff, install, and
        emit — the shared tail of the catch-up paths. Caller holds the
        doc's emission domain."""
        now = time.perf_counter
        m = self._m
        t1 = now()
        with _gc_paused():
            new_state = _decode_state(ld.cols, lanes)
        t2 = now()
        diffs = _diff_states(ld.state, new_state)
        ld.state = new_state
        ld.undecoded = False
        m["t_live_decode"].add(t2 - t1)
        m["t_live_diff"].add(now() - t2)
        self._emit_tick(ld, diffs)

    def _emit_tick(self, ld: _LiveDoc, diffs: List[Diff]) -> None:
        self._sync_doc_meta(ld)
        doc = ld.doc
        if diffs and doc._announced:
            patch = Patch(
                clock=dict(ld.clock),
                deps=dict(ld.clock),
                max_op=ld.max_op,
                diffs=tuple(diffs),
            )
            doc._notify(
                {"type": "RemotePatch", "doc": doc, "patch": patch}
            )
        doc._check_ready()

    def _run_group(self, bucket_n: int, lds: List[_LiveDoc]) -> None:
        """Tick phases 2+3 for one shape bucket: ONE batched kernel
        dispatch across the group's docs with NO locks held (rows
        under each doc's phase-1 snapshot are immutable — LiveColumns
        appends publish `n` last), then a per-doc install back under
        its emission domain with a recheck: a doc a writer caught up
        (or evicted/closed) mid-kernel discards its stale lanes."""
        now = time.perf_counter
        m = self._m
        t0 = now()
        lanes_by_doc = self._kernel(bucket_n, lds)
        m["t_live_kernel"].add(now() - t0)
        for ld, lanes in zip(lds, lanes_by_doc):
            with ld.doc.emission:
                if not ld.undecoded:
                    continue  # a writer's catch-up beat us to it
                with self._lock:
                    if self._docs.get(ld.doc.id) is not ld:
                        continue  # dropped/demoted mid-kernel
                if ld.cols.n != ld.tick_rows:
                    # rows landed after the snapshot: redo at the
                    # current shape instead of installing stale lanes
                    self._catch_up_locked(ld)
                    continue
                self._decode_install_locked(ld, lanes)

    def _kernel(self, bucket_n: int, lds: List[_LiveDoc]):
        """Run the materialize kernel over the group; returns one lane
        view per doc. Device when the padded batch clears the min-cells
        bar, numpy twin otherwise (both bit-identical — the twin is the
        fuzz reference)."""
        D = len(lds)
        if D * bucket_n < _device_min_cells():
            self._m["kernel_runs"].add(1)
            return [self._host_lanes(ld.cols) for ld in lds]
        return self._kernel_device(bucket_n, lds)

    @staticmethod
    def _host_lanes(lv: LiveColumns):
        """One doc's numpy kernel lanes over its UNPADDED live columns
        — shared by the tick path's small-group kernel and adoption
        (which runs at exact n instead of the padded batch shape)."""
        from ..ops.host_kernel import _host_doc_kernel

        n = lv.n
        A = max(1, len(lv.actors.items))
        K = max(1, len(lv.keys.items))
        c = lv.cols
        return _host_doc_kernel(
            c["action"][:n], lv.slots(), c["ctr"][:n],
            np.zeros(n, np.int32), c["obj"][:n],
            c["key"][:n], c["ref"][:n], c["insert"][:n],
            c["value"][:n], lv.psrc[: lv.n_preds],
            lv.ptgt[: lv.n_preds],
            np.arange(A, dtype=np.int32), A, K,
        )

    def _kernel_device(self, bucket_n: int, lds: List[_LiveDoc]):
        from ..ops.crdt_kernels import (
            LIVE_MIN_DOCS,
            live_bucket,
            materialize_live_device,
        )

        self._m["kernel_runs"].add(1)
        self._m["device_dispatches"].add(1)
        D = live_bucket(len(lds), LIVE_MIN_DOCS)
        N = bucket_n
        A = live_bucket(
            max(len(ld.cols.actors.items) for ld in lds), 4
        )
        K = live_bucket(max(len(ld.cols.keys.items) for ld in lds), 16)
        P = live_bucket(max(ld.cols.n_preds for ld in lds), 16)
        from ..ops.columnar import PAD

        flags = np.zeros((D, N), np.uint8)
        flags[:, :] = PAD
        slot = np.zeros((D, N), np.int32)
        ctr = np.zeros((D, N), np.int32)
        obj = np.full((D, N), -1, np.int32)
        key = np.full((D, N), -1, np.int32)
        ref = np.full((D, N), -3, np.int32)
        value = np.zeros((D, N), np.int32)
        psrc = np.full((D, P), -1, np.int32)
        ptgt = np.full((D, P), -1, np.int32)
        for d, ld in enumerate(lds):
            lv = ld.cols
            n, npred = lv.n, lv.n_preds
            c = lv.cols
            flags[d, :n] = (
                c["action"][:n].astype(np.uint8)
                | (c["insert"][:n].astype(np.uint8) << 3)
            )
            slot[d, :n] = lv.slots()
            ctr[d, :n] = c["ctr"][:n]
            obj[d, :n] = c["obj"][:n]
            key[d, :n] = c["key"][:n]
            ref[d, :n] = c["ref"][:n]
            value[d, :n] = c["value"][:n]
            psrc[d, :npred] = lv.psrc[:npred]
            ptgt[d, :npred] = lv.ptgt[:npred]
        out = materialize_live_device(
            flags, slot, ctr, obj, key, ref, value, psrc, ptgt, A=A, K=K
        )
        host = {
            name: np.asarray(getattr(out, name))
            for name in ("visible", "elem_live", "rank", "inc_total")
        }
        return [_LaneDict(host, d) for d in range(len(lds))]

    def _evict_to_host(self, ld: _LiveDoc) -> None:
        """A doc outgrew the kernel's composite ranges: hand it back to
        the host OpSet path. Everything admitted is already in the
        feeds, so the explicit replay (at the serving clock) rebuilds
        the exact state; un-admitted pending changes re-queue so none
        is lost. Caller holds the doc's emission domain; the table
        mutation takes the engine lock inside it."""
        doc = ld.doc
        log("live", f"evicting {doc.id[:6]} to host path (range)")
        with self._lock:
            self._docs.pop(doc.id, None)
            self._refused.add(doc.id)
        with doc._lock:
            # doc._live stays set (see _ensure_doc): emissions keep the
            # engine lock so the Ready ordering contract holds
            doc._live_adopted = False
            doc._lazy_clock = dict(ld.clock)
            doc._lazy_len = ld.history_len
        doc._ensure_opset()  # the documented fallback: full host replay
        if ld.pending:
            doc.apply_remote_changes(list(ld.pending.values()))

    # ------------------------------------------------------------------
    # local change resolution (OpSet.apply_local_request twin)

    def _apply_local_locked(
        self, ld: _LiveDoc, req: ChangeRequest
    ) -> Tuple[Change, Patch]:
        state = ld.state
        start_op = ld.max_op + 1
        deps = {a: s for a, s in ld.clock.items() if a != req.actor}
        temp_map: Dict[str, OpId] = {}
        ops: List[Op] = []
        diffs: List[Diff] = []
        ctr = start_op
        for intent in req.intents:
            op = self._resolve_intent(
                state, intent, OpId(ctr, req.actor), temp_map
            )
            if op is None:
                continue
            self._apply_op_state(state, OpId(ctr, req.actor), op, diffs)
            ops.append(op)
            ctr += 1
        change = Change(
            actor=req.actor,
            seq=req.seq,
            start_op=start_op,
            deps=deps,
            ops=tuple(ops),
            time=req.time,
            message=req.message,
        )
        ld.cols.append_changes([change])
        ld.clock[req.actor] = req.seq
        ld.max_op = max(ld.max_op, change.max_op)
        ld.history_len += 1
        patch = Patch(
            clock=dict(ld.clock),
            deps=dict(ld.clock),
            max_op=ld.max_op,
            diffs=tuple(diffs),
            actor=req.actor,
            seq=req.seq,
        )
        return change, patch

    @staticmethod
    def _resolve_intent(
        state: _DocState, intent, opid: OpId, temp_map
    ) -> Optional[Op]:
        # the SHARED resolver (crdt/opset.py) — one implementation for
        # both HM_LIVE twins, parameterized over this engine's decoded
        # state (_Obj has the same .is_sequence/.fields shape)
        from ..crdt.opset import resolve_intent

        return resolve_intent(
            intent, opid, temp_map, state.objs.get, _Obj.live
        )

    def _apply_op_state(
        self, state: _DocState, opid: OpId, op: Op, diffs: List[Diff]
    ) -> None:
        """OpSet._apply_op twin over the decoded state — ONE
        implementation serves both local resolution and the incremental
        remote tick path, so the two engines cannot drift."""
        obj = state.objs.get(op.obj)
        if obj is None:
            return  # tolerate ops against unknown objects (OpSet does)
        if op.action.makes_object and opid not in state.objs:
            child_type = OBJ_TYPE_BY_MAKE[op.action]
            state.objs[opid] = _Obj(child_type)
            state.reachable.add(opid)
            diffs.append(
                Diff(action="create", obj=str(opid), obj_type=child_type)
            )
        val = _Val(
            None if op.action.makes_object else op.value,
            op.action.makes_object,
            None if op.action.makes_object else op.datatype,
        )
        if obj.is_sequence:
            self._apply_seq_state(state, obj, opid, op, val, diffs)
        else:
            self._apply_map_state(state, obj, opid, op, val, diffs)

    @staticmethod
    def _obj_str(op: Op) -> str:
        return ROOT_ID if op.obj == ROOT else str(op.obj)

    @staticmethod
    def _live_index(obj: _Obj, elem: OpId) -> int:
        """Index among LIVE elems (OpSet._live_index twin)."""
        idx = 0
        for e in obj.order:
            if e == elem:
                return idx
            if obj.fields.get(e):
                idx += 1
        return idx

    def _apply_map_state(self, state, obj, opid, op, val, diffs) -> None:
        key = op.key
        if key is None:
            return
        visible = obj.fields.setdefault(key, {})
        had = bool(visible)
        if op.action == Action.INC:
            for p in op.pred:
                if p in visible:
                    state.inc[p] = state.inc.get(p, 0) + (op.value or 0)
        else:
            for p in op.pred:
                if visible.pop(p, None) is not None:
                    state.inc.pop(p, None)
            if op.action == Action.SET or op.action.makes_object:
                visible[opid] = val
        oid = self._obj_str(op)
        if not visible:
            if had:
                diffs.append(
                    Diff(
                        action="remove",
                        obj=oid,
                        obj_type=obj.type,
                        key=key,
                    )
                )
            else:
                obj.fields.pop(key, None)
            return
        winner, value, link, datatype, conflicts = _display(state, visible)
        diffs.append(
            Diff(
                action="set",
                obj=oid,
                obj_type=obj.type,
                key=key,
                value=value,
                link=link,
                datatype=datatype,
                conflicts=conflicts,
            )
        )

    def _apply_seq_state(self, state, obj, opid, op, val, diffs) -> None:
        oid = self._obj_str(op)
        if op.insert:
            # RGA insert-after with descending-OpId skip scan (OpSet's
            # algorithm verbatim; `order` includes tombstones)
            if op.ref == HEAD:
                pos = 0
            else:
                try:
                    pos = obj.order.index(op.ref) + 1
                except ValueError:
                    return  # unknown predecessor
            while pos < len(obj.order) and obj.order[pos] > opid:
                pos += 1
            obj.order.insert(pos, opid)
            obj.fields[opid] = {opid: val}
            value, link, datatype = _op_value(state, opid, val)
            diffs.append(
                Diff(
                    action="insert",
                    obj=oid,
                    obj_type=obj.type,
                    index=self._live_index(obj, opid),
                    elem_id=str(opid),
                    value=value,
                    link=link,
                    datatype=datatype,
                )
            )
            return
        elem = op.ref
        if elem is None or elem not in obj.fields:
            return
        visible = obj.fields[elem]
        had = bool(visible)
        if op.action == Action.INC:
            for p in op.pred:
                if p in visible:
                    state.inc[p] = state.inc.get(p, 0) + (op.value or 0)
        else:
            for p in op.pred:
                if visible.pop(p, None) is not None:
                    state.inc.pop(p, None)
            if op.action == Action.SET or op.action.makes_object:
                visible[opid] = val
        if visible:
            winner, value, link, datatype, conflicts = _display(
                state, visible
            )
            diffs.append(
                Diff(
                    # a tombstoned elem coming back to life (concurrent
                    # set vs delete) is an *insert* to the frontend
                    action="set" if had else "insert",
                    obj=oid,
                    obj_type=obj.type,
                    index=self._live_index(obj, elem),
                    elem_id=str(elem),
                    value=value,
                    link=link,
                    datatype=datatype,
                    conflicts=conflicts,
                )
            )
        elif had:
            # tombstone RETAINED in order/fields (OpSet keeps it: later
            # remote inserts may reference this elem)
            diffs.append(
                Diff(
                    action="remove",
                    obj=oid,
                    obj_type=obj.type,
                    index=self._live_index(obj, elem),
                    elem_id=str(elem),
                )
            )


# ---------------------------------------------------------------------------
# lane adapters


class _LaneDict:
    __slots__ = ("visible", "elem_live", "rank", "inc_total")

    def __init__(self, host: Dict[str, np.ndarray], d: int) -> None:
        self.visible = host["visible"][d]
        self.elem_live = host["elem_live"][d]
        self.rank = host["rank"][d]
        self.inc_total = host["inc_total"][d]
