"""Streaming slab pipeline — overlap IO → pack → dispatch → fetch.

The serial bulk loader pays a cold open as the SUM of its per-slab
stage costs: sidecar IO, spec, pack, upload/dispatch, and the summary
fetch each finish completely before the next begins (BENCH_r05: 9.45s
= 0.37 sql + 1.99 io + 0.21 spec + 2.96 pack + ~0.1 wire + 2.68 fetch
+ 1.14 other). But the stages are independent per slab: slab N+1's
sidecar reads and native pack need nothing from slab N beyond host
buffers, and slab N's device work needs nothing from the host at all.
This module is the classic software-pipelining / double-buffering move
from accelerator input pipelines: four stages connected by small
BOUNDED queues so the cold open costs ~max(stage) instead of
sum(stages), with at most `HM_PIPELINE_DEPTH` (default 2) slabs of
host staging alive per seam — double buffering, not an unbounded
backlog.

    io/spec thread:   slab read-ahead (storage/slab.py mmap slices +
                      colcache decode; file reads drop the GIL) and
                      per-doc feed specs, emitted as slab-sized entry
                      groups — composition IDENTICAL to the serial
                      loader's chunks, so summaries are bit-identical.
    pack thread:      pack_docs_columns — the native hm_pack_prefix
                      call is bound through ctypes.CDLL and therefore
                      RELEASES the GIL (native/__init__.py), so packs
                      genuinely overlap the io thread's reads.
    caller thread:    async device upload + dispatch (round-robin
                      across visible devices via parallel/sharded.py
                      SlabRoundRobin, mesh-sharded, or single-device)
                      plus deferred doc init; never blocks on results.
    fetch workers:    summary wire transfer + host parse for slab N
                      overlapped with slab N+1's pack; with >1 device
                      one worker per chip (bounded, HM_FETCH_WORKERS)
                      so fetches overlap ACROSS chips too. The
                      materialization barrier (fetch_bulk_summaries)
                      joins them and finds host arrays.

Failure contract: any stage raising aborts the whole pipeline — every
queue drains, every worker joins (bounded), device refs drop, and the
caller sees one PipelineError carrying the original exception. A fetch
failure after the load returned surfaces at the barrier via
FetchContext.join. The serial path stays available behind
HM_PIPELINE=0 as the correctness twin.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from ..analysis.lockdep import make_lock
from .. import telemetry

# process-wide pipeline series (telemetry registry): cumulative stage
# busy seconds + slab counts across every bulk load, and live queue
# depth gauges — the "what is the cold open doing RIGHT NOW" view
# tools/top.py renders. last_bulk_stats stays the per-load truth
# bench.py scrapes; these are the daemon-lifetime aggregate.
_M_SLABS = telemetry.counter("pipeline.slabs")
_M_BUSY = {
    stage: telemetry.counter(f"pipeline.{stage}_busy_s")
    for stage in ("io", "pack", "dispatch", "fetch")
}


class PipelineError(RuntimeError):
    """A pipeline stage failed; the original exception is __cause__."""


class _Abort(Exception):
    """Internal: another stage failed; unwind quietly."""


_DONE = object()
_POLL_S = 0.05
_JOIN_S = 120.0


def pipeline_enabled() -> bool:
    """Pipeline gate. Explicit HM_PIPELINE=0/1 always wins; the unset
    default enables the pipeline only when the native GIL-dropping
    pack is actually loadable (HM_NATIVE_PACK not disabled). With the
    pure-numpy pack fallback, the pack worker holds the GIL for long
    stretches and starves the dispatch feeder on a small host — the
    r5 measurement that kept packing serial — so that configuration
    stays on the serial twin unless forced."""
    v = os.environ.get("HM_PIPELINE")
    if v is not None:
        return v != "0"
    if os.environ.get("HM_NATIVE_PACK", "1") == "0":
        return False
    from .. import native

    return native.pack_drops_gil()


def queue_depth() -> int:
    return max(1, int(os.environ.get("HM_PIPELINE_DEPTH", "2")))


class FetchContext:
    """Handle on the async fetch stage (one or more workers — with >1
    device the fetch overlaps ACROSS chips: each worker can be pulling
    a different chip's wire concurrently). The barrier
    (RepoBackend.fetch_bulk_summaries) joins it before decoding; a
    fetch error recorded during the overlap window re-raises there."""

    def __init__(self) -> None:
        self.threads: List[threading.Thread] = []
        self.error: Optional[BaseException] = None

    def join(self, timeout: float = _JOIN_S) -> None:
        for t in self.threads:
            t.join(timeout)
            if t.is_alive():  # pragma: no cover - defensive
                raise PipelineError("pipeline fetch stage did not drain")
        if self.error is not None:
            raise PipelineError(
                "bulk summary fetch failed"
            ) from self.error


class SlabPipeline:
    """One bulk load's stage executor. All callables are supplied by
    RepoBackend (which owns locks, stats, and device handles):

      prefetch(doc_chunk)      read-ahead actors + sidecar columns
      classify(doc)            -> ("entry", e) | ("memo", (e, m))
                                  | ("fallback", doc)
      pack(entries)            -> ColumnarBatch
      dispatch(entries, batch) -> pending summary entry (runs on the
                                  CALLER thread — device dispatch and
                                  doc init stay single-threaded)
      fetch(entry)             transfer + parse one slab's summary
                                  (mutates the entry in place)
    """

    def __init__(
        self,
        docs: List[Any],
        *,
        prefetch: Callable[[List[Any]], None],
        classify: Callable[[Any], Tuple[str, Any]],
        pack: Callable[[List[Any]], Any],
        dispatch: Callable[[List[Any], Any], Any],
        fetch: Callable[[Any], None],
        slab: int,
        fetch_workers: int = 1,
    ) -> None:
        self.docs = docs
        self.prefetch = prefetch
        self.classify = classify
        self.pack = pack
        self.dispatch = dispatch
        self.fetch = fetch
        self.slab = max(1, int(slab))
        self.fetch_workers = max(1, int(fetch_workers))
        depth = queue_depth()
        self.pack_q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.disp_q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.fetch_q: "queue.Queue" = queue.Queue(maxsize=2 * depth)
        # live queue-depth gauges (one table per seam, process-wide:
        # concurrent loads share the gauges — last writer wins, which
        # is the right answer for a "now" view)
        self._q_gauges = {
            id(self.pack_q): telemetry.gauge("pipeline.q_pack"),
            id(self.disp_q): telemetry.gauge("pipeline.q_dispatch"),
            id(self.fetch_q): telemetry.gauge("pipeline.q_fetch"),
        }
        self.abort = threading.Event()
        self.error: Optional[BaseException] = None
        self.error_stage: Optional[str] = None
        self._err_lock = make_lock("pipeline.err")
        self.memo_hits: List[Any] = []
        self.fallbacks: List[Any] = []

    # -- queue plumbing (abort-aware: a failed stage must never leave a
    # sibling blocked forever on a full/empty bounded queue) ----------

    def _put(self, q: "queue.Queue", item: Any) -> None:
        while True:
            if self.abort.is_set():
                raise _Abort()
            try:
                q.put(item, timeout=_POLL_S)
                self._q_gauges[id(q)].set(q.qsize())
                return
            except queue.Full:
                continue

    def _get(self, q: "queue.Queue") -> Any:
        while True:
            if self.abort.is_set():
                raise _Abort()
            try:
                item = q.get(timeout=_POLL_S)
                self._q_gauges[id(q)].set(q.qsize())
                return item
            except queue.Empty:
                continue

    def _fail(self, stage: str, exc: BaseException) -> None:
        with self._err_lock:
            if self.error is None:
                self.error = exc
                self.error_stage = stage
        self.abort.set()

    # -- stages ---------------------------------------------------------

    def _io_loop(self) -> None:
        """Read-ahead + spec: emits slab-sized entry groups in doc
        order — exactly the chunks the serial loader would form, so
        pipeline and serial materialize bit-identical slabs."""
        try:
            buf: List[Any] = []
            for base in range(0, len(self.docs), self.slab):
                if self.abort.is_set():
                    raise _Abort()
                chunk = self.docs[base : base + self.slab]
                t0 = time.perf_counter()
                with telemetry.span("pipeline.io", "pipeline"):
                    self.prefetch(chunk)
                    for doc in chunk:
                        kind, payload = self.classify(doc)
                        if kind == "entry":
                            buf.append(payload)
                        elif kind == "memo":
                            self.memo_hits.append(payload)
                        else:
                            self.fallbacks.append(payload)
                _M_BUSY["io"].add(time.perf_counter() - t0)
                # the put blocks on a full queue: that's backpressure
                # WAIT, not io busy — keep it outside the busy window
                while len(buf) >= self.slab:
                    self._put(self.pack_q, buf[: self.slab])
                    buf = buf[self.slab :]
            if buf:
                self._put(self.pack_q, buf)
            self._put(self.pack_q, _DONE)
        except _Abort:
            pass
        except BaseException as e:
            self._fail("io", e)

    def _pack_loop(self) -> None:
        try:
            while True:
                item = self._get(self.pack_q)
                if item is _DONE:
                    self._put(self.disp_q, _DONE)
                    return
                t0 = time.perf_counter()
                with telemetry.span("pipeline.pack", "pipeline"):
                    packed = self.pack(item)
                _M_BUSY["pack"].add(time.perf_counter() - t0)
                _M_SLABS.add(1)
                self._put(self.disp_q, (item, packed))
        except _Abort:
            pass
        except BaseException as e:
            self._fail("pack", e)

    def _fetch_loop(self, ctx: FetchContext) -> None:
        try:
            while True:
                item = self._get(self.fetch_q)
                if item is _DONE:
                    # recirculate the token so sibling workers (fetch
                    # overlaps across chips) see it and drain too
                    self._put(self.fetch_q, _DONE)
                    return
                t0 = time.perf_counter()
                with telemetry.span("pipeline.fetch", "pipeline"):
                    self.fetch(item)
                _M_BUSY["fetch"].add(time.perf_counter() - t0)
        except _Abort:
            pass
        except BaseException as e:
            self._fail("fetch", e)
            ctx.error = e

    # -- driver ---------------------------------------------------------

    def run(self, ctx: FetchContext) -> Tuple[List[Any], List[Any]]:
        """Run the pipeline to completion on the caller thread (which
        owns dispatch + doc init). Returns (memo_hits, fallbacks); the
        fetch thread may still be draining — `ctx` tracks it for the
        barrier. Raises PipelineError if any stage failed."""
        io_t = threading.Thread(
            target=self._io_loop, name="hm-pipe-io", daemon=True
        )
        pack_t = threading.Thread(
            target=self._pack_loop, name="hm-pipe-pack", daemon=True
        )
        fetch_ts = [
            threading.Thread(
                target=self._fetch_loop,
                args=(ctx,),
                name=f"hm-pipe-fetch-{i}",
                daemon=True,
            )
            for i in range(self.fetch_workers)
        ]
        ctx.threads = fetch_ts
        io_t.start()
        pack_t.start()
        for t in fetch_ts:
            t.start()
        try:
            while True:
                item = self._get(self.disp_q)
                if item is _DONE:
                    break
                entries, batch = item
                t0 = time.perf_counter()
                with telemetry.span("pipeline.dispatch", "pipeline"):
                    pending = self.dispatch(entries, batch)
                _M_BUSY["dispatch"].add(time.perf_counter() - t0)
                self._put(self.fetch_q, pending)
            self._put(self.fetch_q, _DONE)
        except _Abort:
            pass
        except BaseException as e:
            self._fail("dispatch", e)
        # upstream stages are done (or aborting): join them bounded
        io_t.join(_JOIN_S)
        pack_t.join(_JOIN_S)
        if self.error is not None:
            # drain so nothing pins batches/device refs, then take the
            # fetch workers down too — the load failed as a unit
            for t in fetch_ts:
                t.join(_JOIN_S)
            for q in (self.pack_q, self.disp_q, self.fetch_q):
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
            if (
                io_t.is_alive()
                or pack_t.is_alive()
                or any(t.is_alive() for t in fetch_ts)
            ):
                raise PipelineError(  # pragma: no cover - defensive
                    f"pipeline stage '{self.error_stage}' failed and "
                    "workers did not drain"
                ) from self.error
            raise PipelineError(
                f"bulk load pipeline stage '{self.error_stage}' failed"
            ) from self.error
        if io_t.is_alive() or pack_t.is_alive():
            raise PipelineError(  # pragma: no cover - defensive
                "pipeline workers did not drain"
            )
        return self.memo_hits, self.fallbacks
