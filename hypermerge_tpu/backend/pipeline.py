"""Streaming slab pipeline — overlap IO → pack → dispatch → fetch.

The serial bulk loader pays a cold open as the SUM of its per-slab
stage costs: sidecar IO, spec, pack, upload/dispatch, and the summary
fetch each finish completely before the next begins (BENCH_r05: 9.45s
= 0.37 sql + 1.99 io + 0.21 spec + 2.96 pack + ~0.1 wire + 2.68 fetch
+ 1.14 other). But the stages are independent per slab: slab N+1's
sidecar reads and native pack need nothing from slab N beyond host
buffers, and slab N's device work needs nothing from the host at all.
This module is the classic software-pipelining / double-buffering move
from accelerator input pipelines: four stages connected by small
BOUNDED queues so the cold open costs ~max(stage) instead of
sum(stages), with at most `HM_PIPELINE_DEPTH` (default 2) slabs of
host staging alive per seam — double buffering, not an unbounded
backlog.

    io/spec thread:   slab read-ahead (storage/slab.py mmap slices +
                      colcache decode; file reads drop the GIL) and
                      per-doc feed specs, emitted as slab-sized entry
                      groups — composition IDENTICAL to the serial
                      loader's chunks, so summaries are bit-identical.
    pack pool:        pack_docs_columns on HM_PACK_WORKERS threads —
                      the native hm_pack_prefix call is bound through
                      ctypes.CDLL and therefore RELEASES the GIL
                      (native/__init__.py pack_parallel_ok), so N
                      workers pack N slabs on N cores concurrently.
                      Sharding is slab-granular and the emit into the
                      dispatch queue is SEQUENCED (a turn counter under
                      the pipeline.pack_pool condition), so slab order
                      and bytes stay identical to the single-worker
                      and serial twins no matter which worker finishes
                      first. Per-worker busy seconds are kept apart
                      (pack_busy[w]) so busy-vs-wall accounting stays
                      honest — the SUM of pack busy can exceed the
                      load's wall once packs genuinely overlap.
    caller thread:    async device upload + dispatch (round-robin
                      across visible devices via parallel/sharded.py
                      SlabRoundRobin, mesh-sharded, or single-device)
                      plus deferred doc init; never blocks on results.
    fetch workers:    summary wire transfer + host parse for slab N
                      overlapped with slab N+1's pack; with >1 device
                      one worker per chip (bounded, HM_FETCH_WORKERS)
                      so fetches overlap ACROSS chips too. The
                      materialization barrier (fetch_bulk_summaries)
                      joins them and finds host arrays.

Failure contract: any stage raising aborts the whole pipeline — every
queue drains, every worker joins (bounded), device refs drop, and the
caller sees one PipelineError carrying the original exception. A fetch
failure after the load returned surfaces at the barrier via
FetchContext.join. The serial path stays available behind
HM_PIPELINE=0 as the correctness twin.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from ..analysis.lockdep import make_condition, make_lock
from .. import telemetry

# process-wide pipeline series (telemetry registry): cumulative stage
# busy seconds + slab counts across every bulk load, and live queue
# depth gauges — the "what is the cold open doing RIGHT NOW" view
# tools/top.py renders. last_bulk_stats stays the per-load truth
# bench.py scrapes; these are the daemon-lifetime aggregate.
_M_SLABS = telemetry.counter("pipeline.slabs")
_M_BUSY = {
    stage: telemetry.counter(f"pipeline.{stage}_busy_s")
    for stage in ("io", "pack", "dispatch", "fetch")
}


class PipelineError(RuntimeError):
    """A pipeline stage failed; the original exception is __cause__."""


class _Abort(Exception):
    """Internal: another stage failed; unwind quietly."""


_DONE = object()
_POLL_S = 0.05
_JOIN_S = 120.0


def pipeline_enabled() -> bool:
    """Pipeline gate. Explicit HM_PIPELINE=0/1 always wins; the unset
    default enables the pipeline only when the native GIL-dropping
    pack is actually loadable (HM_NATIVE_PACK not disabled). With the
    pure-numpy pack fallback, the pack worker holds the GIL for long
    stretches and starves the dispatch feeder on a small host — the
    r5 measurement that kept packing serial — so that configuration
    stays on the serial twin unless forced."""
    v = os.environ.get("HM_PIPELINE")
    if v is not None:
        return v != "0"
    if os.environ.get("HM_NATIVE_PACK", "1") == "0":
        return False
    from .. import native

    return native.pack_drops_gil()


def queue_depth() -> int:
    return max(1, int(os.environ.get("HM_PIPELINE_DEPTH", "2")))


def pack_worker_count() -> int:
    """Size of the pack pool. HM_PACK_WORKERS=N pins N workers; 0 (the
    default) resolves automatically: min(4, cores) when the native pack
    entry points both drop the GIL and are safe to call concurrently
    (native.pack_parallel_ok — stateless C loops into caller-owned
    buffers), else 1 — the numpy scatter twin holds the GIL for long
    stretches, so extra pack threads would only contend."""
    v = int(os.environ.get("HM_PACK_WORKERS", "0") or 0)
    if v > 0:
        return v
    from .. import native

    if not native.pack_parallel_ok():
        return 1
    return max(1, min(4, os.cpu_count() or 1))


class FetchContext:
    """Handle on the async fetch stage (one or more workers — with >1
    device the fetch overlaps ACROSS chips: each worker can be pulling
    a different chip's wire concurrently). The barrier
    (RepoBackend.fetch_bulk_summaries) joins it before decoding; a
    fetch error recorded during the overlap window re-raises there."""

    def __init__(self) -> None:
        self.threads: List[threading.Thread] = []
        self.error: Optional[BaseException] = None

    def join(self, timeout: float = _JOIN_S) -> None:
        for t in self.threads:
            t.join(timeout)
            if t.is_alive():  # pragma: no cover - defensive
                raise PipelineError("pipeline fetch stage did not drain")
        if self.error is not None:
            raise PipelineError(
                "bulk summary fetch failed"
            ) from self.error


class SlabPipeline:
    """One bulk load's stage executor. All callables are supplied by
    RepoBackend (which owns locks, stats, and device handles):

      prefetch(doc_chunk)      read-ahead actors + sidecar columns
      classify(doc)            -> ("entry", e) | ("memo", (e, m))
                                  | ("fallback", doc)
      pack(entries, seq)       -> ColumnarBatch (seq = slab index in
                                  doc order — the device-pack path
                                  uses it for per-chip placement)
      dispatch(entries, batch) -> pending summary entry (runs on the
                                  CALLER thread — device dispatch and
                                  doc init stay single-threaded)
      fetch(entry)             transfer + parse one slab's summary
                                  (mutates the entry in place)
    """

    def __init__(
        self,
        docs: List[Any],
        *,
        prefetch: Callable[[List[Any]], None],
        classify: Callable[[Any], Tuple[str, Any]],
        pack: Callable[[List[Any], int], Any],
        dispatch: Callable[[List[Any], Any], Any],
        fetch: Callable[[Any], None],
        slab: int,
        fetch_workers: int = 1,
        pack_workers: int = 1,
    ) -> None:
        self.docs = docs
        self.prefetch = prefetch
        self.classify = classify
        self.pack = pack
        self.dispatch = dispatch
        self.fetch = fetch
        self.slab = max(1, int(slab))
        self.fetch_workers = max(1, int(fetch_workers))
        self.pack_workers = max(1, int(pack_workers))
        depth = queue_depth()
        self.pack_q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.disp_q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.fetch_q: "queue.Queue" = queue.Queue(maxsize=2 * depth)
        # live queue-depth gauges (one table per seam, process-wide:
        # concurrent loads share the gauges — last writer wins, which
        # is the right answer for a "now" view)
        self._q_gauges = {
            id(self.pack_q): telemetry.gauge("pipeline.q_pack"),
            id(self.disp_q): telemetry.gauge("pipeline.q_dispatch"),
            id(self.fetch_q): telemetry.gauge("pipeline.q_fetch"),
        }
        self.abort = threading.Event()
        self.error: Optional[BaseException] = None
        self.error_stage: Optional[str] = None
        self._err_lock = make_lock("pipeline.err")
        self.memo_hits: List[Any] = []
        self.fallbacks: List[Any] = []
        # -- pack pool sequencing + per-worker busy accounting ---------
        # slabs are packed CONCURRENTLY but emitted into disp_q in slab
        # order: a worker holding packed slab `seq` waits its turn on
        # the pack_pool condition, so downstream (dispatch, fetch, doc
        # init) sees the exact slab stream the serial twin produces.
        self._pack_cv = make_condition("pipeline.pack_pool")
        self._pack_turn = 0         # next slab seq allowed to emit
        self._pack_eof_claimed = False  # one worker forwards _DONE
        self.total_slabs: Optional[int] = None  # set by io before EOF
        # per-worker slots, single-writer by construction (worker w is
        # the only writer of index w) — read after the workers join
        self.pack_busy = [0.0] * self.pack_workers
        self.pack_t0 = [None] * self.pack_workers  # first pack start
        self.pack_t1 = [None] * self.pack_workers  # last pack end

    # -- queue plumbing (abort-aware: a failed stage must never leave a
    # sibling blocked forever on a full/empty bounded queue) ----------

    def _put(self, q: "queue.Queue", item: Any) -> None:
        while True:
            if self.abort.is_set():
                raise _Abort()
            try:
                q.put(item, timeout=_POLL_S)
                self._q_gauges[id(q)].set(q.qsize())
                return
            except queue.Full:
                continue

    def _get(self, q: "queue.Queue") -> Any:
        while True:
            if self.abort.is_set():
                raise _Abort()
            try:
                item = q.get(timeout=_POLL_S)
                self._q_gauges[id(q)].set(q.qsize())
                return item
            except queue.Empty:
                continue

    def _fail(self, stage: str, exc: BaseException) -> None:
        with self._err_lock:
            if self.error is None:
                self.error = exc
                self.error_stage = stage
        self.abort.set()

    # -- stages ---------------------------------------------------------

    def _io_loop(self) -> None:
        """Read-ahead + spec: emits slab-sized entry groups in doc
        order — exactly the chunks the serial loader would form, so
        pipeline and serial materialize bit-identical slabs."""
        try:
            buf: List[Any] = []
            seq = 0
            for base in range(0, len(self.docs), self.slab):
                if self.abort.is_set():
                    raise _Abort()
                chunk = self.docs[base : base + self.slab]
                t0 = time.perf_counter()
                with telemetry.span("pipeline.io", "pipeline"):
                    self.prefetch(chunk)
                    for doc in chunk:
                        kind, payload = self.classify(doc)
                        if kind == "entry":
                            buf.append(payload)
                        elif kind == "memo":
                            self.memo_hits.append(payload)
                        else:
                            self.fallbacks.append(payload)
                _M_BUSY["io"].add(time.perf_counter() - t0)
                # the put blocks on a full queue: that's backpressure
                # WAIT, not io busy — keep it outside the busy window
                while len(buf) >= self.slab:
                    self._put(self.pack_q, (seq, buf[: self.slab]))
                    seq += 1
                    buf = buf[self.slab :]
            if buf:
                self._put(self.pack_q, (seq, buf))
                seq += 1
            # publish the slab count BEFORE the EOF token: the worker
            # that claims EOF forwarding reads it after taking the
            # token off the queue (queue put/get is the happens-before)
            self.total_slabs = seq
            self._put(self.pack_q, _DONE)
        except _Abort:
            pass
        except BaseException as e:
            self._fail("io", e)

    def _await_pack_turn(self, seq: int) -> None:
        """Block until slab `seq` may emit into disp_q (ordered merge
        of the pack pool's out-of-order completions). Abort-aware."""
        with self._pack_cv:
            while self._pack_turn != seq:
                if self.abort.is_set():
                    raise _Abort()
                self._pack_cv.wait(_POLL_S)

    def _bump_pack_turn(self) -> None:
        with self._pack_cv:
            self._pack_turn += 1
            self._pack_cv.notify_all()

    def pack_wall(self) -> float:
        """Pack LANE span: first pack start -> last pack end across the
        pool. This is the wall-clock footprint of the pack stage; with
        N workers the busy SUM (sum(pack_busy)) exceeds it once packs
        genuinely overlap, and busy/wall is the measured parallel
        speedup. Read after the workers joined."""
        t0s = [t for t in self.pack_t0 if t is not None]
        t1s = [t for t in self.pack_t1 if t is not None]
        if not t0s or not t1s:
            return 0.0
        return max(0.0, max(t1s) - min(t0s))

    def _pack_loop(self, widx: int) -> None:
        """One pack-pool worker. Workers race through pack_q (slab
        compute overlaps across cores — hm_pack_prefix drops the GIL)
        but emit strictly in slab order via the turn counter, so the
        dispatch stream is byte-identical to a single pack thread. The
        EOF token recirculates to drain siblings; exactly one worker
        claims it and forwards _DONE only after every real slab
        emitted."""
        try:
            while True:
                item = self._get(self.pack_q)
                if item is _DONE:
                    # siblings need the token too
                    self._put(self.pack_q, _DONE)
                    with self._pack_cv:
                        if self._pack_eof_claimed:
                            return
                        self._pack_eof_claimed = True
                    self._await_pack_turn(self.total_slabs)
                    self._put(self.disp_q, _DONE)
                    return
                seq, entries = item
                t0 = time.perf_counter()
                with telemetry.span("pipeline.pack", "pipeline"):
                    packed = self.pack(entries, seq)
                t1 = time.perf_counter()
                self.pack_busy[widx] += t1 - t0
                if self.pack_t0[widx] is None:
                    self.pack_t0[widx] = t0
                self.pack_t1[widx] = t1
                _M_BUSY["pack"].add(t1 - t0)
                _M_SLABS.add(1)
                # ordered emit: the turn-wait is backpressure, not busy
                self._await_pack_turn(seq)
                self._put(self.disp_q, (entries, packed))
                self._bump_pack_turn()
        except _Abort:
            pass
        except BaseException as e:
            self._fail("pack", e)

    def _fetch_loop(self, ctx: FetchContext) -> None:
        try:
            while True:
                item = self._get(self.fetch_q)
                if item is _DONE:
                    # recirculate the token so sibling workers (fetch
                    # overlaps across chips) see it and drain too
                    self._put(self.fetch_q, _DONE)
                    return
                t0 = time.perf_counter()
                with telemetry.span("pipeline.fetch", "pipeline"):
                    self.fetch(item)
                _M_BUSY["fetch"].add(time.perf_counter() - t0)
        except _Abort:
            pass
        except BaseException as e:
            self._fail("fetch", e)
            ctx.error = e

    # -- driver ---------------------------------------------------------

    def run(self, ctx: FetchContext) -> Tuple[List[Any], List[Any]]:
        """Run the pipeline to completion on the caller thread (which
        owns dispatch + doc init). Returns (memo_hits, fallbacks); the
        fetch thread may still be draining — `ctx` tracks it for the
        barrier. Raises PipelineError if any stage failed."""
        io_t = threading.Thread(
            target=self._io_loop, name="hm-pipe-io", daemon=True
        )
        pack_ts = [
            threading.Thread(
                target=self._pack_loop,
                args=(i,),
                name=f"hm-pipe-pack-{i}",
                daemon=True,
            )
            for i in range(self.pack_workers)
        ]
        fetch_ts = [
            threading.Thread(
                target=self._fetch_loop,
                args=(ctx,),
                name=f"hm-pipe-fetch-{i}",
                daemon=True,
            )
            for i in range(self.fetch_workers)
        ]
        ctx.threads = fetch_ts
        io_t.start()
        for t in pack_ts:
            t.start()
        for t in fetch_ts:
            t.start()
        try:
            while True:
                item = self._get(self.disp_q)
                if item is _DONE:
                    break
                entries, batch = item
                t0 = time.perf_counter()
                with telemetry.span("pipeline.dispatch", "pipeline"):
                    pending = self.dispatch(entries, batch)
                _M_BUSY["dispatch"].add(time.perf_counter() - t0)
                self._put(self.fetch_q, pending)
            self._put(self.fetch_q, _DONE)
        except _Abort:
            pass
        except BaseException as e:
            self._fail("dispatch", e)
        # upstream stages are done (or aborting): join them bounded
        io_t.join(_JOIN_S)
        for t in pack_ts:
            t.join(_JOIN_S)
        if self.error is not None:
            # drain so nothing pins batches/device refs, then take the
            # fetch workers down too — the load failed as a unit
            for t in fetch_ts:
                t.join(_JOIN_S)
            for q in (self.pack_q, self.disp_q, self.fetch_q):
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
            if (
                io_t.is_alive()
                or any(t.is_alive() for t in pack_ts)
                or any(t.is_alive() for t in fetch_ts)
            ):
                raise PipelineError(  # pragma: no cover - defensive
                    f"pipeline stage '{self.error_stage}' failed and "
                    "workers did not drain"
                ) from self.error
            raise PipelineError(
                f"bulk load pipeline stage '{self.error_stage}' failed"
            ) from self.error
        if io_t.is_alive() or any(t.is_alive() for t in pack_ts):
            raise PipelineError(  # pragma: no cover - defensive
                "pipeline workers did not drain"
            )
        return self.memo_hits, self.fallbacks
