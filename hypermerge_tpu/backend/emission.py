"""Per-doc emission domains — the many-writer write plane.

Until this module, `live.engine` was THE emission lock: every
{compute patch -> feed append -> IPC push} pair in the repo — every
doc, every writer thread — serialized under one global re-entrant
lock, and at HM_FSYNC=2 that put ~0.4ms of platter time under the
global lock per acked edit (bench `config_lockdebt`, BASELINE round
17). This module splits emission ordering into per-doc domains:

- `EmissionDomain` — ONE re-entrant lock per doc, the emission
  ordering domain. Everything that must stay ordered is per-doc: a
  Ready snapshot may not be overtaken by a newer delta patch OF THE
  SAME DOC; a local echo must precede the next tick's delta ON THE
  SAME DOC. Disjoint docs' emissions have no ordering contract, so
  they now run concurrently — feed appends, WAL commits, and frontend
  pushes for different docs proceed on different threads in parallel.

- the **no-cross-doc invariant**: a thread never holds two docs'
  domains at once, and never holds any OTHER doc's domain across a
  feed append or push. Machine-checked twice: `doc.emit` ranks at 8
  and lockdep flags a same-class nested acquisition as an order
  violation, and the domain tracks a thread-local stack of entered
  doc ids so re-entry can be detected.

- `entered_other(doc_id)` + `defer(fn)` — the re-entrancy escape
  hatch. A frontend callback dispatched synchronously from a push
  (the pushing thread holds that doc's domain) may re-enter the repo:
  same-doc re-entry simply recurses on the re-entrant domain; a
  CROSS-doc call (change/open of another doc from inside a patch
  callback) must not nest domains — the caller parks the work on the
  deferred-emission worker, which replays it on a clean thread with
  no domains held. This replaces the old answer (one global lock so
  re-entry always recurses) without reintroducing the global
  serialization.

The engine lock (`live.engine`) survives as tick/dirty-set
COORDINATION only and is never held across a blocking call —
`lock.held_blocking_ms.live_engine` reading 0.0 at every HM_FSYNC
tier is the acceptance gate bench `config_lockdebt` measures.
"""

from __future__ import annotations

import threading
from typing import Callable, List

from ..analysis.lockdep import make_condition, make_lock, make_rlock
from ..utils.debug import log

_tls = threading.local()


def _stack() -> List[str]:
    s = getattr(_tls, "domains", None)
    if s is None:
        s = _tls.domains = []
    return s


def entered_ids() -> List[str]:
    """Doc ids whose emission domains the CURRENT thread holds."""
    return list(_stack())


def entered_other(doc_id: str) -> bool:
    """True when this thread is mid-emission for a DIFFERENT doc —
    the caller must defer() instead of nesting domains."""
    return any(d != doc_id for d in _stack())


class EmissionDomain:
    """One doc's emission ordering domain: a re-entrant `doc.emit`
    lock plus the thread-local entry bookkeeping the cross-doc
    invariant is checked against. Used as a context manager."""

    def __init__(self, doc_id: str) -> None:
        self.doc_id = doc_id
        self._lock = make_rlock("doc.emit")

    def __enter__(self) -> "EmissionDomain":
        self._lock.acquire()
        _stack().append(self.doc_id)
        return self

    def __exit__(self, *exc) -> None:
        _stack().pop()
        self._lock.release()

    def held_by_me(self) -> bool:
        return self.doc_id in _stack()


# ---------------------------------------------------------------------------
# deferred-emission worker (cross-doc re-entry escape hatch)

_defer_lock = make_lock("doc.emit.defer")
_defer_cv = make_condition("doc.emit.defer", _defer_lock)
_defer_items: List[Callable[[], None]] = []
_defer_thread = None


def defer(fn: Callable[[], None]) -> None:
    """Run `fn` on the deferred-emission worker — a clean thread with
    no emission domains held. Per-source ordering is preserved (one
    worker drains in FIFO order); the deferred path is the RARE
    cross-doc re-entry case, not a hot path."""
    global _defer_thread
    with _defer_cv:
        _defer_items.append(fn)
        if _defer_thread is None or not _defer_thread.is_alive():
            _defer_thread = threading.Thread(
                target=_defer_loop, daemon=True, name="hm-emit-defer"
            )
            _defer_thread.start()
        _defer_cv.notify()


def _defer_loop() -> None:
    while True:
        with _defer_cv:
            while not _defer_items:
                _defer_cv.wait()
            batch = list(_defer_items)
            del _defer_items[:]
        for fn in batch:
            try:
                fn()
            except Exception as e:  # pragma: no cover - defensive
                log("emission", f"deferred emission failed: {e}")
