"""Append-only per-actor feeds + FeedStore.

Parity: the hypercore feed + FeedStore surface the reference relies on
(SURVEY.md §2.1 FeedStore; src/types/hypercore.d.ts append/get/getBatch/
stream/on('download'/'sync')). Design differences, TPU-first:

- A feed is a block log with a signed merkle root per append (signing in
  storage/integrity.py; writable feeds hold the secret key — feed identity
  IS the ed25519 public key, like the reference).
- Storage backends are pluggable like random-access-* (reference
  src/RepoBackend.ts:84): MemoryFeedStorage and FileFeedStorage.
- Readers can subscribe to appends (replication + Actor block parsing).

The columnar bulk loader (ops/columnar.py) reads whole feeds at once for
the batched cold-start path — `read_all` is the API it uses.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Callable, Dict, List, Optional

from ..analysis.lockdep import make_rlock
from ..utils import keys as keymod
from ..utils.debug import log
from ..utils.ids import DiscoveryId, get_or_create
from ..utils.queue import Queue
from .durability import fsync_tier
from .faults import harness_gen, io_fsync, io_open, io_remove


class MemoryFeedStorage:
    def __init__(self) -> None:
        self.blocks: List[bytes] = []

    def append(self, data: bytes) -> None:
        self.blocks.append(data)

    def get(self, index: int) -> bytes:
        return self.blocks[index]

    def __len__(self) -> int:
        return len(self.blocks)

    def destroy(self) -> None:
        self.blocks.clear()

    def close(self) -> None:  # pragma: no cover - nothing to do
        pass


class FileFeedStorage:
    """Length-prefixed block log + block-count index sidecar.

    Crash-safety model matches the reference's append-only philosophy
    (SURVEY.md §5 failure detection): a torn tail write is detected by the
    length prefix running past EOF and the tail is ignored — the same
    self-healing the reference applies to holey feeds
    (reference src/hypercore.ts:39-47).

    The `.len` sidecar holds (block_count, end_offset); when its end
    offset matches the log's stat size, `len(storage)` is a stat call —
    a bulk cold start with fresh columnar sidecars needs only the block
    COUNT of ten thousand feeds (the sidecar-trust check), not their
    bytes. Any mismatch (torn append, out-of-band edit) falls back to a
    full scan. The per-block offset index is built lazily on first
    `get`.

    Durability (storage/durability.py HM_FSYNC): tier 2 fsyncs the log
    inside `append` BEFORE the `.len` sidecar describes it; tier 1
    marks this storage dirty with the repo's DurabilityManager, whose
    group flusher calls `sync()`. Tier 0 (default) never fsyncs —
    crash-safe (torn tails heal), not crash-durable."""

    _HDR = struct.Struct("<I")
    _LEN = struct.Struct("<QQ")  # block count, end offset

    def __init__(self, path: str, durability=None) -> None:
        self.path = path
        self._durability = durability
        self._offsets: List[int] = []
        self._sizes: List[int] = []
        self._end = 0
        self._count: Optional[int] = None  # known count, offsets may lag
        self._scanned = False
        # the does-the-log-exist stat is deferred to first use: a bulk
        # cold open constructs thousands of these and metadata syscalls
        # are a measurable slice of its serial host time
        self._init_checked = False
        # cached write handles (log + .len sidecar): an acked edit's
        # append is the repo's hottest path, and re-opening both files
        # per append was ~0.5ms of serialized syscall+setup cost under
        # the per-doc emission domain (bench config_writers). Handles
        # open lazily on the first append — read-only consumers (the
        # bulk cold open's thousands of storages) never pay an fd —
        # and drop on close/destroy/repair/truncate. The appender
        # (under its doc's emission domain + feed lock) and the WAL
        # checkpoint thread's sync() share these fds: _io serializes
        # every use/drop (analysis/guards.py FileFeedStorage).
        self._io = make_rlock("store.feed_io")
        self._wfh = None
        self._len_fh = None
        self._fh_gen = -1  # faults.harness_gen() the handles saw

    def _check_gen(self) -> None:
        # a fault harness came or went since the handles were opened:
        # they must re-open through the io_* seam, or injected faults
        # and crash recording would bypass the hot path entirely.
        # REQUIRES store.feed_io (analysis/guards.py).
        gen = harness_gen()
        if gen != self._fh_gen:
            self._drop_write_handles()
            self._fh_gen = gen

    def _write_handle(self):
        # REQUIRES store.feed_io (analysis/guards.py)
        self._check_gen()
        if self._wfh is None or self._wfh.closed:
            mode = "r+b" if os.path.exists(self.path) else "w+b"
            self._wfh = io_open(self.path, mode)
        return self._wfh

    def _drop_write_handles(self) -> None:
        # REQUIRES store.feed_io (analysis/guards.py)
        for fh in (self._wfh, self._len_fh):
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
        self._wfh = None
        self._len_fh = None

    def _check_init(self) -> None:
        if self._init_checked:
            return
        self._init_checked = True
        if not os.path.exists(self.path):
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._scanned = True
            self._count = 0

    def _len_path(self) -> str:
        return self.path + ".len"

    def _write_len(self) -> None:
        # REQUIRES store.feed_io (analysis/guards.py)
        self._check_gen()
        fh = self._len_fh
        if fh is None or fh.closed:
            # w+b then in-place rewrites: the record is fixed-size, so
            # no truncate is ever needed after the first open
            self._len_fh = fh = io_open(self._len_path(), "w+b")
        fh.seek(0)
        fh.write(self._LEN.pack(self._count, self._end))
        fh.flush()

    def _try_count_shortcut(self) -> bool:
        """Trust the .len sidecar iff its end offset equals the log's
        actual size."""
        try:
            with open(self._len_path(), "rb") as fh:
                raw = fh.read(self._LEN.size)
            if len(raw) != self._LEN.size:
                return False
            count, end = self._LEN.unpack(raw)
            if os.path.getsize(self.path) != end:
                return False  # torn append or external edit: rescan
            self._count = count
            self._end = end
            return True
        except OSError:
            return False

    def _ensure_count(self) -> None:
        if self._count is not None:
            return
        if self._try_count_shortcut():
            # a matching .len proves the log exists: the healthy-feed
            # fast path costs one open + one stat, nothing else
            self._init_checked = True
            return
        self._check_init()
        self._ensure_scan()

    def _ensure_scan(self) -> None:
        self._check_init()
        if self._scanned:
            return
        self._scanned = True
        with open(self.path, "rb") as fh:
            raw = fh.read()
        end = len(raw)
        pos = 0
        self._offsets = []
        self._sizes = []
        while pos + self._HDR.size <= end:
            (size,) = self._HDR.unpack_from(raw, pos)
            if pos + self._HDR.size + size > end:
                break  # torn tail: ignore
            self._offsets.append(pos + self._HDR.size)
            self._sizes.append(size)
            pos += self._HDR.size + size
        self._end = pos
        self._count = len(self._offsets)

    def append(self, data: bytes) -> None:
        with self._io:
            self._append_io_locked(data)

    def _append_io_locked(self, data: bytes) -> None:
        # REQUIRES store.feed_io (analysis/guards.py)
        self._ensure_scan()
        tier = fsync_tier()
        # exception safety under mid-write ENOSPC/EIO: the in-memory
        # _offsets/_end/_count only advance AFTER every log byte landed
        # (and, at tier 2, fsynced) — a raise leaves memory pointing at
        # the pre-append end, so the next append seeks there, overwrites
        # the torn tail, and truncates the stale bytes. The (possibly
        # torn) on-disk tail is exactly what the scan already heals.
        # A raise also drops the cached handle: its buffered state is
        # no longer trustworthy.
        try:
            fh = self._write_handle()
            fh.seek(self._end)  # overwrite any torn tail...
            fh.write(self._HDR.pack(len(data)))
            fh.write(data)
            fh.truncate()  # ...and drop stale bytes beyond it, so a later
            # scan can't misparse leftovers as a phantom block
            fh.flush()
            # shared journal (storage/wal.py): at HM_FSYNC>=1 the
            # block's durability is ONE sequential journal append +
            # the group-commit fsync — the log itself stays page-cache
            # only until checkpoint. A raise here (journal fsync
            # error) unwinds exactly like a torn write: memory never
            # advances, the on-disk tail heals on the next append.
            journaled = False
            if self._durability is not None:
                journaled = self._durability.journal_append(
                    self.path, len(self._offsets), data, self
                )
            if tier >= 2 and not journaled:
                # legacy: log durable BEFORE the .len sidecar
                # describes it
                io_fsync(fh)
        except BaseException:
            self._drop_write_handles()
            raise
        self._offsets.append(self._end + self._HDR.size)
        self._sizes.append(len(data))
        self._end += self._HDR.size + len(data)
        self._count = len(self._offsets)
        try:
            self._write_len()
        except OSError as e:
            # the block is durable; the sidecar is advisory (a mismatch
            # just costs the next open a rescan) — never fail the
            # acked append over it
            log("storage:feed", f".len write failed {self.path}: {e}")
        if tier == 1 and not journaled and self._durability is not None:
            self._durability.mark_dirty(self)

    def sync(self) -> None:
        """Make the log (and its .len sidecar) durable: the tier-1
        group-fsync target and the pre-sqlite barrier. Log first, .len
        second — the sidecar must never describe unfsynced bytes.
        Serializes against the appender under _io: the WAL checkpoint
        thread calls this on a storage whose cached handles a writer
        may be mid-append on."""
        if not os.path.exists(self.path):
            return
        with self._io:
            self._check_gen()
            fh = self._wfh
            if fh is not None and not fh.closed:
                # the cached append handle: every append flushed
                # before _io released, so an fd-level fsync is safe
                io_fsync(fh)
            else:
                with io_open(self.path, "r+b") as fh:
                    io_fsync(fh)
            if self._count is not None:
                try:
                    self._write_len()
                    with io_open(self._len_path(), "r+b") as fh:
                        io_fsync(fh)
                except OSError as e:
                    log(
                        "storage:feed",
                        f".len sync failed {self.path}: {e}",
                    )

    def repair(self, write: bool = True) -> Dict[str, int]:
        """Crash recovery: scan the log, physically truncate any torn
        tail, rewrite the .len sidecar. Returns counters for the scrub
        report; write=False only reports (tools/scrub.py --dry-run).
        (Lazy healing would do all of this on the next append; repair
        makes the on-disk state clean NOW so audits, byte accounting,
        and read-only consumers see no leftovers.)"""
        out = {"blocks": 0, "bytes_truncated": 0}
        with self._io:
            self._drop_write_handles()  # repair rewrites out-of-band
            if not os.path.exists(self.path):
                return out
            # force a fresh scan (ignore any .len shortcut state)
            self._scanned = False
            self._count = None
            self._init_checked = True
            self._ensure_scan()
            out["blocks"] = self._count or 0
            size = os.path.getsize(self.path)
            if size > self._end:
                out["bytes_truncated"] = size - self._end
                if write:
                    with io_open(self.path, "r+b") as fh:
                        fh.truncate(self._end)
            if write:
                try:
                    self._write_len()
                except OSError:
                    pass
        return out

    def truncate_to(self, count: int) -> int:
        """Drop blocks beyond `count` (scrub's recovery for a READ-ONLY
        feed whose unsigned tail cannot be trusted — the blocks
        re-replicate from peers). Returns the number dropped."""
        with self._io:
            self._ensure_scan()
            if count >= len(self._offsets):
                return 0
            dropped = len(self._offsets) - count
            self._end = (
                self._offsets[count] - self._HDR.size if count else 0
            )
            del self._offsets[count:]
            del self._sizes[count:]
            self._count = count
            self._drop_write_handles()
            with io_open(self.path, "r+b") as fh:
                fh.truncate(self._end)
            try:
                self._write_len()
            except OSError:
                pass
        return dropped

    def get(self, index: int) -> bytes:
        self._ensure_scan()
        if index >= len(self._offsets):
            # the .len sidecar can promise more blocks than the scan
            # could parse (tampered/torn size header): the log truly
            # ends here — IndexError, not a silent empty read
            raise IndexError(
                f"block {index} beyond scanned log end "
                f"({len(self._offsets)} block(s))"
            )
        with open(self.path, "rb") as fh:
            fh.seek(self._offsets[index])
            return fh.read(self._sizes[index])

    def __len__(self) -> int:
        self._ensure_count()
        return self._count

    def destroy(self) -> None:
        """Remove the block log (and its .len index) from disk."""
        with self._io:
            self._drop_write_handles()
            for p in (self.path, self._len_path()):
                if os.path.exists(p):
                    io_remove(p)
            self._offsets = []
            self._sizes = []
            self._end = 0
            self._count = 0
            self._scanned = True

    def close(self) -> None:
        with self._io:
            self._drop_write_handles()


StorageFn = Callable[[str], object]  # name -> storage backend


def memory_storage_fn(_name: str) -> MemoryFeedStorage:
    return MemoryFeedStorage()


def file_storage_fn(root: str, durability=None) -> StorageFn:
    def fn(name: str) -> FileFeedStorage:
        return FileFeedStorage(
            os.path.join(root, name[:2], name), durability=durability
        )

    return fn


class Feed:
    """One append-only log, identified by its ed25519 public key."""

    def __init__(
        self,
        public_key: str,
        storage,
        secret_key: Optional[str] = None,
    ) -> None:
        self.public_key = public_key
        self.secret_key = secret_key
        self._discovery_id: Optional[str] = None  # lazy: ~40us of
        # base58+blake2b per feed adds up over a 10k-feed cold open
        self._storage = storage
        self._lock = make_rlock("store.feed")
        self._append_listeners: List[Callable[[int, bytes], None]] = []
        # chunk-granularity listeners: cb(start, end) once per extension
        # (a verified multi-block chunk fires ONE of these but one
        # on_append per block) — replication tails and progress events
        # subscribe here to avoid per-block amplification
        self._extend_listeners: List[Callable[[int, int], None]] = []
        # columnar sidecar (storage/colcache.py), attached by FeedStore
        # when a cache_fn is configured; maintained by Actor
        self.colcache = None
        # signed-merkle state (storage/integrity.py), attached by
        # FeedStore; loaded lazily (bulk cold opens never read it)
        self.integrity = None
        # sparse side-buffer: inclusion-proof-verified blocks fetched
        # OUT OF ORDER (net/replication.py range fetch — hypercore's
        # sparse download). The contiguous log stays authoritative;
        # entries are dropped as the head passes them.
        self._sparse: Dict[int, bytes] = {}
        self._sparse_listeners: List[Callable[[int, bytes], None]] = []

    @property
    def writable(self) -> bool:
        return self.secret_key is not None

    @property
    def discovery_id(self) -> str:
        if self._discovery_id is None:
            self._discovery_id = keymod.discovery_id(self.public_key)
        return self._discovery_id

    @property
    def length(self) -> int:
        with self._lock:
            return len(self._storage)

    def append(self, data: bytes) -> int:
        """Writer append: store the block AND extend the signed merkle
        log (storage/integrity.py) before listeners fire, so replication
        tails always have a signature covering what they push."""
        if not self.writable:
            raise PermissionError(f"feed {self.public_key[:8]} not writable")
        with self._lock:
            self._storage.append(data)
            index = len(self._storage) - 1
            if self.integrity is not None:
                self.integrity.sign_append(self, index, data)
            self._prune_sparse_locked()
            listeners = list(self._append_listeners)
            extended = list(self._extend_listeners)
        for cb in listeners:
            cb(index, data)
        for cb in extended:
            cb(index, index + 1)
        return index

    def append_verified(
        self, start: int, blocks: List[bytes], length: int, sig: bytes
    ) -> bool:
        """Replication append: verify the sender's signed merkle root
        over [0, length) BEFORE storing anything (the trust boundary —
        reference: hypercore verifies every replicated block against the
        feed key). Duplicate prefixes are tolerated; a gap or a bad
        signature stores nothing and returns False."""
        if self.integrity is None:
            return False
        with self._lock:
            have = len(self._storage)
            if length <= have:
                return True  # nothing new (stale retransmit)
            if start > have:
                return False  # gap: caller re-requests from our head
            eff = blocks[have - start :]
            if have + len(eff) != length:
                return False
            res = self.integrity.verify_extension(
                self, have, eff, length, sig
            )
            if res is None:
                return False
            root, new_leaves = res
            indices = []
            for b in eff:
                self._storage.append(b)
                indices.append(len(self._storage) - 1)
            self.integrity.record_verified(length, root, sig, new_leaves)
            self._prune_sparse_locked()
            listeners = list(self._append_listeners)
            extended = list(self._extend_listeners)
        for i, b in zip(indices, eff):
            for cb in listeners:
                cb(i, b)
        for cb in extended:
            cb(indices[0], length)
        return True

    def seal(self) -> None:
        """Persist a signed record at the current head. Live appends
        sign lazily (storage/integrity.py sign_interval); seal closes
        the gap so the on-disk chain covers every block — called on
        close and before audit."""
        if self.integrity is not None and self.writable and self.length:
            self.integrity.record_for(self, self.length)

    def audit(self) -> bool:
        """Re-hash the whole block log against the signed record chain
        (on-disk tamper detection). True for an empty unsigned feed.

        Sealing first happens ONLY for a tail this process itself
        appended (unsigned_tail — inside the local trust boundary). A
        tail found on disk beyond the last record — crash leftovers or
        an attacker's append — must FAIL the audit, never be signed
        into validity."""
        from .integrity import AUDIT_OK

        return self.audit_status() == AUDIT_OK

    def audit_status(self) -> str:
        """Three-way audit (storage/integrity.py AUDIT_*): "ok",
        "unsigned_tail" (a writable feed's crash-orphaned lazy-signing
        tail — recoverable: seal() signs a fresh head record), or
        "tampered". In-process unsigned tails are sealed before
        auditing, exactly as audit() always did."""
        from .integrity import AUDIT_TAMPERED

        if self.integrity is None:
            return AUDIT_TAMPERED  # unverifiable: no sig chain storage
        if self.writable and self.integrity.unsigned_tail:
            self.seal()
        return self.integrity.audit_status(self)

    def _append_raw(self, data: bytes) -> int:
        """Append without writability or signature checks. Only for
        callers inside the local trust boundary (tests, migration tools);
        replication MUST use append_verified."""
        with self._lock:
            self._storage.append(data)
            index = len(self._storage) - 1
            self._prune_sparse_locked()
            listeners = list(self._append_listeners)
            extended = list(self._extend_listeners)
        for cb in listeners:
            cb(index, data)
        for cb in extended:
            cb(index, index + 1)
        return index

    def put_sparse(self, index: int, data: bytes) -> bool:
        """Store an out-of-order block the caller has ALREADY verified
        (inclusion proof against a signed root — net/replication.py).

        The buffer is bounded (HM_SPARSE_CAP entries): when full, the
        entry FURTHEST beyond the contiguous head is evicted — blocks
        near the head are about to be absorbed by backfill, while far
        ones can be re-fetched; an incoming block beyond everything
        buffered is simply dropped. A hostile or runaway peer can
        therefore never grow this map without bound.

        Returns True when the block is retrievable afterwards (stored,
        or already covered by the contiguous log) and False when the cap
        dropped it — the replication layer keeps a dropped index in its
        outstanding-request set so a re-served copy is not mistaken for
        an unsolicited push."""
        with self._lock:
            if index < len(self._storage):
                return True  # contiguous log already holds it
            if index not in self._sparse:
                cap = int(os.environ.get("HM_SPARSE_CAP", "1024"))
                if len(self._sparse) >= cap:
                    if not self._sparse:
                        # cap <= 0: the buffer admits nothing — drop the
                        # block instead of max() on an empty dict
                        return False
                    worst = max(self._sparse)
                    if index >= worst:
                        return False  # incoming is the furthest: drop
                    del self._sparse[worst]
            self._sparse[index] = data
            listeners = list(self._sparse_listeners)
        for cb in listeners:
            cb(index, data)
        return True

    def _prune_sparse_locked(self) -> None:
        # caller holds the lock; entries the contiguous head passed are
        # redundant (storage is authoritative for them)
        if self._sparse:
            head = len(self._storage)
            for i in [i for i in self._sparse if i < head]:
                del self._sparse[i]

    def get_sparse(self, index: int) -> Optional[bytes]:
        """Block at `index` from the contiguous log or the sparse
        buffer; None when neither holds it."""
        with self._lock:
            if index < len(self._storage):
                return self._storage.get(index)
            data = self._sparse.get(index)
            if data is None:
                return None
            return data

    def has_block(self, index: int) -> bool:
        with self._lock:
            return index < len(self._storage) or index in self._sparse

    def on_sparse(self, cb: Callable[[int, bytes], None]) -> None:
        with self._lock:
            self._sparse_listeners.append(cb)

    def get(self, index: int) -> bytes:
        with self._lock:
            return self._storage.get(index)

    def get_batch(self, start: int, end: int) -> List[bytes]:
        with self._lock:
            end = min(end, len(self._storage))
            out = []
            for i in range(start, end):
                try:
                    out.append(self._storage.get(i))
                except IndexError:
                    # count index ran ahead of what the block log can
                    # actually parse (tampered/torn header): hand the
                    # caller the true short log — the integrity audit
                    # turns the shortfall into AUDIT_TAMPERED
                    break
            return out

    def read_all(self) -> List[bytes]:
        return self.get_batch(0, self.length)

    def on_append(self, cb: Callable[[int, bytes], None]) -> None:
        with self._lock:
            self._append_listeners.append(cb)

    def off_append(self, cb: Callable[[int, bytes], None]) -> None:
        with self._lock:
            if cb in self._append_listeners:
                self._append_listeners.remove(cb)

    def on_extended(self, cb: Callable[[int, int], None]) -> None:
        with self._lock:
            self._extend_listeners.append(cb)

    def off_extended(self, cb: Callable[[int, int], None]) -> None:
        with self._lock:
            if cb in self._extend_listeners:
                self._extend_listeners.remove(cb)

    def destroy(self) -> None:
        """Delete everything this feed persisted: block log, columnar
        sidecar, signature records."""
        with self._lock:
            if self.colcache is not None:
                self.colcache.destroy()
                self.colcache.close()
            if self.integrity is not None:
                self.integrity.destroy()
            if hasattr(self._storage, "destroy"):
                self._storage.destroy()
            self._storage.close()

    def close(self) -> None:
        if self.integrity is not None and self.integrity.unsigned_tail:
            self.seal()
        if self.colcache is not None:
            self.colcache.close()
        self._storage.close()


class FeedStore:
    """Feeds keyed by public key, with discovery-id lookup.

    Mirrors the reference FeedStore surface (create/append/read/head/
    stream, reference src/FeedStore.ts:26-142) minus streams — readers
    subscribe to appends instead."""

    def __init__(
        self,
        storage_fn: StorageFn,
        cache_fn: Optional[StorageFn] = None,
        sig_fn: Optional[StorageFn] = None,
    ) -> None:
        from .integrity import memory_sig_storage_fn

        self._storage_fn = storage_fn
        self._cache_fn = cache_fn
        self._sig_fn = sig_fn or memory_sig_storage_fn
        self._feeds: Dict[str, Feed] = {}
        self._by_discovery: Dict[str, str] = {}
        self._discovery_pending: List[Feed] = []  # ids computed lazily
        self._lock = make_rlock("store.feed_store")
        self.feed_q: Queue = Queue("feedstore")

    def create(self, pair: keymod.KeyPair) -> Feed:
        return self._open(pair.public_key, pair.secret_key)

    def open_feed(self, public_key: str) -> Feed:
        return self._open(public_key, None)

    def _open(self, public_key: str, secret_key: Optional[str]) -> Feed:
        with self._lock:
            feed = self._feeds.get(public_key)
            if feed is None:
                feed = Feed(
                    public_key, self._storage_fn(public_key), secret_key
                )
                if self._cache_fn is not None:
                    from .colcache import FeedColumnCache

                    feed.colcache = FeedColumnCache(
                        self._cache_fn(public_key), writer=public_key
                    )
                from .integrity import FeedIntegrity

                feed.integrity = FeedIntegrity(
                    self._sig_fn(public_key), public_key
                )
                self._feeds[public_key] = feed
                self._discovery_pending.append(feed)
                self.feed_q.push(feed)
            elif secret_key is not None and feed.secret_key is None:
                feed.secret_key = secret_key
            return feed

    def get_feed(self, public_key: str) -> Optional[Feed]:
        with self._lock:
            return self._feeds.get(public_key)

    def open_if_present(self, public_key: str) -> Optional[Feed]:
        """Open a feed only if its storage already holds blocks (e.g.
        persisted from a previous run). Unlike open_feed this never
        registers/announces an empty feed for an unknown key — lookups
        for bogus ids must not pollute the store."""
        with self._lock:
            feed = self._feeds.get(public_key)
            if feed is not None:
                return feed
            storage = self._storage_fn(public_key)
            has_blocks = len(storage) > 0
            storage.close()  # _open builds its own storage instance
            if not has_blocks:
                return None
        return self._open(public_key, None)

    def _drain_discovery_pending(self) -> None:
        # caller holds the lock
        for feed in self._discovery_pending:
            self._by_discovery[feed.discovery_id] = feed.public_key
        self._discovery_pending.clear()

    def by_discovery_id(self, discovery_id: str) -> Optional[Feed]:
        with self._lock:
            self._drain_discovery_pending()
            pk = self._by_discovery.get(discovery_id)
            return self._feeds.get(pk) if pk else None

    def known_discovery_ids(self) -> List[str]:
        with self._lock:
            self._drain_discovery_pending()
            return list(self._by_discovery.keys())

    def append(self, public_key: str, data: bytes) -> int:
        feed = self._feeds.get(public_key)
        if feed is None:
            raise KeyError(public_key)
        return feed.append(data)

    def read(self, public_key: str, index: int) -> bytes:
        feed = self._feeds.get(public_key)
        if feed is None:
            raise KeyError(public_key)
        return feed.get(index)

    def head(self, public_key: str) -> bytes:
        feed = self._feeds[public_key]
        return feed.get(feed.length - 1)

    def remove(self, public_key: str) -> None:
        """Forget a feed and delete its persisted state (doc destroy) —
        including state persisted by PREVIOUS sessions for feeds never
        opened in this one."""
        with self._lock:
            feed = self._feeds.pop(public_key, None)
            if feed is not None:
                self._discovery_pending = [
                    f for f in self._discovery_pending if f is not feed
                ]
                self._by_discovery = {
                    d: pk
                    for d, pk in self._by_discovery.items()
                    if pk != public_key
                }
        if feed is not None:
            feed.destroy()
            return
        # not open this session: destroy the on-disk state directly,
        # without registering/announcing a transient feed
        storage = self._storage_fn(public_key)
        if hasattr(storage, "destroy"):
            storage.destroy()
        storage.close()
        if self._cache_fn is not None:
            from .colcache import FeedColumnCache

            cc = FeedColumnCache(self._cache_fn(public_key), public_key)
            cc.destroy()
            cc.close()
        from .integrity import FeedIntegrity

        FeedIntegrity(self._sig_fn(public_key), public_key).destroy()

    def close(self) -> None:
        with self._lock:
            for feed in self._feeds.values():
                feed.close()
            self._feeds.clear()
