"""SQLite database: one file (or memory), migrated on open.

Parity: the reference's SqlDatabase + migration (reference
src/SqlDatabase.ts:11-22, src/migrations/0001_initial_schema.sql — tables
Clocks/Keys/Cursors/Feeds). Python's stdlib sqlite3 replaces the
better-sqlite3 native addon; a C++ store can swap in behind this module's
API without touching callers.

Crash model: sqlite's own journal makes each commit atomic and durable;
for the simulated crash matrix (storage/faults.py CrashRecorder) every
statement is journaled per-connection and lands in the event log as one
batch per commit — a crash between statements of a transaction drops
the whole transaction, exactly sqlite's semantics. Clock/cursor rows
committed ahead of unfsynced feed bytes are the one skew sqlite cannot
prevent; recovery-on-open (storage/scrub.py) reconciles them back to
feed reality, and HM_FSYNC>=1 prevents the skew outright (the store
flusher's durability barrier syncs feeds before committing).
"""

from __future__ import annotations

import contextlib
import sqlite3
import threading

from ..analysis import lockdep
from ..analysis.lockdep import make_rlock
from .faults import active_recorder

_SCHEMA = """
CREATE TABLE IF NOT EXISTS clocks (
  repo_id  TEXT NOT NULL,
  doc_id   TEXT NOT NULL,
  actor_id TEXT NOT NULL,
  seq      INTEGER NOT NULL,
  PRIMARY KEY (repo_id, doc_id, actor_id)
);
CREATE TABLE IF NOT EXISTS cursors (
  repo_id  TEXT NOT NULL,
  doc_id   TEXT NOT NULL,
  actor_id TEXT NOT NULL,
  seq      INTEGER NOT NULL,
  PRIMARY KEY (repo_id, doc_id, actor_id)
);
CREATE INDEX IF NOT EXISTS cursors_by_actor ON cursors (repo_id, actor_id);
CREATE TABLE IF NOT EXISTS keys (
  name       TEXT PRIMARY KEY,
  public_key TEXT NOT NULL,
  secret_key TEXT
);
CREATE TABLE IF NOT EXISTS feeds (
  public_id    TEXT PRIMARY KEY,
  discovery_id TEXT NOT NULL,
  is_writable  INTEGER NOT NULL DEFAULT 0
);
CREATE INDEX IF NOT EXISTS feeds_by_discovery ON feeds (discovery_id);
"""


class SqlDatabase:
    def __init__(self, path: str = ":memory:") -> None:
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = make_rlock("store.sql")
        self._defer_commit = 0
        with self._lock:
            self._conn.executescript(_SCHEMA)
            self._record("script", _SCHEMA, None)
            self._commit()

    def _record(self, kind: str, sql: str, params) -> None:
        if self.path == ":memory:":
            return
        rec = active_recorder()
        if rec is not None:
            rec.db_stmt(self.path, kind, sql, params)

    def _commit(self) -> None:
        # every commit routes through here: the lockdep blocking seam
        # for sqlite (a commit under an emission lock would stall
        # every doc's patch pushes on disk latency); the `with` form
        # also times the commit into the per-held-lock-class
        # blocking-debt counters (lock.held_blocking_ms.*)
        with lockdep.blocking("sqlite_commit", self.path):
            self._conn.commit()
        if self.path == ":memory:":
            return
        rec = active_recorder()
        if rec is not None:
            rec.db_commit(self.path)

    @contextlib.contextmanager
    def bulk(self):
        """Defer commits for a batch of writes (bulk cold start issues
        thousands of per-feed/per-doc upserts; one fsync, not N). Holds
        the db lock for the duration so writes from other threads can't
        slip into the deferred window and silently lose durability."""
        with self._lock:
            self._defer_commit += 1
            try:
                yield self
            finally:
                self._defer_commit -= 1
                if self._defer_commit == 0:
                    self._commit()

    def execute(self, sql: str, params=()) -> sqlite3.Cursor:
        with self._lock:
            cur = self._conn.execute(sql, params)
            self._record("exec", sql, tuple(params))
            if not self._defer_commit:
                self._commit()
            return cur

    def executemany(self, sql: str, rows) -> None:
        with self._lock:
            if active_recorder() is not None and self.path != ":memory:":
                rows = [tuple(r) for r in rows]  # generators: journal too
            self._conn.executemany(sql, rows)
            self._record("many", sql, rows)
            if not self._defer_commit:
                self._commit()

    def query(self, sql: str, params=()) -> list:
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
