"""Feed integrity: ed25519-signed merkle log per feed — the trust model.

Parity: hypercore's signed merkle tree (reference
src/types/hypercore.d.ts:132-188 — every feed is an append-only log whose
state is an ed25519 signature over a merkle root; replicas verify every
extension against the feed's public key before storing it). SURVEY §2.4
calls this the biggest native build item; the crypto primitives live in
the C++ layer (native/src/hm_native.cpp) behind utils/crypto.py.

Design (TPU-irrelevant, host-side, but built for the bulk scale):

- leaf hash = blake2b32(0x00 || block) (domain-separated, crypto.leaf_hash)
- tree = the promote-odd merkle over leaf hashes (crypto.merkle_root);
  maintained incrementally as binary-counter PEAKS so a writer's append
  is O(log n) hashing, not O(n) — equivalence with the bulk recompute is
  pinned by tests/test_integrity.py.
- signature = ed25519(seed, b"hm-feed-v1" || uint64le(length) || root),
  records (length, root, sig) persist in a `.sig` sidecar next to the
  block log (104-byte fixed records; a torn tail truncates to the last
  whole record). Only the newest record is needed to verify a full
  prefix. A live writer signs PERIODICALLY (every HM_SIGN_INTERVAL
  appends, default 1024 — the replication chunk size) plus ON DEMAND at
  any boundary via record_for (the incremental peaks give the head root
  for free; older boundaries recompute from the cached leaves), so an
  interactive burst of appends costs one signature per replication
  flush, not one per append. The dense-record corpus format
  (sign_chain) remains valid input: record_for prefers stored records.
- replication (net/replication.py) verifies every inbound extension:
  recompute root over (own leaves[0:start] + received blocks) and check
  the sender's signature against the feed public key BEFORE _append_raw.
  Tampered or unsigned extensions are dropped and logged
  (HM_ALLOW_UNSIGNED_FEEDS=1 restores pre-signature interop).
- `audit(feed)` re-hashes the whole log against the newest stored
  record — detects on-disk tampering of blocks or sig records.

Local writes by this process are inside the local trust boundary (as in
the reference — hypercore trusts its own storage, sqlite rows included);
verification guards the REPLICATION boundary, audit guards the disk.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import List, Optional, Tuple

from ..analysis.lockdep import make_rlock
from ..utils import crypto
from ..utils import keys as keymod
from ..utils.debug import log
from .faults import io_open, io_remove

_SIG_CONTEXT = b"hm-feed-v1"
_REC = struct.Struct("<Q32s64s")  # length, root, signature

# audit_status() results: OK / recoverable crash-orphan / tampered.
# Lazy signing (sign_interval) means a crash can legitimately leave a
# writable feed with blocks beyond its last signed record; that is NOT
# the same evidence as on-disk tampering, and tooling (tools/ls.py)
# surfaces it separately with the seal() recovery path.
AUDIT_OK = "ok"
AUDIT_UNSIGNED_TAIL = "unsigned_tail"
AUDIT_TAMPERED = "tampered"

_NODE_PREFIX = b"\x01"


def sign_interval() -> int:
    return int(os.environ.get("HM_SIGN_INTERVAL", "1024"))


def _parent(left: bytes, right: bytes) -> bytes:
    return crypto.blake2b32(_NODE_PREFIX + left + right)


def signable(length: int, root: bytes) -> bytes:
    return _SIG_CONTEXT + struct.pack("<Q", length) + root


class Peaks:
    """Incremental promote-odd merkle: binary-counter peaks.

    `append(leaf)` is O(log n) amortized; `root()` folds the peaks
    right-to-left with the same parent hash the bulk
    crypto.merkle_root(leaves) computes, so both paths agree bit-for-bit
    on every length."""

    def __init__(self) -> None:
        self.sizes: List[int] = []
        self.hashes: List[bytes] = []
        self.length = 0

    def append(self, leaf_hash: bytes) -> None:
        self.sizes.append(1)
        self.hashes.append(leaf_hash)
        while len(self.sizes) >= 2 and self.sizes[-1] == self.sizes[-2]:
            right = self.hashes.pop()
            left = self.hashes.pop()
            s = self.sizes.pop() + self.sizes.pop()
            self.hashes.append(_parent(left, right))
            self.sizes.append(s)
        self.length += 1

    def root(self) -> bytes:
        if not self.hashes:
            return b"\x00" * 32
        acc = self.hashes[-1]
        for h in reversed(self.hashes[:-1]):
            acc = _parent(h, acc)
        return acc


# ---------------------------------------------------------------------------
# signature-record storage


class MemorySigStorage:
    def __init__(self) -> None:
        self.records: List[Tuple[int, bytes, bytes]] = []

    def append(self, length: int, root: bytes, sig: bytes) -> None:
        self.records.append((length, root, sig))

    def load(self) -> List[Tuple[int, bytes, bytes]]:
        return list(self.records)

    def destroy(self) -> None:
        self.records.clear()

    def close(self) -> None:  # pragma: no cover - nothing to do
        pass


class FileSigStorage:
    """Fixed-size (length, root, sig) records; torn tail ignored."""

    def __init__(self, path: str) -> None:
        self.path = path

    def append(self, length: int, root: bytes, sig: bytes) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        with io_open(self.path, "ab") as fh:
            fh.write(_REC.pack(length, root, sig))

    def load(self) -> List[Tuple[int, bytes, bytes]]:
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as fh:
            raw = fh.read()
        n = len(raw) // _REC.size
        return [
            _REC.unpack_from(raw, i * _REC.size) for i in range(n)
        ]

    def repair(self) -> int:
        """Truncate a torn trailing fragment (load() already ignores
        it; repair drops the bytes so audits and byte accounting see a
        clean chain). Returns bytes dropped."""
        if not os.path.exists(self.path):
            return 0
        size = os.path.getsize(self.path)
        keep = (size // _REC.size) * _REC.size
        if size > keep:
            with io_open(self.path, "r+b") as fh:
                fh.truncate(keep)
        return size - keep

    def rewrite(self, records: List[Tuple[int, bytes, bytes]]) -> None:
        """Replace the whole chain (scrub dropping records that claim
        blocks the log lost after a power cut)."""
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        with io_open(self.path, "wb") as fh:
            for length, root, sig in records:
                fh.write(_REC.pack(length, root, sig))

    def destroy(self) -> None:
        if os.path.exists(self.path):
            io_remove(self.path)

    def close(self) -> None:  # pragma: no cover - nothing to do
        pass


def memory_sig_storage_fn(_name: str) -> MemorySigStorage:
    return MemorySigStorage()


def file_sig_storage_fn(root: str):
    def fn(name: str) -> FileSigStorage:
        return FileSigStorage(os.path.join(root, name[:2], name + ".sig"))

    return fn


# ---------------------------------------------------------------------------


class FeedIntegrity:
    """Signed-merkle state of one feed.

    Lazily loaded: bulk cold opens never touch it; replication and audit
    do. The leaf-hash cache rebuilds from the feed's blocks on demand
    (blocks are the source of truth, as with the columnar sidecar)."""

    def __init__(self, store, public_key: str) -> None:
        self._store = store
        self.public_key = public_key
        self._lock = make_rlock("store.integrity")
        self._records: Optional[List[Tuple[int, bytes, bytes]]] = None
        self._peaks: Optional[Peaks] = None
        self._leaves: List[bytes] = []
        # per-length interior merkle levels for the proof server
        # (build_proof_ctx): the tree at a given length is immutable in
        # an append-only log, so entries stay valid forever — the tiny
        # LRU just bounds memory. Serving a repeated RequestRange costs
        # O(range x log n) hash LOOKUPS, zero hash computations.
        self._proof_cache: Dict[int, tuple] = {}
        # appends this session not yet covered by a stored record
        # (periodic signing skipped them) — Feed.close/seal signs then
        self.unsigned_tail = False

    # -- records --------------------------------------------------------

    def _ensure_records(self) -> List[Tuple[int, bytes, bytes]]:
        if self._records is None:
            self._records = self._store.load()
        return self._records

    @property
    def signed_length(self) -> int:
        recs = self._ensure_records()
        return recs[-1][0] if recs else 0

    def latest(self) -> Optional[Tuple[int, bytes, bytes]]:
        recs = self._ensure_records()
        return recs[-1] if recs else None

    def records(self) -> List[Tuple[int, bytes, bytes]]:
        return list(self._ensure_records())

    def record_at(self, length: int) -> Optional[Tuple[int, bytes, bytes]]:
        """The stored (length, root, sig) covering exactly `length`."""
        for rec in reversed(self._ensure_records()):
            if rec[0] == length:
                return rec
            if rec[0] < length:
                break
        return None

    # -- leaf cache ------------------------------------------------------

    def _ensure_leaves(self, feed, upto: int) -> List[bytes]:
        """Leaf hashes for feed blocks [0, upto) — cached, extended from
        the block log as needed.

        Lock order: the documented order is feed lock BEFORE integrity
        lock (Feed.append -> sign_append). Callers that hold neither
        (range_proofs serving a RequestRange with a stale leaf cache)
        must not acquire them inverted, so the block snapshot
        (feed.get_batch, feed lock) happens OUTSIDE the integrity lock;
        the extension then re-checks under the lock — leaves are a pure
        function of the blocks, so a concurrent extension that won the
        race simply means fewer entries left for us to append."""
        while True:
            with self._lock:
                have = len(self._leaves)
                if have >= upto:
                    return self._leaves[:upto]
            blocks = feed.get_batch(have, upto)  # feed lock only
            hashes = [crypto.leaf_hash(b) for b in blocks]
            with self._lock:
                cur = len(self._leaves)
                if cur >= upto:
                    return self._leaves[:upto]
                if cur >= have:
                    # a concurrent extension may have won part of the
                    # race; leaves are a pure function of the blocks, so
                    # the overlap is identical and we append the rest
                    self._leaves.extend(hashes[cur - have :])
                    return self._leaves[:upto]
                # cur < have: the cache was RESET (destroy) between the
                # snapshot and the re-lock — our hashes are misaligned;
                # retry from the fresh state

    def _ensure_peaks(self, feed, upto: int) -> Peaks:
        with self._lock:
            if self._peaks is None:
                self._peaks = Peaks()
            if self._peaks.length < upto:
                for leaf in self._ensure_leaves(feed, upto)[
                    self._peaks.length :
                ]:
                    self._peaks.append(leaf)
            return self._peaks

    # -- writer path ------------------------------------------------------

    def sign_append(self, feed, index: int, data: bytes) -> None:
        """Writer appended block `index`: extend the tree, and store a
        fresh signed record every sign_interval appends (any other
        boundary signs on demand in record_for — per-append ed25519 +
        sidecar IO is the dominant cost of an interactive write)."""
        with self._lock:
            peaks = self._ensure_peaks(feed, index)
            leaf = crypto.leaf_hash(data)
            if len(self._leaves) == index:
                self._leaves.append(leaf)
            peaks.append(leaf)
            if (index + 1) % sign_interval() == 0:
                root = peaks.root()
                sig = crypto.sign(
                    signable(index + 1, root),
                    keymod.decode(feed.secret_key),
                )
                try:
                    self._store.append(index + 1, root, sig)
                except OSError as e:
                    # sig sidecar full/bad (ENOSPC/EIO): the BLOCK is
                    # already durable and locally authored — degrade to
                    # an unsigned tail (recoverable: seal()/record_for
                    # re-signs) instead of failing the acked append
                    log(
                        "repo:integrity",
                        f"sig append failed {self.public_key[:6]}: {e}",
                    )
                    self.unsigned_tail = True
                else:
                    self._ensure_records().append((index + 1, root, sig))
                    self.unsigned_tail = False
            else:
                self.unsigned_tail = True

    def record_for(self, feed, length: int):
        """The (length, root, sig) covering exactly `length`: a stored
        record when one exists, else — for a feed we hold the secret key
        of — a freshly signed one. At the head the incremental peaks
        yield the root directly (the live-tail flush path: one signature
        per flush window); older boundaries recompute from the cached
        leaf hashes. Newly signed head records persist; off-head ones
        are served without storing (the sidecar stays sorted).

        Lock order: feed lock BEFORE integrity lock — the same order
        the writer path uses (Feed.append -> sign_append), so a flusher
        signing on demand cannot deadlock against a concurrent append.
        """
        rec = self.record_at(length)
        if rec is not None:
            return rec
        if feed.secret_key is None or length <= 0:
            return None
        with feed._lock:
            if length > feed.length:
                return None
            seed = keymod.decode(feed.secret_key)
            with self._lock:
                peaks = self._ensure_peaks(feed, length)
                if peaks.length == length:
                    root = peaks.root()
                else:  # boundary behind the head: rebuild to length
                    probe = Peaks()
                    for leaf in self._ensure_leaves(feed, length):
                        probe.append(leaf)
                    root = probe.root()
                sig = crypto.sign(signable(length, root), seed)
                rec = (length, root, sig)
                recs = self._ensure_records()
                if not recs or recs[-1][0] < length:
                    try:
                        self._store.append(length, root, sig)
                    except OSError as e:
                        # serve the record anyway (it is valid); the
                        # chain stays un-extended so a later seal or
                        # sign retries persistence
                        log(
                            "repo:integrity",
                            f"sig store failed "
                            f"{self.public_key[:6]}: {e}",
                        )
                        if length == feed.length:
                            self.unsigned_tail = True
                    else:
                        recs.append(rec)
                        if length == feed.length:
                            self.unsigned_tail = False
                return rec

    # -- replication boundary ---------------------------------------------

    def verify_extension(
        self, feed, start: int, blocks: List[bytes], length: int,
        root_sig: bytes,
    ) -> Optional[Tuple[bytes, List[bytes]]]:
        """Check a claimed extension: blocks fill [start, length) on top
        of our local prefix [0, start). Returns (root, new leaf hashes)
        when the signature verifies against the feed public key; None
        otherwise. Nothing is appended here. The prefix root comes from
        the incremental peaks, so verifying a feed chunk-by-chunk is
        O(chunk log n), not O(n) hashing per chunk."""
        if length != start + len(blocks) or start > feed.length:
            return None
        with self._lock:
            peaks = self._ensure_peaks(feed, start)
            probe = Peaks()
            probe.sizes = list(peaks.sizes)
            probe.hashes = list(peaks.hashes)
            probe.length = peaks.length
            new_leaves = [crypto.leaf_hash(b) for b in blocks]
            for leaf in new_leaves:
                probe.append(leaf)
            root = probe.root()
            ok = crypto.verify(
                signable(length, root),
                root_sig,
                keymod.decode(self.public_key),
            )
            return (root, new_leaves) if ok else None

    def record_verified(
        self, length: int, root: bytes, sig: bytes,
        new_leaves: List[bytes],
    ) -> None:
        """Store the record for an extension that verify_extension
        accepted and whose blocks the caller appended."""
        with self._lock:
            self._leaves.extend(new_leaves)
            if self._peaks is not None:
                for leaf in new_leaves:
                    self._peaks.append(leaf)
            self._ensure_records().append((length, root, sig))
            try:
                self._store.append(length, root, sig)
            except OSError as e:
                # the blocks are stored and the in-memory chain serves
                # this session; after a crash the uncovered tail is
                # scrub-truncated and re-replicates from peers
                log(
                    "repo:integrity",
                    f"sig store failed {self.public_key[:6]}: {e}",
                )

    def range_proofs(self, feed, start: int, end: int):
        """Serve a sparse range: (proof_length, sig, [(block, proof)])
        for blocks [start, end) against a signed record — a stored one
        covering the range, else (writable feeds) one signed on demand
        at the head. None when no record can cover `end`."""
        rec = None
        for r in self._ensure_records():
            if r[0] >= end:
                rec = r
                break
        if rec is None:
            rec = self.record_for(feed, feed.length)
            if rec is None or rec[0] < end:
                return None
        length, _root, sig = rec
        ctx = self._proof_ctx(feed, length)
        blocks = feed.get_batch(start, end)
        proofs = proofs_from_ctx(ctx, start, end)
        return (length, sig, list(zip(blocks, proofs)))

    def _proof_ctx(self, feed, length: int):
        """The forest levels at `length`, cached. First build is the
        O(length) hashing pass; every later range served against the
        same signed record is pure lookup (the pre-cache server re-built
        the whole level set per request: O(range x length))."""
        with self._lock:
            ctx = self._proof_cache.get(length)
            if ctx is not None:
                return ctx
        # leaves snapshot outside the integrity lock: store.integrity
        # is a LEAF class in the lock hierarchy (analysis/hierarchy.py
        # — same rule as _ensure_leaves: never integrity -> feed)
        leaves = self._ensure_leaves(feed, length)
        ctx = build_proof_ctx(leaves, length)
        with self._lock:
            self._proof_cache[length] = ctx
            while len(self._proof_cache) > 4:
                self._proof_cache.pop(next(iter(self._proof_cache)))
        return ctx

    # -- disk audit ---------------------------------------------------------

    def destroy(self) -> None:
        """Drop all records + cached state (doc destroy)."""
        with self._lock:
            self._store.destroy()
            self._records = []
            self._peaks = None
            self._leaves = []
            self._proof_cache = {}

    def audit(self, feed) -> bool:
        """Strict boolean audit: True only for AUDIT_OK (see
        audit_status — an unsigned tail is NOT ok, but callers that
        need to distinguish recoverable-unsigned from tampered must use
        audit_status; this keeps the historical contract that anything
        short of a fully verified chain fails)."""
        return self.audit_status(feed) == AUDIT_OK

    def audit_status(self, feed) -> str:
        """Re-hash the entire block log against EVERY stored record —
        the newest covers the signed prefix; intermediate ones are
        load-bearing for chunked replication serving, so a corrupted
        record anywhere in the chain fails the audit (pinned by the
        tamper fuzz). Reads the feed and recomputes independently of
        the cached state — and takes no integrity lock while reading
        the feed, so a concurrent writer (feed lock -> integrity lock)
        cannot deadlock against it.

        Returns one of:
        - AUDIT_OK: every block is covered by a verified record chain.
        - AUDIT_UNSIGNED_TAIL: the signed prefix verifies, but a
          WRITABLE feed holds blocks beyond its last record — the
          shape lazy signing leaves after a crash between an append
          and the periodic record (sign_interval). Distinct from
          tampering: the tail is locally authored and recoverable —
          `Feed.seal()` signs a fresh head record and the next audit
          is clean. (Feed.close() seals tails appended in-process; a
          crash skips that, hence this status on reopen.)
        - AUDIT_TAMPERED: blocks or records fail verification, records
          claim blocks the log no longer holds, or a READ-ONLY feed
          carries uncovered blocks (a foreign tail must never audit as
          recoverable — we cannot distinguish it from an attacker's
          append, and must not sign it into validity)."""
        recs = self.records()
        n_blocks = feed.length
        if not recs:
            if n_blocks == 0:
                return AUDIT_OK
            # blocks but no chain at all: an interrupted writable feed
            # that never reached its first sign_interval, or a foreign/
            # unverifiable log
            return (
                AUDIT_UNSIGNED_TAIL if feed.writable else AUDIT_TAMPERED
            )
        last_len = recs[-1][0]
        if last_len > n_blocks:
            return AUDIT_TAMPERED  # records claim blocks the log lost
        wanted = {length for length, _r, _s in recs}
        blocks = feed.get_batch(0, last_len)
        peaks = Peaks()
        roots = {}
        for b in blocks:
            peaks.append(crypto.leaf_hash(b))
            if peaks.length in wanted:
                roots[peaks.length] = peaks.root()
        pub = keymod.decode(self.public_key)
        for length, root, sig in recs:
            if roots.get(length) != root:
                return AUDIT_TAMPERED
            if not crypto.verify(signable(length, root), sig, pub):
                return AUDIT_TAMPERED
        if last_len < n_blocks:
            # signed prefix intact, tail uncovered: crash-orphaned
            # unsigned tail on a writable feed (recoverable via seal);
            # on a read-only feed, indistinguishable from a foreign
            # append — fail hard
            if feed.writable:
                log(
                    "repo:integrity",
                    f"feed {self.public_key[:6]}: unsigned tail beyond "
                    f"last record ({n_blocks - last_len} block(s) past "
                    f"{last_len}) — seal() re-signs the head",
                )
                return AUDIT_UNSIGNED_TAIL
            return AUDIT_TAMPERED
        return AUDIT_OK


def _peak_sizes(length: int) -> List[int]:
    """Subtree sizes of the promote-odd forest at `length`: the set
    bits of length, largest first (binary-counter peaks). Peak j covers
    leaves [sum(sizes[:j]), sum(sizes[:j+1]))."""
    sizes = []
    bit = 1 << (length.bit_length() - 1) if length else 0
    while bit:
        if length & bit:
            sizes.append(bit)
        bit >>= 1
    return sizes


def _peak_levels(leaves: List[bytes]) -> List[List[bytes]]:
    """All levels of one perfect subtree, bottom-up (levels[-1][0] is
    its root)."""
    levels = [list(leaves)]
    while len(levels[-1]) > 1:
        lvl = levels[-1]
        levels.append(
            [_parent(lvl[i], lvl[i + 1]) for i in range(0, len(lvl), 2)]
        )
    return levels


def build_proof_ctx(leaves: List[bytes], length: int):
    """(sizes, offs, levels, roots): every interior level of the
    promote-odd forest at `length` — the one O(length) hashing pass the
    proof server needs; serving any range afterwards is pure lookup.
    Cached per length on FeedIntegrity (append-only logs never mutate
    the tree at a given length)."""
    sizes = _peak_sizes(length)
    offs: List[int] = []
    levels: List[List[List[bytes]]] = []
    roots: List[bytes] = []
    o = 0
    for s in sizes:
        lv = _peak_levels(leaves[o : o + s])
        offs.append(o)
        levels.append(lv)
        roots.append(lv[-1][0])
        o += s
    return sizes, offs, levels, roots


def proofs_from_ctx(ctx, start: int, end: int) -> List[List[bytes]]:
    """Proofs for leaves [start, end) from a built forest context:
    O((end - start) x log(length)) hash lookups, zero hashing."""
    sizes, offs, levels, roots = ctx
    out: List[List[bytes]] = []
    for index in range(start, end):
        j = 0
        while index >= offs[j] + sizes[j]:
            j += 1
        proof: List[bytes] = []
        p = index - offs[j]
        for lvl in levels[j][:-1]:
            proof.append(lvl[p ^ 1])
            p >>= 1
        proof.extend(roots[q] for q in range(len(sizes)) if q != j)
        out.append(proof)
    return out


def range_inclusion_proofs(
    leaves: List[bytes], start: int, end: int, length: int
) -> List[List[bytes]]:
    """Merkle inclusion proofs for leaves [start, end) against the
    promote-odd root at `length` (hypercore's sparse-download
    verification model: a peer verifies blocks against a signed root
    without holding the prefix). Each proof = the sibling path inside
    the leaf's peak subtree (bottom-up), then every OTHER peak root in
    forest order — positions derive client-side from (index, length),
    so a proof is just hashes, ≤ 2·log2(length) of them."""
    return proofs_from_ctx(build_proof_ctx(leaves, length), start, end)


def inclusion_proof(
    leaves: List[bytes], index: int, length: int
) -> List[bytes]:
    """Single-leaf convenience over range_inclusion_proofs."""
    return range_inclusion_proofs(leaves, index, index + 1, length)[0]


def verify_inclusion(
    public_key: str,
    leaf: bytes,
    index: int,
    length: int,
    proof: List[bytes],
    root_sig: bytes,
) -> bool:
    """Check a single leaf hash against a SIGNED promote-odd root at
    `length` using an inclusion_proof. The signature binds (length,
    root) to the feed key, so a verified sparse block is as trusted as
    a contiguously replicated one."""
    sizes = _peak_sizes(length)
    off = 0
    for peak_idx, size in enumerate(sizes):
        if index < off + size:
            break
        off += size
    else:
        return False
    k = size.bit_length() - 1  # path length inside the peak
    if len(proof) != k + len(sizes) - 1:
        return False
    acc = leaf
    p = index - off
    for lvl in range(k):
        sib = proof[lvl]
        acc = _parent(acc, sib) if p % 2 == 0 else _parent(sib, acc)
        p >>= 1
    peaks = []
    others = iter(proof[k:])
    for j in range(len(sizes)):
        peaks.append(acc if j == peak_idx else next(others))
    root = peaks[-1]
    for h in reversed(peaks[:-1]):
        root = _parent(h, root)
    return crypto.verify(
        signable(length, root), root_sig, keymod.decode(public_key)
    )


def sign_chain(blocks: List[bytes], seed: bytes) -> bytes:
    """The packed .sig-file content a writer produces appending `blocks`
    in order — one (length, root, sig) record per append. Single source
    of truth for the record chain; the corpus writer and tests use this
    so their on-disk state is byte-compatible with sign_append's."""
    peaks = Peaks()
    out: List[bytes] = []
    for b in blocks:
        peaks.append(crypto.leaf_hash(b))
        root = peaks.root()
        out.append(
            _REC.pack(
                peaks.length,
                root,
                crypto.sign(signable(peaks.length, root), seed),
            )
        )
    return b"".join(out)


def allow_unsigned() -> bool:
    return os.environ.get("HM_ALLOW_UNSIGNED_FEEDS") == "1"


def capability(
    public_key: str,
    challenge: bytes,
    binding: bytes = b"",
    prover_is_client: Optional[bool] = None,
) -> str:
    """Proof of feed-key knowledge for the replication protocol
    (hypercore-protocol's capability verification, reference
    src/types/hypercore-protocol.d.ts:62-106): a keyed hash only a
    holder of the feed PUBLIC key can compute — discovery ids alone
    (which peers learn from announcements) must not unlock block data.

    The MAC input binds three things (hypercore-protocol binds its
    capabilities to the noise session the same way):
    - the VERIFIER's per-connection random `challenge`;
    - the transport session's channel `binding` (net/secure.py
      exporter over the ephemeral handshake transcript), so a proof
      obtained on one connection cannot be replayed on another even by
      a peer that controls the challenge it hands out;
    - the PROVER's transport role (client/server), so a proof we send
      on a connection cannot be mirrored straight back to us on that
      same connection by a peer that chose its challenge equal to ours.
    """
    import hashlib

    role = b""
    if prover_is_client is not None:
        role = b"C" if prover_is_client else b"S"
    return keymod.encode(
        hashlib.blake2b(
            b"hm-cap:" + challenge + b"|" + binding + b"|" + role,
            key=keymod.decode(public_key),
            digest_size=32,
        ).digest()
    )
