"""Change-block codec: JSON + compression with a tagged header.

Parity target: the reference packs each change as brotli-compressed JSON
with a 2-byte magic header and falls back to raw JSON when compression
doesn't help, sniffing `{` for legacy blocks (reference src/Block.ts:6-29).

Dispatch is by header:
  '\\xc5\\x01' binary change frame            (crdt/codec.py, preferred
                                            for change blocks)
  'BR' + uint32le raw_len + brotli stream   (native layer, preferred)
  'ZL' + zlib stream                        (pure-Python fallback)
  '{' / '['                                 raw JSON (incompressible)

Writers pick brotli when the native layer loaded (HM_BLOCK_CODEC=zlib
forces the fallback); readers handle every format, so feeds written by
either configuration stay readable — except brotli-written feeds on a
machine that cannot load the native layer, which fail loudly rather
than silently misparse. Change blocks go through `pack_change`, which
prefers the binary change frame (GIL-free native encode/decode; the
HM_NATIVE_CODEC=0 hatch reverts new writes to the JSON formats while
readers keep handling frames already on disk).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Any

from .. import native
from ..crdt import codec as change_codec
from ..utils.json_buffer import bufferify, parse

_ZLIB_MAGIC = b"ZL"
_BROTLI_MAGIC = b"BR"
_BR_LEN = struct.Struct("<I")
_BR_QUALITY = 5  # block packing wants speed; q5 beats zlib-6 on JSON


def _use_brotli() -> bool:
    if os.environ.get("HM_BLOCK_CODEC") == "zlib":
        return False
    return bool(native.caps() & native.CAP_BROTLI)


def pack(obj: Any) -> bytes:
    return pack_raw(bufferify(obj))


def pack_change(obj: Any) -> bytes:
    """Pack a change dict, preferring the binary change frame for the
    small interactive blocks the per-edit hot loop emits — the encode
    runs in C with the GIL released and the frame undercuts raw JSON.
    Big blocks (bulk text pastes) keep the brotli path: there the
    payload dominates and compression beats a flat frame on disk.
    Off-canon shapes and the HM_NATIVE_CODEC=0 hatch fall back to the
    JSON block path."""
    if change_codec.enabled():
        frame = change_codec.encode_change(obj)
        if frame is not None and len(frame) < _MIN_COMPRESS:
            return frame
    return pack_raw(bufferify(obj))


_MIN_COMPRESS = 512  # tiny interactive blocks: framing+cpu beats the
# handful of saved bytes, store raw JSON


def pack_raw(raw: bytes) -> bytes:
    """Pack already-serialized JSON bytes (callers that template/replay
    serialized changes skip the re-serialization)."""
    if len(raw) < _MIN_COMPRESS:
        return raw
    if _use_brotli():
        compressed = native.compress(
            native.CODEC_BROTLI, raw, quality=_BR_QUALITY
        )
        if compressed is not None:
            framed = _BROTLI_MAGIC + _BR_LEN.pack(len(raw)) + compressed
            if len(framed) < len(raw):
                return framed
            return raw  # incompressible: store raw JSON
    compressed = zlib.compress(raw, level=6)
    if len(compressed) + 2 < len(raw):
        return _ZLIB_MAGIC + compressed
    return raw  # incompressible: store raw JSON (starts with '{' or '[')


# Blocks arrive from untrusted peers: the framed raw_len must be bounded
# before it sizes an allocation. Brotli tops out around ~1000:1 on
# pathological input; honest JSON change blocks sit far below 2048x.
_MAX_RATIO = 2048


def unpack(data: bytes) -> Any:
    magic = data[:2]
    if magic == change_codec.MAGIC:
        # binary change frame: decode (native when available) back to
        # canonical JSON bytes, then parse like any raw block. Readers
        # take this branch regardless of HM_NATIVE_CODEC — the hatch
        # only stops new frames being written.
        return parse(change_codec.decode_change(data))
    if magic == _BROTLI_MAGIC:
        if len(data) < 2 + _BR_LEN.size:
            raise ValueError("corrupt brotli block: truncated header")
        (raw_len,) = _BR_LEN.unpack_from(data, 2)
        stream = data[2 + _BR_LEN.size :]
        if raw_len > max(4096, len(stream) * _MAX_RATIO):
            raise ValueError(
                "corrupt brotli block: implausible raw length "
                f"{raw_len} for {len(stream)} compressed bytes"
            )
        if not native.caps() & native.CAP_BROTLI:
            raise ValueError(
                "brotli block but native codec unavailable "
                "(build hypermerge_tpu/native or set HM_BLOCK_CODEC=zlib "
                "before writing)"
            )
        raw = native.decompress(native.CODEC_BROTLI, stream, raw_len)
        if raw is None:
            raise ValueError("corrupt brotli block: stream failed to decode")
        return parse(raw)
    if magic == _ZLIB_MAGIC:
        try:
            return parse(zlib.decompress(data[2:]))
        except zlib.error as exc:
            raise ValueError(f"corrupt zlib block: {exc}") from exc
    return parse(data)
