"""Change-block codec: JSON + compression with a tagged header.

Parity target: the reference packs each change as brotli-compressed JSON
with a 2-byte magic header and falls back to raw JSON when compression
doesn't help, sniffing `{` for legacy blocks (reference src/Block.ts:6-29).

This codec uses zlib ('ZL' header) — available without native deps — and
the native/ C++ extension can register a brotli-class codec under a new
header byte-pair without breaking stored feeds (the header dispatches).
"""

from __future__ import annotations

import zlib
from typing import Any

from ..utils.json_buffer import bufferify, parse

_ZLIB_MAGIC = b"ZL"


def pack(obj: Any) -> bytes:
    raw = bufferify(obj)
    compressed = zlib.compress(raw, level=6)
    if len(compressed) + 2 < len(raw):
        return _ZLIB_MAGIC + compressed
    return raw  # incompressible: store raw JSON (starts with '{' or '[')


def unpack(data: bytes) -> Any:
    if data[:2] == _ZLIB_MAGIC:
        return parse(zlib.decompress(data[2:]))
    return parse(data)
