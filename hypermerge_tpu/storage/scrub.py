"""Whole-repo crash recovery: audit → truncate → repair-forward →
reconcile sqlite against feed reality.

Each on-disk format heals its own torn tail lazily (feed.py length-
prefix scan, slab.py repair-forward, colcache.py commit records,
integrity.py fixed records) — but a doc's persistent state SPANS those
files plus the sqlite clock/cursor rows, and a crash can land between
any pair of writes. This module is the cross-file reconciler:

  recover_repo(back)   runs on RepoBackend open when the previous
                       session did not close cleanly (the repo.dirty
                       marker): physically truncates torn tails,
                       drops signature records that claim blocks the
                       log lost, re-signs (seals) writable feeds'
                       crash-orphaned unsigned tails, truncates
                       READ-ONLY feeds' unverifiable tails back to the
                       last signed record (those blocks re-replicate
                       from peers), resets columnar sidecars that ran
                       ahead of their block log, and clamps sqlite
                       clock rows down to what the feeds actually hold
                       (clocks-ahead-of-feeds is the direction nothing
                       else recovers: a stale row advertises state the
                       repo cannot supply). Writes its report to
                       <repo>/scrub.json so operators (tools/ls.py)
                       can see crash damage after the fact.

  doc_status(...)      cheap per-doc verdict for tools/ls.py: ok /
                       recovered / truncated-N-blocks / unsigned_tail.

tools/scrub.py is the CLI driver (adds the full merkle audit).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Set

from ..utils.debug import log
from .. import telemetry
from .integrity import allow_unsigned

REPORT_NAME = "scrub.json"
_COUNTERS = (
    "feeds",
    "blocks_truncated",
    "bytes_truncated",
    "sig_fragment_bytes",
    "sig_records_dropped",
    "tail_blocks_dropped",
    "unsigned_tails_sealed",
    "colcache_reset",
    "clock_rows_clamped",
    "slab_segments_recovered",
    "slab_idx_rebuilt",
)


def feed_names_on_disk(feeds_root: str) -> Set[str]:
    """Every block-log name under feeds/: files with no extension in
    the two-char fan-out dirs (sidecars carry .len/.sig/.cols2)."""
    out: Set[str] = set()
    if not os.path.isdir(feeds_root):
        return out
    for sub in os.listdir(feeds_root):
        d = os.path.join(feeds_root, sub)
        if len(sub) != 2 or not os.path.isdir(d):
            continue
        for name in os.listdir(d):
            if "." not in name and os.path.isfile(os.path.join(d, name)):
                out.add(name)
    return out


def _repair_sig_chain(sig_store, n_blocks: int, write: bool = True):
    """(records_kept, fragment_bytes, records_dropped): truncate a torn
    trailing fragment, then drop records claiming more blocks than the
    log holds (a power cut can persist the sig append but drop the
    block bytes; without this the next audit brands a plain crash as
    TAMPERED). write=False measures without touching disk (dry run)."""
    if hasattr(sig_store, "repair"):
        fragment = (
            sig_store.repair()
            if write
            else _sig_fragment_bytes(sig_store)
        )
    else:
        fragment = 0
    records = sig_store.load()
    kept = [r for r in records if r[0] <= n_blocks]
    dropped = len(records) - len(kept)
    if write and dropped and hasattr(sig_store, "rewrite"):
        sig_store.rewrite(kept)
    return kept, fragment, dropped


def _sig_fragment_bytes(sig_store) -> int:
    """Torn trailing fragment size without repairing (dry run)."""
    from .integrity import _REC

    path = getattr(sig_store, "path", None)
    if path is None or not os.path.exists(path):
        return 0
    return os.path.getsize(path) % _REC.size


def _colcache_changes(cache_storage) -> Optional[int]:
    """Committed change count in a columnar sidecar, or None when the
    sidecar has no commits to speak of."""
    try:
        lv3 = getattr(cache_storage, "load_v3", None)
        if lv3 is not None:
            commits = lv3()[4]
        else:
            commits = cache_storage.load()[3]
    except Exception as e:  # unreadable sidecar: rebuild it
        log("storage:scrub", f"sidecar unreadable ({e}): resetting")
        return -1
    return len(commits)


def recover_repo(back, repair: bool = True) -> Dict:
    """Crash recovery over an already-constructed (file-backed)
    RepoBackend, BEFORE any doc is opened. Returns (and persists) the
    report. With repair=False nothing is written — the report describes
    what a repair would do (tools/scrub.py --dry-run)."""
    # span lands even when recovery RAISES (the trace you want most is
    # the failed one); the counter only counts completed recoveries
    sp = telemetry.begin("storage.recover", "storage")
    ok = False
    try:
        report = _recover_repo(back, repair)
        ok = True
    finally:
        sp.end(ok=ok)
    telemetry.counter("storage.recoveries").add(1)
    return report


def _recover_repo(back, repair: bool) -> Dict:
    t0 = time.perf_counter()
    report: Dict = {k: 0 for k in _COUNTERS}
    per_feed: Dict[str, Dict] = {}
    report["per_feed"] = per_feed

    # -- journal replay FIRST (storage/wal.py): acked blocks a power
    # cut dropped from the (unfsynced-at-ack) per-feed logs come back
    # from the fsynced journal, so the torn-tail/sig/clock passes
    # below see the replayed reality. The journal's session stamp +
    # dirty-name ledger also BOUND the scan: a matching durable-tier
    # journal proves which feeds the crashed session could have
    # touched, and every other sidecar is skipped unopened (the
    # 100k-feed recovery constant).
    from . import wal as walmod

    wal_report = walmod.recover(back, repair)
    report["wal"] = wal_report
    bounded = bool(wal_report.get("bounded"))

    # -- slab: loading IS the repair-forward (torn segments ignored,
    # index rebuilt/extended from segment headers) ---------------------
    slab = getattr(back, "_col_slab", None)
    if slab is not None:
        slab.feed_names()  # forces _ensure_loaded
        rep = getattr(slab, "last_repair", {})
        report["slab_segments_recovered"] = rep.get(
            "segments_recovered", 0
        )
        report["slab_idx_rebuilt"] = rep.get("idx_rebuilt", 0)

    feeds_root = os.path.join(back.path, "feeds")
    names = set(back.feed_info.all_public_ids())
    names |= feed_names_on_disk(feeds_root)
    if bounded:
        dirty = set(wal_report.get("dirty", ()))
        report["feeds_skipped"] = len(names - dirty)
        names &= dirty
    blocks_by_feed: Dict[str, int] = {}
    for name in sorted(names):
        entry: Dict = {}
        storage = back.feeds._storage_fn(name)
        try:
            if hasattr(storage, "repair"):
                r = storage.repair(write=repair)
                n_blocks = r["blocks"]
                if r["bytes_truncated"]:
                    entry["bytes_truncated"] = r["bytes_truncated"]
                    report["bytes_truncated"] += r["bytes_truncated"]
            else:
                n_blocks = len(storage)

            # -- signature chain vs block log ----------------------------
            sig_store = back.feeds._sig_fn(name)
            kept, fragment, dropped = _repair_sig_chain(
                sig_store, n_blocks, write=repair
            )
            if fragment:
                entry["sig_fragment_bytes"] = fragment
                report["sig_fragment_bytes"] += fragment
            if dropped:
                entry["sig_records_dropped"] = dropped
                report["sig_records_dropped"] += dropped
            signed = kept[-1][0] if kept else 0
            writable = name in getattr(back, "_actor_keys", {})
            if n_blocks > signed:
                if writable:
                    # locally authored crash-orphaned tail: re-sign it
                    # (Feed.seal via the real feed machinery)
                    if repair:
                        feed = back.feeds.create(back._actor_keys[name])
                        feed.seal()
                    entry["sealed"] = n_blocks - signed
                    report["unsigned_tails_sealed"] += 1
                elif kept and not allow_unsigned():
                    # read-only feed: an uncovered tail is
                    # indistinguishable from a foreign append — drop
                    # back to the verified prefix; the blocks
                    # re-replicate from whichever peer served them
                    n = n_blocks - signed
                    if repair and hasattr(storage, "truncate_to"):
                        n = storage.truncate_to(signed)
                    entry["tail_blocks_dropped"] = n
                    report["tail_blocks_dropped"] += n
                    n_blocks = signed
                else:
                    entry["unsigned_tail"] = n_blocks - signed

            # -- columnar sidecar ahead of the block log -----------------
            cache_storage = (
                back.feeds._cache_fn(name)
                if back.feeds._cache_fn is not None
                else None
            )
            if cache_storage is not None:
                n_changes = _colcache_changes(cache_storage)
                if n_changes is not None and (
                    n_changes < 0 or n_changes > n_blocks
                ):
                    if repair:
                        cache_storage.reset()
                    entry["colcache_reset"] = 1
                    report["colcache_reset"] += 1
                cache_storage.close()
        finally:
            storage.close()
        blocks_by_feed[name] = n_blocks
        report["feeds"] += 1
        if entry:
            per_feed[name] = entry

    # -- sqlite clock rows vs feed reality -----------------------------
    # Our own repo's clock rows advertise what we can SUPPLY; a row
    # ahead of the (possibly truncated) feed would gossip state no
    # peer can ever pull from us. Clamp down to the block counts.
    # (Cursor rows are intent — "include this actor up to here" — and
    # monotonic-safe: replication re-fills them, so they stay.)
    for doc_id in back.clocks.all_doc_ids(back.id):
        clock = back.clocks.get(back.id, doc_id)
        # bounded runs: an actor OUTSIDE the scan set is session-clean
        # by the journal's ledger — its clock row stands. Full scans
        # keep the strict rule: no feed on disk means clamp to zero.
        clamped = {
            a: min(s, blocks_by_feed.get(a, s if bounded else 0))
            for a, s in clock.items()
        }
        if clamped != clock:
            n = sum(
                1 for a in clock if clamped.get(a, 0) != clock[a]
            )
            report["clock_rows_clamped"] += n
            if repair:
                back.clocks.set(
                    back.id,
                    doc_id,
                    {a: s for a, s in clamped.items() if s > 0},
                )

    report["t_recover_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
    if repair:
        from .faults import io_open

        try:
            with io_open(os.path.join(back.path, REPORT_NAME), "wb") as fh:
                fh.write(json.dumps(report).encode("utf-8"))
        except OSError as e:
            log("storage:scrub", f"report write failed: {e}")
    repairs = sum(report[k] for k in _COUNTERS if k != "feeds")
    if repairs:
        log(
            "storage:scrub",
            f"crash recovery repaired {repairs} item(s) across "
            f"{report['feeds']} feed(s) in {report['t_recover_ms']}ms",
        )
    return report


def last_report(path: str) -> Optional[Dict]:
    """The report recover_repo persisted on the last crash recovery of
    the repo at `path`, or None."""
    p = os.path.join(path, REPORT_NAME)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def wal_status(report: Optional[Dict], actors) -> str:
    """Per-doc journal verdict for tools/ls.py, from the persisted
    scrub report's `wal` section:

      replayed      the last recovery re-appended journaled blocks
                    into one of this doc's feeds (a power cut had
                    dropped unfsynced log pages)
      checkpointed  the crashed session touched a feed of this doc,
                    but its blocks were already durable in the logs
                    (nothing to replay)
      clean         untouched by the crashed session (or no journal
                    ran)
    """
    wal = (report or {}).get("wal") or {}
    actors = set(actors)
    if actors & set(wal.get("replayed_feeds", ())):
        return "replayed"
    if actors & set(wal.get("dirty", ())):
        return "checkpointed"
    return "clean"


def doc_status(back, doc_id: str, report: Optional[Dict] = None) -> str:
    """Cheap per-doc crash/scrub verdict for tools/ls.py — no block
    re-hashing (that is --audit):

      truncated-N-blocks  the last recovery dropped N of this doc's
                          blocks (read-only unverifiable tails)
      recovered           the last recovery repaired something for one
                          of this doc's feeds (torn tails, sidecar
                          resets, seals — no block loss)
      unsigned_tail       a feed currently holds blocks beyond its
                          last signature record
      ok                  none of the above
    """
    actors = list(back.cursors.get(back.id, doc_id))
    dropped = 0
    repaired = False
    per_feed = (report or {}).get("per_feed", {})
    for a in actors:
        entry = per_feed.get(a)
        if entry:
            dropped += entry.get("tail_blocks_dropped", 0)
            repaired = True
    unsigned = False
    for a in actors:
        feed = back.feeds.get_feed(a)
        if feed is None:
            storage = back.feeds._storage_fn(a)
            try:
                n_blocks = len(storage)
            finally:
                storage.close()
            sig_store = back.feeds._sig_fn(a)
            try:
                recs = sig_store.load()
            finally:
                sig_store.close()
            signed = recs[-1][0] if recs else 0
        else:
            n_blocks = feed.length
            signed = (
                feed.integrity.signed_length
                if feed.integrity is not None
                else 0
            )
        if n_blocks > signed:
            unsigned = True
    if dropped:
        return f"truncated-{dropped}-blocks"
    if repaired:
        return "recovered"
    if unsigned:
        return "unsigned_tail"
    return "ok"
