"""Deterministic disk-fault injection + crash-schedule recording.

The storage twin of net/faults.py: PR 5 proved that a seeded fault
harness at the transport seam flushes out real bugs the ordinary test
suite never reaches. This module sits at the storage-backend seam — the
file-handle layer that FileFeedStorage, CorpusSlab, FileColumnStorageV2,
FileSigStorage, and SqlDatabase all write through — and provides:

  DiskFaultPlan   seeded per-path RNG fault schedules: short/torn
                  writes, ENOSPC/EIO on write and fsync, and fsync
                  LIES (the syscall succeeds, the bytes are dropped at
                  the next simulated power cut). Per-path streams are
                  keyed by (seed, path), so which op of a given file
                  faults is reproducible regardless of how threads
                  interleave ops across files.

  CrashRecorder   records the write/fsync/rename/commit schedule of a
                  workload as an ordered event log; `materialize()`
                  replays any prefix of it into a fresh directory — a
                  simulated crash at that boundary. Two crash models:
                    - kill -9 (default): every syscall issued before
                      the cut survives (the page cache outlives the
                      process);
                    - power cut (`powercut=True`): per file, only
                      bytes covered by an honest fsync survive; writes
                      after the last fsync — and everything a LYING
                      fsync claimed — are gone. SQLite commits are
                      modeled durable at commit (sqlite fsyncs its
                      journal itself).

  io_open/io_fsync/io_replace/io_remove
                  the seam: drop-in wrappers the storage backends use
                  for every WRITE-side file op. With no harness active
                  they are the builtins (one global read per call);
                  with one active they consult the plan and/or feed
                  the recorder. Read-side opens never route here.

The kill-anywhere matrix (tests/test_crash.py) runs a mixed workload
under a CrashRecorder, replays every prefix, reopens the repo, and
asserts the recovery invariants: reopen never raises; recovered state
is a prefix of acknowledged state; anything acknowledged under the
durable tier (storage/durability.py HM_FSYNC) survives a power cut;
and a crashed-then-recovered repo reconverges bit-identically to a
clean twin after resync.
"""

from __future__ import annotations

import contextlib
import errno
import os
import random
import sqlite3
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..analysis import lockdep
from ..analysis.lockdep import make_lock

WRITE = "write"
APPEND = "append"
TRUNCATE = "truncate"
FSYNC = "fsync"
REPLACE = "replace"
UNLINK = "unlink"
DB_COMMIT = "db_commit"

_W_OK = "ok"
_W_ERROR = "error"
_W_TORN = "torn"

_F_OK = "ok"
_F_ERROR = "error"
_F_LIE = "lie"


class DiskFaultPlan:
    """Seeded per-path fault schedule for writes and fsyncs.

    Each path gets its own RNG stream seeded by (seed, relpath), and
    each write/fsync on that path consumes the stream in op order — so
    the fate of "write #7 of feeds/ab/abcd" is a pure function of the
    seed, however the workload interleaves files. `after` ops per path
    are always fault-free (lets a unit test build a healthy prefix and
    then fault the tail deterministically); `path_filter` restricts
    faults to matching relpaths (substring)."""

    def __init__(
        self,
        seed: int = 0,
        write_error_p: float = 0.0,
        torn_write_p: float = 0.0,
        fsync_error_p: float = 0.0,
        fsync_lie_p: float = 0.0,
        errnos: Tuple[int, ...] = (errno.ENOSPC, errno.EIO),
        after: int = 0,
        path_filter: Optional[str] = None,
    ) -> None:
        self.seed = seed
        self.write_error_p = write_error_p
        self.torn_write_p = torn_write_p
        self.fsync_error_p = fsync_error_p
        self.fsync_lie_p = fsync_lie_p
        self.errnos = errnos
        self.after = after
        self.path_filter = path_filter
        self._lock = make_lock("store.fault.plan")
        self._rngs: Dict[str, random.Random] = {}
        self._ops: Dict[str, int] = {}
        self.stats: Dict[str, int] = {
            "write_errors": 0,
            "torn_writes": 0,
            "fsync_errors": 0,
            "fsync_lies": 0,
        }

    def _draw(self, path: str) -> Tuple[random.Random, int]:
        rng = self._rngs.get(path)
        if rng is None:
            rng = random.Random(f"{self.seed}|{path}")
            self._rngs[path] = rng
            self._ops[path] = 0
        n = self._ops[path]
        self._ops[path] = n + 1
        return rng, n

    def _applies(self, path: str) -> bool:
        return self.path_filter is None or self.path_filter in path

    def write_fate(self, path: str, nbytes: int):
        """(fate, errno, n_written_before_error) for the next write on
        `path`. The RNG stream advances even for filtered paths so the
        schedule of every OTHER path stays fixed."""
        with self._lock:
            rng, n = self._draw(path)
            r = rng.random()
            e = self.errnos[rng.randrange(len(self.errnos))]
            torn_at = rng.randrange(nbytes) if nbytes > 1 else 0
            if n < self.after or not self._applies(path):
                return _W_OK, 0, nbytes
            if r < self.write_error_p:
                self.stats["write_errors"] += 1
                return _W_ERROR, e, 0
            if r < self.write_error_p + self.torn_write_p:
                self.stats["torn_writes"] += 1
                return _W_TORN, e, torn_at
            return _W_OK, 0, nbytes

    def fsync_fate(self, path: str):
        """(fate, errno) for the next fsync on `path`."""
        with self._lock:
            rng, n = self._draw(path)
            r = rng.random()
            e = self.errnos[rng.randrange(len(self.errnos))]
            if n < self.after or not self._applies(path):
                return _F_OK, 0
            if r < self.fsync_error_p:
                self.stats["fsync_errors"] += 1
                return _F_ERROR, e
            if r < self.fsync_error_p + self.fsync_lie_p:
                self.stats["fsync_lies"] += 1
                return _F_LIE, 0
            return _F_OK, 0


class CrashRecorder:
    """Ordered write/fsync/rename/commit schedule of a workload under
    `root`, replayable prefix-by-prefix into fresh directories.

    The workload must start from an EMPTY root (materialize replays
    from nothing). SQLite statements journal per-connection and land in
    the event log as one DB_COMMIT batch per commit, so a crash between
    statements of a transaction drops the whole transaction — the same
    atomicity sqlite's rollback journal provides."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self._lock = make_lock("store.fault.recorder")
        self.events: List[Tuple] = []
        self._db_pending: Dict[str, List[Tuple]] = {}

    def rel(self, path: str) -> Optional[str]:
        """Path relative to root, or None for paths outside it (those
        are not recorded — e.g. an unrelated tmp dir)."""
        p = os.path.abspath(path)
        if p == self.root:
            return ""
        prefix = self.root + os.sep
        if not p.startswith(prefix):
            return None
        return p[len(prefix):]

    @property
    def n_points(self) -> int:
        """Number of crash boundaries: before event 0 .. after the
        last event."""
        with self._lock:
            return len(self.events) + 1

    def _emit(self, *event: Any) -> None:
        with self._lock:
            self.events.append(event)

    # -- file ops (called from the io_* seam) --------------------------

    def on_write(self, path: str, offset: Optional[int], data: bytes):
        rel = self.rel(path)
        if rel is None:
            return
        if offset is None:
            self._emit(APPEND, rel, bytes(data))
        else:
            self._emit(WRITE, rel, offset, bytes(data))

    def on_truncate(self, path: str, size: int) -> None:
        rel = self.rel(path)
        if rel is not None:
            self._emit(TRUNCATE, rel, size)

    def on_fsync(self, path: str, lied: bool) -> None:
        rel = self.rel(path)
        if rel is not None:
            self._emit(FSYNC, rel, lied)

    def on_replace(self, src: str, dst: str) -> None:
        rs, rd = self.rel(src), self.rel(dst)
        if rs is not None and rd is not None:
            self._emit(REPLACE, rs, rd)

    def on_unlink(self, path: str) -> None:
        rel = self.rel(path)
        if rel is not None:
            self._emit(UNLINK, rel)

    # -- sqlite ops (called from storage/sql.py) -----------------------

    def db_stmt(self, path: str, kind: str, sql: str, params) -> None:
        rel = self.rel(path)
        if rel is None:
            return
        with self._lock:
            self._db_pending.setdefault(rel, []).append(
                (kind, sql, params)
            )

    def db_commit(self, path: str) -> None:
        rel = self.rel(path)
        if rel is None:
            return
        with self._lock:
            stmts = self._db_pending.pop(rel, [])
            if stmts:
                self.events.append((DB_COMMIT, rel, stmts))

    # -- replay --------------------------------------------------------

    def materialize(
        self,
        dst_root: str,
        upto: int,
        powercut: bool = False,
        partial_last: Optional[int] = None,
        base: Optional[str] = None,
    ) -> None:
        """Build `dst_root` as the on-disk state of a crash after
        `upto` events. kill -9 model: every applied syscall survives.
        Power-cut model: per file, only the image captured by its last
        HONEST fsync before the cut (lying fsyncs capture nothing);
        sqlite commits are durable either way. `partial_last` applies
        only the first N bytes of event `upto` itself (an intra-write
        tear at the crash boundary).

        `base` is the pre-workload snapshot of the root for workloads
        that did NOT start from an empty directory (e.g. crash/recover
        cycles): its files seed the replay, and untouched files carry
        over verbatim. Without it the replay starts from nothing —
        recording over pre-existing state then drops that state."""
        with self._lock:
            events = list(self.events[:upto])
            if partial_last is not None and upto < len(self.events):
                ev = self.events[upto]
                if ev[0] == WRITE:
                    events.append(
                        (WRITE, ev[1], ev[2], ev[3][:partial_last])
                    )
                elif ev[0] == APPEND:
                    events.append((APPEND, ev[1], ev[2][:partial_last]))
        os.makedirs(dst_root, exist_ok=True)
        if base is not None:
            import shutil

            shutil.copytree(base, dst_root, dirs_exist_ok=True)
        volatile: Dict[str, bytearray] = {}
        durable: Dict[str, bytearray] = {}
        removed: set = set()
        dbs: Dict[str, List[List[Tuple]]] = {}

        def seed(rel: str) -> bytearray:
            """The file's working image, seeded from the base snapshot
            on first touch (a write at offset N lands on the base
            bytes, not on zeros)."""
            buf = volatile.get(rel)
            if buf is None:
                buf = bytearray()
                p = os.path.join(dst_root, rel)
                if (
                    base is not None
                    and rel not in removed
                    and os.path.exists(p)
                ):
                    with open(p, "rb") as fh:
                        buf = bytearray(fh.read())
                    # base content was at rest on disk: durable too
                    durable.setdefault(rel, bytearray(buf))
                volatile[rel] = buf
            return buf

        for ev in events:
            kind = ev[0]
            if kind == WRITE:
                _, rel, off, data = ev
                buf = seed(rel)
                removed.discard(rel)
                if len(buf) < off:
                    buf.extend(b"\x00" * (off - len(buf)))
                buf[off:off + len(data)] = data
            elif kind == APPEND:
                _, rel, data = ev
                seed(rel).extend(data)
                removed.discard(rel)
            elif kind == TRUNCATE:
                _, rel, size = ev
                buf = seed(rel)
                removed.discard(rel)
                if len(buf) > size:
                    del buf[size:]
                elif len(buf) < size:
                    buf.extend(b"\x00" * (size - len(buf)))
            elif kind == FSYNC:
                _, rel, lied = ev
                if not lied and rel in volatile:
                    durable[rel] = bytearray(volatile[rel])
            elif kind == REPLACE:
                _, rs, rd = ev
                seed(rs)
                volatile[rd] = volatile.pop(rs)
                removed.add(rs)
                removed.discard(rd)
                # rename is a metadata op: the DURABLE image of the
                # destination is whatever of the source was durable
                # (checkpoint writers fsync before replacing)
                if rs in durable:
                    durable[rd] = durable.pop(rs)
                else:
                    durable.pop(rd, None)
            elif kind == UNLINK:
                _, rel = ev
                volatile.pop(rel, None)
                durable.pop(rel, None)
                removed.add(rel)
            elif kind == DB_COMMIT:
                _, rel, stmts = ev
                dbs.setdefault(rel, []).append(stmts)
        files = durable if powercut else volatile
        for rel, buf in files.items():
            p = os.path.join(dst_root, rel)
            os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
            with open(p, "wb") as fh:
                fh.write(bytes(buf))
        if powercut:
            # a file touched but never fsynced keeps its base image
            # (already on disk from the copy); one CREATED in-session
            # and never fsynced must not exist at all
            for rel in volatile:
                if rel not in durable:
                    p = os.path.join(dst_root, rel)
                    if os.path.exists(p):
                        os.remove(p)
        for rel in removed:
            if rel in files:
                continue
            p = os.path.join(dst_root, rel)
            if os.path.exists(p):
                os.remove(p)
        for rel, batches in dbs.items():
            p = os.path.join(dst_root, rel)
            os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
            conn = sqlite3.connect(p)
            try:
                for stmts in batches:
                    for kind, sql, params in stmts:
                        if kind == "script":
                            conn.executescript(sql)
                        elif kind == "many":
                            conn.executemany(sql, params)
                        else:
                            conn.execute(sql, params)
                    conn.commit()
            finally:
                conn.close()


# ---------------------------------------------------------------------------
# activation + the io_* seam


class _Active:
    def __init__(
        self,
        plan: Optional[DiskFaultPlan],
        recorder: Optional[CrashRecorder],
    ) -> None:
        self.plan = plan
        self.recorder = recorder


_active: Optional[_Active] = None
_active_lock = make_lock("store.fault.active")
# bumped on every install AND uninstall: long-lived cached write
# handles (FileFeedStorage's hot-append fds) compare this to decide
# whether to re-open through the seam — a handle opened before a
# harness activated would otherwise bypass injection/recording
_gen = 0


def harness_gen() -> int:
    return _gen


@contextlib.contextmanager
def activate(
    plan: Optional[DiskFaultPlan] = None,
    recorder: Optional[CrashRecorder] = None,
):
    """Install a fault plan and/or crash recorder on the io_* seam for
    the duration of the block. One harness at a time (tests)."""
    global _active, _gen
    with _active_lock:
        if _active is not None:
            raise RuntimeError("a disk-fault harness is already active")
        _active = _Active(plan, recorder)
        _gen += 1
    try:
        yield _active
    finally:
        with _active_lock:
            _active = None
            _gen += 1


def active_recorder() -> Optional[CrashRecorder]:
    a = _active
    return a.recorder if a is not None else None


def _plan_rel(path: str) -> str:
    """The per-path fault-stream key: recorder-relative when one is
    active (stable across tmp dirs), absolute otherwise."""
    a = _active
    if a is not None and a.recorder is not None:
        rel = a.recorder.rel(path)
        if rel is not None:
            return rel
    return path


class FaultFile:
    """A writable file handle behind the harness: every write consults
    the plan (short/torn writes, ENOSPC/EIO) and feeds the recorder;
    truncate/close pass through with recording. Read-side methods
    delegate untouched."""

    def __init__(self, fh, path: str, append_mode: bool) -> None:
        self._fh = fh
        self.path = path
        self._append = append_mode

    # -- pass-through ---------------------------------------------------

    def read(self, *a):
        return self._fh.read(*a)

    def seek(self, *a):
        return self._fh.seek(*a)

    def tell(self):
        return self._fh.tell()

    def flush(self):
        return self._fh.flush()

    def fileno(self):
        return self._fh.fileno()

    @property
    def closed(self):
        return self._fh.closed

    def close(self):
        return self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._fh.close()
        return False

    # -- faulted ops ----------------------------------------------------

    def write(self, data) -> int:
        a = _active
        data = bytes(data)
        plan = a.plan if a is not None else None
        if plan is not None:
            fate, err, n_ok = plan.write_fate(
                _plan_rel(self.path), len(data)
            )
            if fate == _W_ERROR:
                raise OSError(err, os.strerror(err), self.path)
            if fate == _W_TORN:
                self._write_through(data[:n_ok], a)
                raise OSError(err, os.strerror(err), self.path)
        self._write_through(data, a)
        return len(data)

    def _write_through(self, data: bytes, a: Optional[_Active]) -> None:
        if not data:
            return
        offset = None if self._append else self._fh.tell()
        self._fh.write(data)
        if a is not None and a.recorder is not None:
            a.recorder.on_write(self.path, offset, data)

    def truncate(self, size: Optional[int] = None) -> int:
        if size is None:
            size = self._fh.tell()
        out = self._fh.truncate(size)
        a = _active
        if a is not None and a.recorder is not None:
            a.recorder.on_truncate(self.path, size)
        return out


_WRITE_MODES = ("w", "a", "+", "x")


def io_open(path: str, mode: str = "rb"):
    """The storage backends' open(). Write-capable opens route through
    the harness when one is active; everything else (and the common
    inactive case) is the builtin."""
    a = _active
    if a is None or not any(m in mode for m in _WRITE_MODES):
        return open(path, mode)
    existed = os.path.exists(path)
    fh = open(path, mode)
    if a.recorder is not None:
        if "w" in mode or (not existed and ("a" in mode or "x" in mode)):
            # w/w+ truncate at open; a fresh a/x creates empty
            a.recorder.on_truncate(path, 0)
    return FaultFile(fh, path, append_mode="a" in mode)


def io_fsync(fh) -> None:
    """fsync through the harness: may raise EIO, may LIE (succeed
    without durability — visible only to the power-cut replay)."""
    with lockdep.blocking("fsync", getattr(fh, "path", "") or ""):
        a = _active
        if a is None:
            os.fsync(fh.fileno())
            return
        path = getattr(fh, "path", None)
        lied = False
        if a.plan is not None and path is not None:
            fate, err = a.plan.fsync_fate(_plan_rel(path))
            if fate == _F_ERROR:
                raise OSError(err, os.strerror(err), path)
            lied = fate == _F_LIE
        if not lied:
            os.fsync(fh.fileno())
        if a.recorder is not None and path is not None:
            a.recorder.on_fsync(path, lied)


def io_replace(src: str, dst: str) -> None:
    os.replace(src, dst)
    a = _active
    if a is not None and a.recorder is not None:
        a.recorder.on_replace(src, dst)


def io_remove(path: str) -> None:
    os.remove(path)
    a = _active
    if a is not None and a.recorder is not None:
        a.recorder.on_unlink(path)
