"""Clock / Cursor / Key / FeedInfo stores over SqlDatabase.

Parity (SURVEY.md §2.1): ClockStore (monotonic upsert, get/getMultiple/
update/set, reference src/ClockStore.ts:24-119), CursorStore (INFINITY_SEQ
clamping, docsWithActor reverse lookup, reference src/CursorStore.ts:19-91),
KeyStore (named keypairs, reference src/KeyStore.ts:10-39), FeedInfoStore
(reference src/FeedStore.ts:150-205).

TPU-first addition: ClockStore.union_query / dominated_query lift the
bulk vector-clock folds onto the device kernels (ops/clock_kernels.py) —
the 100k-doc query of BASELINE.json config 5 — instead of row-at-a-time
SQL aggregation.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from ..analysis.lockdep import make_rlock
from ..crdt import clock as clockmod
from ..utils import keys as keymod
from .sql import SqlDatabase

INFINITY_SEQ = clockmod.INFINITY_SEQ


def _clamp(seq: float) -> int:
    if seq == math.inf or seq >= INFINITY_SEQ:
        return INFINITY_SEQ
    return int(seq)


class ClockStore:
    def __init__(self, db: SqlDatabase) -> None:
        self.db = db
        self.mirror = None  # optional DeviceClockMirror (attach_mirror)
        self._mirror_repo: Optional[str] = None

    def attach_mirror(self, repo_id: str, mirror) -> None:
        """Keep a DeviceClockMirror (ops/clock_mirror.py) consistent
        with every clock write FOR ONE REPO, seeding it with the
        existing rows; whole-corpus union/dominated queries then run as
        single dispatches over the device-resident matrix instead of
        sqlite scans + re-uploads. Writes scoped to other repo ids
        sharing this database never touch the mirror (set() is a hard
        per-repo overwrite — merging repos would corrupt it)."""
        rows = self.db.query(
            "SELECT doc_id, actor_id, seq FROM clocks WHERE repo_id=?",
            (repo_id,),
        )
        by_doc: Dict[str, clockmod.Clock] = {}
        for doc_id, actor, seq in rows:
            by_doc.setdefault(doc_id, {})[actor] = seq
        mirror.update_many(by_doc)
        self.mirror = mirror
        self._mirror_repo = repo_id

    def _mirror_for(self, repo_id: str):
        return self.mirror if repo_id == self._mirror_repo else None

    def get(self, repo_id: str, doc_id: str) -> clockmod.Clock:
        rows = self.db.query(
            "SELECT actor_id, seq FROM clocks WHERE repo_id=? AND doc_id=?",
            (repo_id, doc_id),
        )
        return {a: s for a, s in rows}

    def get_multiple(
        self, repo_id: str, doc_ids: Iterable[str]
    ) -> Dict[str, clockmod.Clock]:
        ids = list(doc_ids)
        out: Dict[str, clockmod.Clock] = {d: {} for d in ids}
        for base in range(0, len(ids), 500):  # see CursorStore note
            chunk = ids[base : base + 500]
            marks = ",".join("?" for _ in chunk)
            rows = self.db.query(
                f"SELECT doc_id, actor_id, seq FROM clocks "
                f"WHERE repo_id=? AND doc_id IN ({marks})",
                (repo_id, *chunk),
            )
            for doc_id, actor, seq in rows:
                out[doc_id][actor] = seq
        return out

    def update(
        self, repo_id: str, doc_id: str, clock: clockmod.Clock
    ) -> clockmod.Clock:
        """Monotonic merge: only raises seqs (reference's
        `seq=excluded.seq WHERE excluded.seq > seq` upsert)."""
        self.db.executemany(
            "INSERT INTO clocks (repo_id, doc_id, actor_id, seq) "
            "VALUES (?,?,?,?) "
            "ON CONFLICT (repo_id, doc_id, actor_id) DO UPDATE "
            "SET seq=excluded.seq WHERE excluded.seq > seq",
            [
                (repo_id, doc_id, a, _clamp(s))
                for a, s in clock.items()
            ],
        )
        m = self._mirror_for(repo_id)
        if m is not None:
            m.update(doc_id, clock)
        return self.get(repo_id, doc_id)

    def update_many(
        self, repo_id: str, clocks: Dict[str, clockmod.Clock]
    ) -> None:
        """Monotonic merge for many docs in one executemany (no per-doc
        read-back — the bulk cold start writes thousands of clock rows)."""
        self.db.executemany(
            "INSERT INTO clocks (repo_id, doc_id, actor_id, seq) "
            "VALUES (?,?,?,?) "
            "ON CONFLICT (repo_id, doc_id, actor_id) DO UPDATE "
            "SET seq=excluded.seq WHERE excluded.seq > seq",
            [
                (repo_id, d, a, _clamp(s))
                for d, clock in clocks.items()
                for a, s in clock.items()
            ],
        )
        m = self._mirror_for(repo_id)
        if m is not None:
            m.update_many(clocks)

    def set(
        self, repo_id: str, doc_id: str, clock: clockmod.Clock
    ) -> None:
        """Hard overwrite (reference ClockStore.set)."""
        self.db.execute(
            "DELETE FROM clocks WHERE repo_id=? AND doc_id=?",
            (repo_id, doc_id),
        )
        self.db.executemany(
            "INSERT INTO clocks (repo_id, doc_id, actor_id, seq) "
            "VALUES (?,?,?,?)",
            [(repo_id, doc_id, a, _clamp(s)) for a, s in clock.items()],
        )
        m = self._mirror_for(repo_id)
        if m is not None:
            m.set(doc_id, clock)

    def delete_doc(self, doc_id: str) -> None:
        """Drop every repo's clock rows for a doc (doc destroy)."""
        self.db.execute("DELETE FROM clocks WHERE doc_id=?", (doc_id,))
        if self.mirror is not None:  # destroy is cross-repo by design
            self.mirror.delete_doc(doc_id)

    def all_doc_ids(self, repo_id: str) -> List[str]:
        return [
            r[0]
            for r in self.db.query(
                "SELECT DISTINCT doc_id FROM clocks WHERE repo_id=?",
                (repo_id,),
            )
        ]

    # -- device bulk queries -------------------------------------------

    def _packed(self, repo_id: str, doc_ids: List[str]):
        clocks = self.get_multiple(repo_id, doc_ids)
        ordered = [clocks[d] for d in doc_ids]
        actors = clockmod.actor_axis(ordered)
        if not actors:
            return None, []
        from ..ops import clock_kernels as K

        return K.pack_clocks(clockmod.pack(ordered, actors)), actors

    def union_query(
        self, repo_id: str, doc_ids: Optional[List[str]] = None
    ) -> clockmod.Clock:
        """Union of many docs' clocks in one device reduction. With a
        mirror attached, the whole-corpus form never touches sqlite —
        the matrix is already device-resident."""
        m = self._mirror_for(repo_id)
        if m is not None and doc_ids is None:
            return m.union()
        ids = doc_ids if doc_ids is not None else self.all_doc_ids(repo_id)
        if not ids:
            return {}
        rows, actors = self._packed(repo_id, ids)
        if rows is None:
            return {}
        from ..ops import clock_kernels as K

        merged = K.union_reduce(rows)
        return clockmod.unpack([[int(x) for x in merged]], actors)[0]

    def dominated_query(
        self, repo_id: str, query: clockmod.Clock,
        doc_ids: Optional[List[str]] = None,
    ) -> List[str]:
        """All docs whose clock is dominated by `query` (one dispatch;
        device-resident when a mirror is attached)."""
        m = self._mirror_for(repo_id)
        if m is not None and doc_ids is None:
            return m.dominated(query)
        ids = doc_ids if doc_ids is not None else self.all_doc_ids(repo_id)
        if not ids:
            return []
        rows, actors = self._packed(repo_id, ids)
        if rows is None:
            return list(ids)
        from ..ops import clock_kernels as K
        import numpy as np

        q = K.pack_clocks(
            clockmod.pack([{a: query.get(a, 0) for a in actors}], actors)
        )[0]
        ok = np.asarray(K.gte(jnp_broadcast(q, rows), rows))
        return [d for d, good in zip(ids, ok) if good]


def jnp_broadcast(q, rows):
    import jax.numpy as jnp

    return jnp.broadcast_to(q, rows.shape)


class CursorStore:
    """Which actors (and up to what seq) a repo includes in each doc.

    Reads serve from a write-through in-memory mirror (hydrated per
    repo_id on first touch): cursor lookups sit on the replication hot
    path (_sync_changes runs docs_with_actor + entry per feed append
    burst) and a ~1ms SQLite round trip under writer contention there
    throttles live convergence. SQLite stays the durable copy — every
    mutation still lands in the table; the mirror merges with the same
    monotonic max-wins rule as the upsert."""

    def __init__(self, db: SqlDatabase) -> None:
        self.db = db
        self._lock = make_rlock("store.cursors")
        # repo_id -> doc_id -> {actor: seq}; repo_id -> actor -> docs
        self._mem: Dict[str, Dict[str, Dict[str, int]]] = {}
        self._by_actor: Dict[str, Dict[str, Dict[str, None]]] = {}
        self._hydrated: set = set()  # repo_ids with SQLite rows merged
        # bumped by delete_doc: deletion is NOT monotonic, so a
        # hydration snapshot taken before a racing delete must be
        # thrown away and re-queried (see _ensure_hydrated)
        self._del_gen: Dict[str, int] = {}

    def _repo(self, repo_id: str) -> Dict[str, Dict[str, int]]:
        """The repo's mirror dicts (created empty on demand).
        REQUIRES store.cursors (analysis/guards.py). Hydration from
        SQLite happens ONLY in _ensure_hydrated — never here, never
        under the mirror lock."""
        mem = self._mem.get(repo_id)
        if mem is None:
            mem = self._mem[repo_id] = {}
            self._by_actor[repo_id] = {}
        return mem

    def _ensure_hydrated(self, repo_id: str) -> None:
        """Merge the repo's SQLite rows into the mirror, once. The
        query runs with NO mirror lock held: the write batches absorb
        into the mirror from inside `db.bulk()` (sql lock HELD), so
        the declared order is store.sql -> store.cursors
        (analysis/hierarchy.py) — hydrating under the mirror lock was
        the other half of a real sql<->cursors AB/BA deadlock the
        first HM_LOCKDEP=1 run over this tree caught (bulk-load /
        store-flush thread vs a replication cursor lookup).

        Upsert races are safe by monotonicity: a row committed after
        our query was also write-through absorbed by its writer, and a
        concurrent hydration merging the same snapshot is idempotent
        (max-wins). DELETION is not monotonic — a delete_doc landing
        between our query and our merge would be resurrected by the
        stale snapshot — so delete_doc bumps a per-repo generation and
        we re-query whenever it moved."""
        while repo_id not in self._hydrated:  # membership: GIL-atomic
            with self._lock:
                gen = self._del_gen.get(repo_id, 0)
            rows = self.db.query(
                "SELECT doc_id, actor_id, seq FROM cursors "
                "WHERE repo_id=?",
                (repo_id,),
            )
            with self._lock:
                if repo_id in self._hydrated:
                    return
                if self._del_gen.get(repo_id, 0) != gen:
                    continue  # a delete raced the query: snapshot stale
                for doc_id, actor, seq in rows:
                    self._absorb(repo_id, doc_id, actor, seq)
                self._hydrated.add(repo_id)

    def _absorb(
        self, repo_id: str, doc_id: str, actor: str, seq: int
    ) -> None:
        """Max-wins merge into the mirror (the upsert's twin).
        REQUIRES store.cursors (analysis/guards.py)."""
        cur = self._repo(repo_id).setdefault(doc_id, {})
        if actor not in cur or seq > cur[actor]:
            cur[actor] = seq
        self._by_actor[repo_id].setdefault(actor, {})[doc_id] = None

    def get(self, repo_id: str, doc_id: str) -> clockmod.Clock:
        self._ensure_hydrated(repo_id)
        with self._lock:
            return dict(self._repo(repo_id).get(doc_id, {}))

    def entry(self, repo_id: str, doc_id: str, actor_id: str) -> int:
        self._ensure_hydrated(repo_id)
        with self._lock:
            return self._repo(repo_id).get(doc_id, {}).get(actor_id, 0)

    def update(
        self, repo_id: str, doc_id: str, clock: clockmod.Clock
    ) -> clockmod.Clock:
        self._ensure_hydrated(repo_id)  # the read-back below merges
        self.db.executemany(
            "INSERT INTO cursors (repo_id, doc_id, actor_id, seq) "
            "VALUES (?,?,?,?) "
            "ON CONFLICT (repo_id, doc_id, actor_id) DO UPDATE "
            "SET seq=excluded.seq WHERE excluded.seq > seq",
            [(repo_id, doc_id, a, _clamp(s)) for a, s in clock.items()],
        )
        with self._lock:
            for a, s in clock.items():
                self._absorb(repo_id, doc_id, a, _clamp(s))
            return dict(self._repo(repo_id).get(doc_id, {}))

    def merge_mem(
        self, repo_id: str, doc_id: str, clock: clockmod.Clock
    ) -> clockmod.Clock:
        """Mirror-only monotonic merge, returning the merged cursor.
        The durable sqlite rows ride the caller's DEBOUNCED store
        flush (RepoBackend._stores -> update_many_rows): cursor gossip
        ingest is the fleet's hottest message path, and a synchronous
        executemany per inbound frame puts sqlite on it O(actors) deep
        (a fleet doc carries one actor per peer). Crash safety is
        unchanged: cursor rows rebuild from feeds on recovery."""
        self._ensure_hydrated(repo_id)
        with self._lock:
            for a, s in clock.items():
                self._absorb(repo_id, doc_id, a, _clamp(s))
            return dict(self._repo(repo_id).get(doc_id, {}))

    def update_many_rows(
        self, repo_id: str, rows: Iterable[Tuple[str, str, int]]
    ) -> None:
        """Monotonic merge of (doc_id, actor_id, seq) rows in one
        statement, no read-back (the debounced live-path store flush)."""
        rows = list(rows)
        self.db.executemany(
            "INSERT INTO cursors (repo_id, doc_id, actor_id, seq) "
            "VALUES (?,?,?,?) "
            "ON CONFLICT (repo_id, doc_id, actor_id) DO UPDATE "
            "SET seq=excluded.seq WHERE excluded.seq > seq",
            [(repo_id, d, a, _clamp(s)) for d, a, s in rows],
        )
        with self._lock:
            for d, a, s in rows:
                self._absorb(repo_id, d, a, _clamp(s))

    def add_actor(
        self, repo_id: str, doc_id: str, actor_id: str,
        seq: float = math.inf,
    ) -> None:
        self.update(repo_id, doc_id, {actor_id: seq})

    def add_actors(
        self, repo_id: str, entries, seq: float = math.inf
    ) -> None:
        """add_actor for many (doc_id, actor_id) pairs in one statement."""
        entries = list(entries)
        s = _clamp(seq)
        self.db.executemany(
            "INSERT INTO cursors (repo_id, doc_id, actor_id, seq) "
            "VALUES (?,?,?,?) "
            "ON CONFLICT (repo_id, doc_id, actor_id) DO UPDATE "
            "SET seq=excluded.seq WHERE excluded.seq > seq",
            [(repo_id, d, a, s) for d, a in entries],
        )
        with self._lock:
            for d, a in entries:
                self._absorb(repo_id, d, a, s)

    def get_multiple(
        self, repo_id: str, doc_ids: Iterable[str]
    ) -> Dict[str, clockmod.Clock]:
        """Cursors for many docs in one pass over the mirror."""
        ids = list(doc_ids)
        self._ensure_hydrated(repo_id)
        with self._lock:
            mem = self._repo(repo_id)
            return {d: dict(mem.get(d, {})) for d in ids}

    def docs_with_actor(self, repo_id: str, actor_id: str) -> List[str]:
        self._ensure_hydrated(repo_id)
        with self._lock:
            self._repo(repo_id)
            return list(self._by_actor[repo_id].get(actor_id, ()))

    def actors_for(self, repo_id: str, doc_id: str) -> List[str]:
        return list(self.get(repo_id, doc_id).keys())

    def delete_doc(self, repo_id: str, doc_id: str) -> None:
        self.db.execute(
            "DELETE FROM cursors WHERE repo_id=? AND doc_id=?",
            (repo_id, doc_id),
        )
        with self._lock:
            # invalidate in-flight hydrations: a snapshot queried
            # before this delete must not merge the doc back in
            self._del_gen[repo_id] = self._del_gen.get(repo_id, 0) + 1
            if repo_id in self._mem:
                self._mem[repo_id].pop(doc_id, None)
                for docs in self._by_actor[repo_id].values():
                    docs.pop(doc_id, None)


class KeyStore:
    def __init__(self, db: SqlDatabase) -> None:
        self.db = db

    def get(self, name: str) -> Optional[keymod.KeyPair]:
        rows = self.db.query(
            "SELECT public_key, secret_key FROM keys WHERE name=?", (name,)
        )
        if not rows:
            return None
        return keymod.KeyPair(public_key=rows[0][0], secret_key=rows[0][1])

    def set(self, name: str, pair: keymod.KeyPair) -> keymod.KeyPair:
        self.db.execute(
            "INSERT OR REPLACE INTO keys (name, public_key, secret_key) "
            "VALUES (?,?,?)",
            (name, pair.public_key, pair.secret_key),
        )
        return pair

    def get_or_create(self, name: str) -> keymod.KeyPair:
        pair = self.get(name)
        if pair is None:
            pair = keymod.create()
            self.set(name, pair)
        return pair

    def all_pairs(self) -> Dict[str, keymod.KeyPair]:
        """Every stored keypair in ONE query (the backend hydrates its
        actor-key map from this at open — a per-actor SELECT would put
        sqlite back on the bulk cold-open path)."""
        return {
            name: keymod.KeyPair(public_key=pub, secret_key=sec)
            for name, pub, sec in self.db.query(
                "SELECT name, public_key, secret_key FROM keys"
            )
        }

    def clear(self, name: str) -> None:
        self.db.execute("DELETE FROM keys WHERE name=?", (name,))


class FeedInfoStore:
    def __init__(self, db: SqlDatabase) -> None:
        self.db = db

    def save(
        self, public_id: str, discovery_id: str, is_writable: bool
    ) -> None:
        self.db.execute(
            "INSERT OR REPLACE INTO feeds "
            "(public_id, discovery_id, is_writable) VALUES (?,?,?)",
            (public_id, discovery_id, 1 if is_writable else 0),
        )

    def save_many(self, rows) -> None:
        """(public_id, discovery_id, is_writable) triples, one statement."""
        self.db.executemany(
            "INSERT OR REPLACE INTO feeds "
            "(public_id, discovery_id, is_writable) VALUES (?,?,?)",
            [(p, d, 1 if w else 0) for p, d, w in rows],
        )

    def delete(self, public_id: str) -> None:
        self.db.execute(
            "DELETE FROM feeds WHERE public_id=?", (public_id,)
        )

    def all_public_ids(self) -> List[str]:
        return [r[0] for r in self.db.query("SELECT public_id FROM feeds")]

    def by_discovery_id(self, discovery_id: str) -> Optional[str]:
        rows = self.db.query(
            "SELECT public_id FROM feeds WHERE discovery_id=?",
            (discovery_id,),
        )
        return rows[0][0] if rows else None

    def remove(self, public_id: str) -> None:
        self.db.execute(
            "DELETE FROM feeds WHERE public_id=?", (public_id,)
        )

    def is_writable(self, public_id: str) -> bool:
        rows = self.db.query(
            "SELECT is_writable FROM feeds WHERE public_id=?", (public_id,)
        )
        return bool(rows and rows[0][0])
