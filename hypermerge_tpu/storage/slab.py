"""Corpus slab — every feed's columnar sidecar in ONE append-only file.

The per-feed single-file sidecar (storage/colcache.py FileColumnStorageV2)
made each sidecar one open+read — but a 10k-doc cold open still paid ~10k
opens plus the directory-walk stats to find them, about 2s of the
cold-open wall clock (BENCH_r05 t_io). The slab collapses all of that to
O(1) opens and large sequential reads: one file of framed segments plus a
tiny extent index, mmap'd once and sliced per feed.

Layout (`feeds/cols.slab`):

    header   b"HMSB" <u32 version=1>
    segment  <u8 kind> <u16 name_len> name <u64 payload_len> payload

kinds:
    1  image     the feed's full sidecar image in FileColumnStorageV2
                 byte format (v3 checkpoint blob, possibly followed by
                 framed v2 records). Supersedes every earlier segment of
                 the feed (written by checkpoint/compaction, and by the
                 lazy migration of a legacy `.cols2` file on first read).
    2  record    one framed v2 record appended after the feed's image
                 (live writer path, storage/colcache.py commit_change).
    3  tombstone the feed was reset/destroyed; earlier segments are dead.

Index (`feeds/cols.slab.idx`): one entry per segment —
    <u8 kind> <u16 name_len> name <u64 payload_off> <u64 payload_len>
so open() reads the small index instead of scanning the slab. The index
is advisory: a torn/missing/short index rebuilds (or repairs forward)
by scanning slab segment headers; a torn slab tail — a segment whose
declared payload runs past EOF — is ignored and overwritten by the next
append. Crash model matches the sidecars it replaces: the columnar cache
is derived data, blocks remain the source of truth.

Superseded bytes (old images, tombstoned feeds) are reclaimed by
`compact()`, which `close()` runs automatically when more than
HM_SLAB_SLACK (default 25%) of the file is dead — tmp + atomic rename,
so a crash mid-compaction leaves either the old file or the new one.
"""

from __future__ import annotations

import io
import mmap
import os
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..analysis.lockdep import make_rlock
from ..utils.debug import log
from .faults import io_fsync, io_open, io_remove, io_replace

_MAGIC = b"HMSB"
_VERSION = 1
_HDR = struct.Struct("<4sI")
_SEG = struct.Struct("<BH")  # kind, name_len  (then name, then <Q len)
_LEN = struct.Struct("<Q")

KIND_IMAGE = 1
KIND_RECORD = 2
KIND_TOMBSTONE = 3


def _slack_fraction() -> float:
    return float(os.environ.get("HM_SLAB_SLACK", "0.25"))


class CorpusSlab:
    """One repo's sidecar slab: extent index + append/read/compact."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.idx_path = path + ".idx"
        self._lock = make_rlock("store.slab")
        self._loaded = False
        # name -> live extents [(kind, payload_off, payload_len)]:
        # an image resets the list, records append, a tombstone clears
        self._feeds: Dict[str, List[Tuple[int, int, int]]] = {}
        self._end = 0  # valid end of the slab file
        self._live_bytes = 0  # header+payload bytes of live segments
        self._fh: Optional[io.BufferedRandom] = None
        self._mm: Optional[mmap.mmap] = None
        self._mm_size = 0
        self._idx_fh = None
        self._closed = False
        # crash-recovery accounting from the last _ensure_loaded: how
        # many segments were repaired forward past the index, and
        # whether the index itself was unusable (tools/scrub.py)
        self.last_repair: Dict[str, int] = {}

    # -- index ----------------------------------------------------------

    def _ensure_loaded(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        self._end = len(_HDR.pack(_MAGIC, _VERSION))
        try:
            slab_size = os.path.getsize(self.path)
        except OSError:
            return
        entries, idx_ok, idx_end = self._read_index(slab_size)
        if not idx_ok:
            entries = []
        pos = len(_HDR.pack(_MAGIC, _VERSION))
        for kind, name, off, ln in entries:
            self._apply(kind, name, off, ln)
            pos = off + ln
        # repair forward: segments appended after the last indexed one
        # (crash between the slab append and the index append), or the
        # whole file when the index was unusable
        recovered = self._scan(pos, slab_size)
        self.last_repair = {
            "segments_recovered": len(recovered),
            "idx_rebuilt": 0 if idx_ok else 1,
            "bytes_ignored": max(0, slab_size - (
                recovered[-1][2] + recovered[-1][3] if recovered else pos
            )),
        }
        if recovered:
            for kind, name, off, ln in recovered:
                self._apply(kind, name, off, ln)
            if idx_ok:
                # a torn partial entry may trail the last good one; drop
                # it BEFORE appending, or every later open would parse
                # the fragment as a bogus entry, fail the monotonic
                # check, and rescan the whole slab
                self._truncate_idx(idx_end)
                for e in recovered:
                    self._append_idx(*e)
            else:
                self._rewrite_idx()
        elif not idx_ok:
            self._rewrite_idx()
        elif idx_end is not None:
            self._truncate_idx(idx_end)

    def _read_index(self, slab_size: int):
        """([(kind, name, payload_off, payload_len)], usable, torn_at) —
        usable is False when the index is missing or inconsistent with
        the slab; torn_at is the byte offset of a trailing partial entry
        fragment (None when the file parsed cleanly to its end)."""
        try:
            with open(self.idx_path, "rb") as fh:
                raw = fh.read()
        except OSError:
            return [], False, None
        out = []
        pos = 0
        end = len(raw)
        prev_end = len(_HDR.pack(_MAGIC, _VERSION))
        while pos + _SEG.size <= end:
            kind, nlen = _SEG.unpack_from(raw, pos)
            p = pos + _SEG.size
            if p + nlen + 16 > end:
                break  # torn index tail: entries so far remain usable
            name = raw[p : p + nlen].decode("ascii", "replace")
            off, ln = struct.unpack_from("<QQ", raw, p + nlen)
            if off < prev_end or off + ln > slab_size:
                return [], False, None  # inconsistent: rebuild by scan
            out.append((kind, name, off, ln))
            prev_end = off + ln
            pos = p + nlen + 16
        return out, True, (pos if pos < end else None)

    def _truncate_idx(self, torn_at: Optional[int]) -> None:
        """Drop a torn partial entry fragment from the index tail so
        later appends land on a clean boundary."""
        if torn_at is None:
            return
        try:
            with io_open(self.idx_path, "r+b") as fh:
                fh.truncate(torn_at)
        except OSError:
            pass  # read-only media: the fragment stays, scan still heals

    def _scan(self, start: int, slab_size: int):
        """Parse slab segment headers in [start, slab_size); stops at a
        torn tail."""
        if start >= slab_size:
            return []
        out = []
        with open(self.path, "rb") as fh:
            mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
            try:
                pos = start
                while pos + _SEG.size <= slab_size:
                    kind, nlen = _SEG.unpack_from(mm, pos)
                    p = pos + _SEG.size
                    if kind not in (
                        KIND_IMAGE, KIND_RECORD, KIND_TOMBSTONE
                    ) or p + nlen + _LEN.size > slab_size:
                        break
                    name = mm[p : p + nlen].decode("ascii", "replace")
                    (ln,) = _LEN.unpack_from(mm, p + nlen)
                    off = p + nlen + _LEN.size
                    if off + ln > slab_size:
                        break  # torn tail
                    out.append((kind, name, off, ln))
                    pos = off + ln
            finally:
                mm.close()
        return out

    def _apply(self, kind: int, name: str, off: int, ln: int) -> None:
        seg_bytes = _SEG.size + len(name) + _LEN.size + ln
        if kind == KIND_IMAGE:
            for _k, _o, dead in self._feeds.get(name, ()):
                self._live_bytes -= _SEG.size + len(name) + _LEN.size + dead
            self._feeds[name] = [(kind, off, ln)]
            self._live_bytes += seg_bytes
        elif kind == KIND_RECORD:
            self._feeds.setdefault(name, []).append((kind, off, ln))
            self._live_bytes += seg_bytes
        else:  # tombstone
            for _k, _o, dead in self._feeds.get(name, ()):
                self._live_bytes -= _SEG.size + len(name) + _LEN.size + dead
            self._feeds[name] = []
        self._end = off + ln

    # -- reads ----------------------------------------------------------

    def has(self, name: str) -> bool:
        with self._lock:
            self._ensure_loaded()
            return name in self._feeds

    def feed_live(self, name: str) -> bool:
        """True iff the feed has live (non-tombstoned) segments."""
        with self._lock:
            self._ensure_loaded()
            return bool(self._feeds.get(name))

    def feed_names(self) -> List[str]:
        with self._lock:
            self._ensure_loaded()
            return [n for n, segs in self._feeds.items() if segs]

    def _mapped(self) -> Optional[mmap.mmap]:
        # caller holds the lock. The mapping is reused stat-free until
        # an append invalidates it (_mm is cleared there) — a bulk cold
        # open slices it thousands of times.
        if self._mm is not None:
            return self._mm
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return None
        if size == 0:
            return None
        with open(self.path, "rb") as fh:
            self._mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        self._mm_size = size
        return self._mm

    def prefetch(self, names) -> None:
        """Read-ahead hint for the streaming pipeline's io stage: ask
        the OS (madvise WILLNEED) to page in the live extents of
        `names` before image_bytes slices them, so a cold-cache bulk
        open's reads are sequential prefetches instead of per-feed
        demand faults. Advisory only — unknown names and platforms
        without madvise are silently fine."""
        with self._lock:
            self._ensure_loaded()
            mm = self._mapped()
            if mm is None or not hasattr(mm, "madvise"):
                return
            page = mmap.PAGESIZE
            for name in names:
                for _k, off, ln in self._feeds.get(name, ()):
                    start = off - (off % page)
                    try:
                        mm.madvise(
                            mmap.MADV_WILLNEED, start, off + ln - start
                        )
                    except (OSError, ValueError):
                        # advisory only: a transient per-extent failure
                        # (ENOMEM/EAGAIN) must not abandon the hints
                        # for the rest of the chunk
                        continue

    def image_bytes(self, name: str) -> bytes:
        """The feed's sidecar image in FileColumnStorageV2 byte format:
        live image segment + record segments, concatenated. One mmap
        slice per segment — the cold-open common case is exactly one."""
        with self._lock:
            self._ensure_loaded()
            segs = self._feeds.get(name)
            if not segs:
                return b""
            mm = self._mapped()
            if mm is None:
                return b""
            if len(segs) == 1:
                _k, off, ln = segs[0]
                return mm[off : off + ln]
            return b"".join(mm[off : off + ln] for _k, off, ln in segs)

    # -- writes ---------------------------------------------------------

    def _writable(self):
        if self._fh is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            fresh = not os.path.exists(self.path)
            self._fh = io_open(self.path, "w+b" if fresh else "r+b")
            if fresh:
                self._fh.write(_HDR.pack(_MAGIC, _VERSION))
                self._fh.flush()
                self._end = self._fh.tell()
            self._idx_fh = io_open(self.idx_path, "ab")
        return self._fh

    def append(self, kind: int, name: str, payload: bytes) -> None:
        with self._lock:
            self._ensure_loaded()
            nb = name.encode("ascii")
            head = _SEG.pack(kind, len(nb)) + nb + _LEN.pack(len(payload))
            # exception safety under mid-write ENOSPC/EIO: in-memory
            # extents (_apply) only advance after the whole segment is
            # on disk, and a failed write drops the persistent handles
            # (their buffers may hold torn bytes in an ambiguous state)
            # — the next append reopens, seeks the unchanged _end, and
            # overwrites the torn tail, exactly like a crash would heal
            try:
                fh = self._writable()
                fh.seek(self._end)  # overwrite any torn tail
                fh.write(head)
                fh.write(payload)
                fh.truncate()
                fh.flush()
            except OSError:
                self._close_files()
                raise
            off = self._end + len(head)
            self._apply(kind, name, off, len(payload))
            if self._mm is not None:
                self._mm.close()  # stale mapping: remap on next read
                self._mm = None
                self._mm_size = 0
            self._append_idx(kind, name, off, len(payload))

    def _append_idx(self, kind, name, off, ln) -> None:
        # the index is advisory: a failed/torn idx append just means the
        # next open repairs forward from the slab's segment headers
        try:
            if self._idx_fh is None:
                self._idx_fh = io_open(self.idx_path, "ab")
            nb = name.encode("ascii")
            self._idx_fh.write(
                _SEG.pack(kind, len(nb)) + nb + struct.pack("<QQ", off, ln)
            )
            self._idx_fh.flush()
        except OSError as e:
            log("storage:slab", f"idx append failed {self.idx_path}: {e}")
            if self._idx_fh is not None:
                try:
                    self._idx_fh.close()
                except OSError:
                    pass
                self._idx_fh = None

    def _rewrite_idx(self) -> None:
        # entries MUST be offset-ordered: _read_index treats any
        # non-monotonic offset as corruption (a feed-grouped dump of
        # interleaved segments would fail that check on every open)
        entries = sorted(
            (off, ln, kind, name)
            for name, segs in self._feeds.items()
            for kind, off, ln in segs
        )
        tmp = self.idx_path + ".tmp"
        with io_open(tmp, "wb") as fh:
            for off, ln, kind, name in entries:
                nb = name.encode("ascii")
                fh.write(
                    _SEG.pack(kind, len(nb))
                    + nb
                    + struct.pack("<QQ", off, ln)
                )
        io_replace(tmp, self.idx_path)

    # -- lifecycle ------------------------------------------------------

    def compact(self, force: bool = False) -> bool:
        """Rewrite the slab keeping only live segments. Returns True when
        a rewrite happened. Without `force`, only when the dead fraction
        exceeds HM_SLAB_SLACK (and at least 4KB of dead bytes)."""
        with self._lock:
            self._ensure_loaded()
            if not os.path.exists(self.path):
                return False
            dead = self._end - len(_HDR.pack(_MAGIC, _VERSION)) - (
                self._live_bytes
            )
            if not force and (
                dead < 4096
                or dead < _slack_fraction() * max(self._end, 1)
            ):
                return False
            mm = self._mapped()
            if mm is None:
                return False
            tmp = self.path + ".tmp"
            new_feeds: Dict[str, List[Tuple[int, int, int]]] = {}
            with io_open(tmp, "wb") as fh:
                fh.write(_HDR.pack(_MAGIC, _VERSION))
                for name, segs in self._feeds.items():
                    if not segs:
                        continue  # tombstoned: simply absent after rewrite
                    nb = name.encode("ascii")
                    out = []
                    for kind, off, ln in segs:
                        head = _SEG.pack(kind, len(nb)) + nb + _LEN.pack(ln)
                        fh.write(head)
                        fh.write(mm[off : off + ln])
                        out.append((kind, fh.tell() - ln, ln))
                    new_feeds[name] = out
                fh.flush()
                io_fsync(fh)
                new_end = fh.tell()
            self._close_files()
            io_replace(tmp, self.path)
            self._feeds = new_feeds
            self._end = new_end
            self._live_bytes = new_end - len(_HDR.pack(_MAGIC, _VERSION))
            self._rewrite_idx()
            return True

    def _close_files(self) -> None:
        if self._mm is not None:
            self._mm.close()
            self._mm = None
            self._mm_size = 0
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._idx_fh is not None:
            self._idx_fh.close()
            self._idx_fh = None

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._loaded:
                try:
                    self.compact()
                except OSError:
                    pass  # read-only media: slack stays until writable
            self._close_files()

    def destroy(self) -> None:
        with self._lock:
            self._close_files()
            for p in (self.path, self.idx_path):
                if os.path.exists(p):
                    io_remove(p)
            self._feeds = {}
            self._loaded = True
            self._end = len(_HDR.pack(_MAGIC, _VERSION))
            self._live_bytes = 0
