"""Durability tiers for the feed write path (HM_FSYNC).

The hot append path was historically flush()-only: an acknowledged
local edit reached the OS page cache but never the platter, so a power
cut could drop acked writes (a kill -9 could not — the page cache
outlives the process). HM_FSYNC picks the trade:

  HM_FSYNC=0  (default) no fsync on the append path. Crash-SAFE but
              not crash-DURABLE: every format heals torn tails and
              recovery-on-open (storage/scrub.py) reconciles sqlite
              against feed reality, so a crash never corrupts — it can
              only lose the unfsynced tail.
  HM_FSYNC=1  batched group fsync: appends mark their storage dirty
              and a debounced flusher (HM_FSYNC_MS, default 25ms)
              fsyncs every dirty feed log — one fsync per log per
              window, not per append. An acked write is durable within
              one window (or at the next sqlite store flush, whose
              barrier syncs feeds FIRST — see below).
  HM_FSYNC=2  the append is durable when the call returns.

With the shared journal attached (HM_WAL=1, storage/wal.py — the
file-backed default), BOTH durable tiers commit through it instead of
fsyncing per-feed logs: tier 1's window fsyncs the JOURNAL once
(O(1), not O(dirty feeds)); tier 2 rides the journal's leader/
follower group commit, so concurrent writers on different docs share
one fsync. The per-feed logs are fsynced only at checkpoint, off the
ack path; recovery replays the journal prefix. HM_WAL=0 restores the
legacy per-feed behavior below verbatim.

Ordering invariants (the recoverable direction):
  - feed log fsync happens BEFORE the .len/index sidecar describes it
    (a sidecar ahead of the log is detected by the size check and
    rescanned; the log is never behind what the sidecar promises).
  - sqlite clock/cursor commits never land ahead of durable feed
    bytes: the store flusher calls `barrier()` before committing, so
    under tiers 1/2 a clock row can only describe blocks that are
    already on the platter. (Tier 0 relies on recovery-on-open
    clamping clock rows back to feed reality instead.)

Sidecars (columnar slab, signature records) stay flush-only at every
tier: they are derived data — blocks are the source of truth and every
sidecar format detects-and-rebuilds on mismatch.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Optional, Set

from ..analysis.lockdep import make_lock
from ..utils.debug import log
from .. import telemetry

# storage durability counters (process registry): fsync passes, the
# storages they synced, failures, and pre-sqlite barriers — the
# "is durability keeping up" view for HM_FSYNC=1 daemons
_M_SYNCS = telemetry.counter("storage.fsyncs")
_M_SYNC_ERRS = telemetry.counter("storage.fsync_errors")
_M_BARRIERS = telemetry.counter("storage.barriers")


def fsync_tier() -> int:
    try:
        return int(os.environ.get("HM_FSYNC", "0"))
    except ValueError:
        return 0


def _flush_window_s() -> float:
    return float(os.environ.get("HM_FSYNC_MS", "25")) / 1e3


class DurabilityManager:
    """Owns the dirty-set + group-fsync flusher for tier 1 and the
    pre-sqlite barrier for every tier. Storages call `mark_dirty(self)`
    after an unfsynced append; anything with a `.sync()` method works.
    The flusher thread starts lazily on the first dirty mark (tier 0
    and tier 2 never pay for it)."""

    def __init__(self) -> None:
        self._lock = make_lock("store.durability")
        self._dirty: Set = set()
        self._flusher = None
        self._closed = False
        # the shared group-commit journal (storage/wal.py), attached
        # by the RepoBackend after recovery consumed the previous
        # session's journal; None = legacy per-feed durability
        self.wal = None
        # recovery replay suspends journaling: replayed blocks COME
        # from the journal (single-threaded, scrub-only window)
        self._wal_suspended = 0
        # fired ONCE on the first journal-less feed write, when set
        # (RepoBackend, HM_RECOVER=0 sessions): a preserved crash
        # stamp must stop bounding recovery once writes land outside
        # the preserved journal's ledger
        self.journalless_write_cb = None

    @property
    def tier(self) -> int:
        return fsync_tier()

    @property
    def ack_durable(self) -> bool:
        """HM_ACK_DURABLE=1: a local edit's ack (the LocalPatch echo)
        waits for the WAL group commit at tier 1 — durable acks at
        group-fsync cost. Tier 2 acks are already durable; tier 0 has
        no durability to wait for."""
        return os.environ.get("HM_ACK_DURABLE", "0") == "1"

    def attach_wal(self, wal) -> None:
        with self._lock:
            self.wal = wal

    @contextmanager
    def suspended(self):
        """Journaling off for the caller's block (recovery replay)."""
        self._wal_suspended += 1
        try:
            yield
        finally:
            self._wal_suspended -= 1

    def journal_append(self, path: str, index: int, data: bytes,
                       storage) -> bool:
        """Route one feed-block append through the shared journal.
        True = the journal owns durability for this block (the caller
        skips its per-feed fsync/mark); False = legacy path (no WAL,
        tier 0 ledger-only, or a broken journal)."""
        wal = self.wal
        if wal is None or self._wal_suspended:
            if wal is None and not self._wal_suspended:
                cb = self.journalless_write_cb
                if cb is not None:
                    self.journalless_write_cb = None
                    cb()
            return False
        name = os.path.basename(path)
        tier = self.tier
        if tier < 1:
            # tier 0 never fsyncs — but the dirty-name ledger still
            # bounds a kill -9 recovery's scan
            wal.note_dirty(name, storage)
            return False
        end = wal.append(name, index, data, storage)
        if end is None:
            return False
        if tier >= 2:
            wal.commit(end)  # the leader/follower group fsync
        else:
            self.mark_dirty(wal)  # ONE journal fsync per window
        return True

    def commit_ack(self) -> None:
        """The durable-ack barrier (HM_ACK_DURABLE=1, tier 1): block
        until everything journaled so far — including the caller's
        just-appended block — is on the platter. Riders share the
        leader's ONE fsync (storage/wal.py group commit, HM_WAL_MS
        gather window), so N concurrent writers' durable acks cost one
        journal fsync per window, not N. Without a journal (HM_WAL=0)
        this degrades to the legacy O(dirty feeds) barrier — and the
        journal fsync only vouches for blocks it JOURNALED: an append
        that fell back to the legacy path (transient journal write
        error, broken journal) was mark_dirty'd instead, so any
        non-journal dirty storage forces the legacy barrier too."""
        wal = self.wal
        if wal is not None and not self._wal_suspended:
            try:
                wal.sync()
            except OSError:
                # journal closed/broken without covering the append:
                # the bytes live in the feed logs — fsync those
                self.barrier()
                return
            with self._lock:
                legacy = any(s is not wal for s in self._dirty)
            if legacy:
                self.barrier()
        else:
            self.barrier()

    def mark_dirty(self, storage) -> None:
        if self.tier < 1:
            return
        with self._lock:
            if self._closed:
                return
            self._dirty.add(storage)
            if self._flusher is None:
                from ..utils.debounce import Debouncer

                self._flusher = Debouncer(
                    lambda _batch: self.sync_now(),
                    window_s=_flush_window_s(),
                    name="fsync",
                )
            self._flusher.mark("sync")

    def sync_now(self) -> int:
        """Group-fsync every dirty storage now. Returns the number
        synced. A storage whose sync fails stays dirty — and the
        flusher is re-marked so the retry does not wait for an
        unrelated append (ENOSPC/EIO on fsync must not silently drop
        durability). The FIRST failure re-raises after the pass so
        callers that gate on durability (barrier) see it."""
        with self._lock:
            dirty = list(self._dirty)
            self._dirty.clear()
        n = 0
        first_err: Optional[OSError] = None
        sp = (
            telemetry.begin("storage.fsync_group", "storage",
                            n=len(dirty))
            if dirty
            else telemetry.NOOP
        )
        try:
            for s in dirty:
                try:
                    s.sync()
                    n += 1
                except OSError as e:
                    log("storage:durability", f"sync failed: {e}")
                    _M_SYNC_ERRS.add(1)
                    if first_err is None:
                        first_err = e
                    with self._lock:
                        if not self._closed:
                            self._dirty.add(s)
                            if self._flusher is not None:
                                self._flusher.mark("sync")
        finally:
            # a non-OSError escaping a sync (ValueError from a closed
            # file) must not drop the span or the already-synced count
            sp.end()
            _M_SYNCS.add(n)
        if first_err is not None:
            raise first_err
        return n

    def barrier(self) -> None:
        """Make every dirty feed durable BEFORE the caller commits
        sqlite rows describing it (clocks-ahead-of-feeds is the
        direction recovery cannot undo without truncating history).
        RAISES on a failed fsync: the caller must NOT commit rows for
        bytes that never reached the platter — the store debouncer
        re-queues the batch and retries with backoff."""
        _M_BARRIERS.add(1)
        if self.tier >= 1:
            self.sync_now()

    def flush_now(self, timeout: float = 5.0) -> bool:
        """Settle the tier-1 flusher (tests/bench ack barrier)."""
        f = self._flusher
        if f is not None and not f.flush_now(timeout):
            return False
        self.sync_now()
        return True

    def close(self) -> bool:
        """Final drain. Returns True when everything dirty reached the
        platter — the backend only marks the repo CLEAN (removes the
        crash marker) on a True close; a failed final sync leaves the
        marker so the next open runs recovery."""
        with self._lock:
            self._closed = True
            f = self._flusher
            self._flusher = None
        if f is not None:
            f.close()
        # final drain: anything still dirty gets one last sync
        with self._lock:
            dirty = list(self._dirty)
            self._dirty.clear()
            wal = self.wal
        clean = True
        for s in dirty:
            try:
                s.sync()
            except OSError as e:
                log("storage:durability", f"close sync failed: {e}")
                clean = False
        if wal is not None:
            # final checkpoint: per-feed logs durable, journal reset —
            # a clean close leaves nothing to replay
            clean = wal.close() and clean
        return clean
