"""Durability tiers for the feed write path (HM_FSYNC).

The hot append path was historically flush()-only: an acknowledged
local edit reached the OS page cache but never the platter, so a power
cut could drop acked writes (a kill -9 could not — the page cache
outlives the process). HM_FSYNC picks the trade:

  HM_FSYNC=0  (default) no fsync on the append path. Crash-SAFE but
              not crash-DURABLE: every format heals torn tails and
              recovery-on-open (storage/scrub.py) reconciles sqlite
              against feed reality, so a crash never corrupts — it can
              only lose the unfsynced tail.
  HM_FSYNC=1  batched group fsync: appends mark their storage dirty
              and a debounced flusher (HM_FSYNC_MS, default 25ms)
              fsyncs every dirty feed log — one fsync per log per
              window, not per append. An acked write is durable within
              one window (or at the next sqlite store flush, whose
              barrier syncs feeds FIRST — see below).
  HM_FSYNC=2  fsync per append, before the .len sidecar write: an
              acked append is durable when the call returns.

Ordering invariants (the recoverable direction):
  - feed log fsync happens BEFORE the .len/index sidecar describes it
    (a sidecar ahead of the log is detected by the size check and
    rescanned; the log is never behind what the sidecar promises).
  - sqlite clock/cursor commits never land ahead of durable feed
    bytes: the store flusher calls `barrier()` before committing, so
    under tiers 1/2 a clock row can only describe blocks that are
    already on the platter. (Tier 0 relies on recovery-on-open
    clamping clock rows back to feed reality instead.)

Sidecars (columnar slab, signature records) stay flush-only at every
tier: they are derived data — blocks are the source of truth and every
sidecar format detects-and-rebuilds on mismatch.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Set

from ..analysis.lockdep import make_lock
from ..utils.debug import log
from .. import telemetry

# storage durability counters (process registry): fsync passes, the
# storages they synced, failures, and pre-sqlite barriers — the
# "is durability keeping up" view for HM_FSYNC=1 daemons
_M_SYNCS = telemetry.counter("storage.fsyncs")
_M_SYNC_ERRS = telemetry.counter("storage.fsync_errors")
_M_BARRIERS = telemetry.counter("storage.barriers")


def fsync_tier() -> int:
    try:
        return int(os.environ.get("HM_FSYNC", "0"))
    except ValueError:
        return 0


def _flush_window_s() -> float:
    return float(os.environ.get("HM_FSYNC_MS", "25")) / 1e3


class DurabilityManager:
    """Owns the dirty-set + group-fsync flusher for tier 1 and the
    pre-sqlite barrier for every tier. Storages call `mark_dirty(self)`
    after an unfsynced append; anything with a `.sync()` method works.
    The flusher thread starts lazily on the first dirty mark (tier 0
    and tier 2 never pay for it)."""

    def __init__(self) -> None:
        self._lock = make_lock("store.durability")
        self._dirty: Set = set()
        self._flusher = None
        self._closed = False

    @property
    def tier(self) -> int:
        return fsync_tier()

    def mark_dirty(self, storage) -> None:
        if self.tier < 1:
            return
        with self._lock:
            if self._closed:
                return
            self._dirty.add(storage)
            if self._flusher is None:
                from ..utils.debounce import Debouncer

                self._flusher = Debouncer(
                    lambda _batch: self.sync_now(),
                    window_s=_flush_window_s(),
                    name="fsync",
                )
            self._flusher.mark("sync")

    def sync_now(self) -> int:
        """Group-fsync every dirty storage now. Returns the number
        synced. A storage whose sync fails stays dirty — and the
        flusher is re-marked so the retry does not wait for an
        unrelated append (ENOSPC/EIO on fsync must not silently drop
        durability). The FIRST failure re-raises after the pass so
        callers that gate on durability (barrier) see it."""
        with self._lock:
            dirty = list(self._dirty)
            self._dirty.clear()
        n = 0
        first_err: Optional[OSError] = None
        sp = (
            telemetry.begin("storage.fsync_group", "storage",
                            n=len(dirty))
            if dirty
            else telemetry.NOOP
        )
        try:
            for s in dirty:
                try:
                    s.sync()
                    n += 1
                except OSError as e:
                    log("storage:durability", f"sync failed: {e}")
                    _M_SYNC_ERRS.add(1)
                    if first_err is None:
                        first_err = e
                    with self._lock:
                        if not self._closed:
                            self._dirty.add(s)
                            if self._flusher is not None:
                                self._flusher.mark("sync")
        finally:
            # a non-OSError escaping a sync (ValueError from a closed
            # file) must not drop the span or the already-synced count
            sp.end()
            _M_SYNCS.add(n)
        if first_err is not None:
            raise first_err
        return n

    def barrier(self) -> None:
        """Make every dirty feed durable BEFORE the caller commits
        sqlite rows describing it (clocks-ahead-of-feeds is the
        direction recovery cannot undo without truncating history).
        RAISES on a failed fsync: the caller must NOT commit rows for
        bytes that never reached the platter — the store debouncer
        re-queues the batch and retries with backoff."""
        _M_BARRIERS.add(1)
        if self.tier >= 1:
            self.sync_now()

    def flush_now(self, timeout: float = 5.0) -> bool:
        """Settle the tier-1 flusher (tests/bench ack barrier)."""
        f = self._flusher
        if f is not None and not f.flush_now(timeout):
            return False
        self.sync_now()
        return True

    def close(self) -> bool:
        """Final drain. Returns True when everything dirty reached the
        platter — the backend only marks the repo CLEAN (removes the
        crash marker) on a True close; a failed final sync leaves the
        marker so the next open runs recovery."""
        with self._lock:
            self._closed = True
            f = self._flusher
            self._flusher = None
        if f is not None:
            f.close()
        # final drain: anything still dirty gets one last sync
        with self._lock:
            dirty = list(self._dirty)
            self._dirty.clear()
        clean = True
        for s in dirty:
            try:
                s.sync()
            except OSError as e:
                log("storage:durability", f"close sync failed: {e}")
                clean = False
        return clean
