"""Columnar feed cache — the vectorized cold-start sidecar.

The reference cold start replays every change through the CRDT backend
one block at a time (reference src/RepoBackend.ts:238-257 loadDocument →
Backend.applyChanges). The TPU-first equivalent wants feeds to arrive on
device as int32 columns with zero per-op Python. This module maintains,
next to each feed's block log, a derived columnar encoding of the same
ops that can be loaded with a single `np.fromfile` and sliced/remapped
with numpy only (ops/columnar.py `pack_docs_columns`).

The cache is *derived data*: the JSON change blocks in the feed remain
the source of truth (and the replication wire format). A missing or
stale cache is rebuilt from blocks; a torn tail (crash mid-append) is
truncated to the last committed change, mirroring the torn-tail healing
of FileFeedStorage (storage/feed.py).

Row layout (int32 x ROW_FIELDS per op):
  0 action   Action code
  1 ctr      lamport counter (op id = (ctr, writer))
  2 seq      change seq (nondecreasing -> np.searchsorted windows)
  3 start_op ctr of the change's first op (causal sort key)
  4 obj_ctr  container op id ctr        (0 if root)
  5 obj_a    feed-local actor idx of container (-1 = ROOT map)
  6 key      feed-local key-string idx (-1 = none / list op)
  7 ref_ctr  referenced element / INC target ctr
  8 ref_a    feed-local actor idx (-2 = HEAD, -3 = none)
  9 insert   1 if the op creates a list/text element
 10 vkind    value kind (ops/columnar.py VK_*)
 11 value    inline int / feed-local table idx
 12 dt       datatype: 0 none, 1 counter, 2 timestamp
 13 flags    reserved

Pred (supersession) edges are separate records (int32 x 3):
  src op index (absolute, within this feed), tgt_ctr, tgt_a.
INC ops contribute no pred edges — their target rides ref_* (matching
ops/columnar.py _pack_one).

Tables are append-only JSON lines: {"t": "a"|"k"|"s"|"f"|"b", "v": ...}
("a" actors — index 0 is always the feed writer; "k" key strings;
"s" value strings; "f" floats; "b" bigints as decimal strings).

A commit record (int32 x 4: n_rows, n_preds, n_table_lines, flag) is
appended **after** each change's data; load() honors only the last
complete commit, so a torn append never corrupts the cache. flag=1
marks a corrupt feed block (occupies a seq slot, contributes no ops) —
needed because the host OpSet stalls an actor's changes at the first
corrupt block (seq continuity), so `ok_prefix_len` clamps windows.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.lockdep import make_rlock
from ..crdt.change import HEAD, ROOT, Action, Change
from .faults import io_fsync, io_open, io_remove, io_replace

ROW_FIELDS = 14
PRED_FIELDS = 3
COMMIT_FIELDS = 4

# value kinds — must match ops/columnar.py
VK_NONE = 0
VK_INT = 1
VK_FLOAT = 2
VK_STR = 3
VK_BOOL = 4
VK_BIGINT = 5

_INT32_MIN, _INT32_MAX = -(2**31), 2**31 - 1

OBJ_ROOT = -1
REF_HEAD = -2
REF_NONE = -3


# plane order == row column order (module docstring row layout)
PLANE_NAMES = (
    "action", "ctr", "seq", "start_op", "obj_ctr", "obj_a", "key",
    "ref_ctr", "ref_a", "insert", "vkind", "value", "dt", "flags",
)


@dataclass
class FeedColumns:
    """One feed's ops as numpy columns + feed-local tables.

    Two storage shapes, one interface: `rows` is [n_ops, ROW_FIELDS]
    int32 (v2 record streams materialize it directly); a v3 checkpoint
    instead carries `planes` — one contiguous array per column in the
    minimal dtype that holds it — and leaves `rows` None until a
    consumer calls `ensure_rows()`. The bulk pack fast path
    (ops/columnar.py) reads planes without ever widening to the row
    matrix; everything else upgrades transparently.

    `preds` is [n_preds, 3] int32. `seq` is nondecreasing, so change
    windows slice via np.searchsorted. `ok_prefix_len` is the number of
    leading non-corrupt changes — the host OpSet can never apply past
    the first corrupt block of an actor, so bulk windows clamp to it.
    """

    rows: Optional[np.ndarray]
    preds: np.ndarray
    actors: List[str]
    keys: List[str]
    strings: List[str]
    floats: List[float]
    bigints: List[int]
    n_changes: int
    ok_prefix_len: int
    # per-change cumulative row counts, len n_changes+1: change i (seq
    # i+1) owns rows [row_ends[i], row_ends[i+1])
    row_ends: np.ndarray
    planes: Optional[Dict[str, np.ndarray]] = None
    # (base_addr, offsets[len(PLANE_NAMES)] int64, dtype_codes uint8,
    # keep_alive) when every plane is a slice of ONE raw checkpoint
    # buffer: the native bulk pack derives all plane pointers from the
    # base address instead of a per-plane __array_interface__ walk
    # (which costs ~5us x 12 planes x 10k feeds on a cold open)
    plane_meta: Optional[Tuple] = None

    @property
    def n_rows(self) -> int:
        if self.rows is not None:
            return len(self.rows)
        return len(self.planes["action"]) if self.planes else 0

    def plane(self, name: str) -> np.ndarray:
        """One column, narrow dtype when plane-backed."""
        if self.planes is not None:
            return self.planes[name]
        return self.rows[:, PLANE_NAMES.index(name)]

    def ensure_rows(self) -> np.ndarray:
        """Materialize (and cache) the [n, ROW_FIELDS] int32 matrix —
        the general pack path and per-op consumers want row slices."""
        if self.rows is None:
            self.rows = rows_from_planes(self.planes)
        return self.rows

    @property
    def seq(self) -> np.ndarray:
        if self.rows is not None:
            return self.rows[:, 2]
        return self.plane("seq")

    def window(self, start_seq: int, end_seq: float) -> Tuple[int, int]:
        """Row range [lo, hi) for changes with seq in (start_seq, end_seq],
        clamped to the applicable (ok) prefix."""
        e = min(float(end_seq), float(self.ok_prefix_len))
        e = int(e)
        s = min(start_seq, self.n_changes)
        lo = int(self.row_ends[s])
        hi = int(self.row_ends[min(e, self.n_changes)]) if e > 0 else 0
        return lo, max(hi, lo)

    def changes_in_window(self, start_seq: int, end_seq: float) -> int:
        """Number of applicable changes with seq in (start_seq, end_seq]."""
        e = int(min(float(end_seq), float(self.ok_prefix_len)))
        return max(0, e - min(start_seq, e))

    def seqs_contiguous(self) -> bool:
        """True iff the rows' seq column matches the contiguous 1..n
        assignment (change i owns seq i+1). The bulk clock shortcut
        (clock[actor] = applied-change count) is only sound under this
        invariant; a feed with a seq gap — e.g. partially replicated or
        corrupt-then-healed out-of-band — must fail loudly, not produce a
        silently wrong clock."""
        n = int(self.row_ends[-1]) if len(self.row_ends) else 0
        if n != self.n_rows:
            return False
        expected = np.repeat(
            np.arange(1, self.n_changes + 1, dtype=np.int64),
            np.diff(self.row_ends),
        )
        return bool(
            np.array_equal(self.seq[:n].astype(np.int64), expected)
        )


# ---------------------------------------------------------------------------
# storage backends


class MemoryColumnStorage:
    def __init__(self) -> None:
        self.rows: List[np.ndarray] = []
        self.preds: List[np.ndarray] = []
        self.tables: List[str] = []
        self.commits: List[Tuple[int, int, int, int]] = []

    def commit_change(
        self,
        rows: np.ndarray,
        preds: np.ndarray,
        table_lines: List[str],
        flag: int,
    ) -> None:
        if len(rows):
            self.rows.append(rows)
        if len(preds):
            self.preds.append(preds)
        self.tables.extend(table_lines)
        n_rows = sum(len(r) for r in self.rows)
        n_preds = sum(len(p) for p in self.preds)
        self.commits.append((n_rows, n_preds, len(self.tables), flag))

    def load(self):
        rows = (
            np.concatenate(self.rows, axis=0)
            if self.rows
            else np.zeros((0, ROW_FIELDS), np.int32)
        )
        preds = (
            np.concatenate(self.preds, axis=0)
            if self.preds
            else np.zeros((0, PRED_FIELDS), np.int32)
        )
        commits = np.asarray(self.commits, np.int32).reshape(
            -1, COMMIT_FIELDS
        )
        return rows, preds, list(self.tables), commits

    def reset(self) -> None:
        self.rows.clear()
        self.preds.clear()
        self.tables.clear()
        self.commits.clear()

    def destroy(self) -> None:
        self.reset()

    def close(self) -> None:
        pass


class FileColumnStorage:
    """rows.bin / preds.bin / tables.jsonl / commits.bin in a directory.

    Only the prefix covered by the last complete commit record is ever
    read back — a crash mid-append loses at most the uncommitted change,
    which the rebuild path re-derives from the feed's blocks."""

    _COMMIT = struct.Struct("<4i")

    def __init__(self, path: str) -> None:
        self.path = path
        self._dir_ready = os.path.isdir(path)
        self._fhs = None  # (rows, preds, tables, commits) — lazy: a
        # read-only bulk load over many feeds must not hold 4 FDs each
        self._n_rows: Optional[int] = None
        self._n_preds: Optional[int] = None
        self._n_tables_written: Optional[int] = None

    def _ensure_writable(self):
        if self._fhs is not None:
            return self._fhs
        if not self._dir_ready:
            os.makedirs(self.path, exist_ok=True)
            self._dir_ready = True
        self._truncate_to_committed()
        self._fhs = (
            open(os.path.join(self.path, "rows.bin"), "ab"),
            open(os.path.join(self.path, "preds.bin"), "ab"),
            open(os.path.join(self.path, "tables.jsonl"), "ab"),
            open(os.path.join(self.path, "commits.bin"), "ab"),
        )
        self._n_rows = os.path.getsize(
            os.path.join(self.path, "rows.bin")
        ) // (4 * ROW_FIELDS)
        self._n_preds = os.path.getsize(
            os.path.join(self.path, "preds.bin")
        ) // (4 * PRED_FIELDS)
        self._n_tables_written = self._count_table_lines()
        return self._fhs

    def _truncate_to_committed(self) -> None:
        """Drop any torn tail from a crash mid-append: the data files are
        rolled back to the sizes the last complete commit record names
        (the lost change re-derives from its feed block on catch-up)."""
        cpath = os.path.join(self.path, "commits.bin")
        csize = (
            os.path.getsize(cpath) if os.path.exists(cpath) else 0
        )
        n_commits = csize // self._COMMIT.size
        if csize != n_commits * self._COMMIT.size:
            with open(cpath, "r+b") as fh:
                fh.truncate(n_commits * self._COMMIT.size)
        if n_commits:
            with open(cpath, "rb") as fh:
                fh.seek((n_commits - 1) * self._COMMIT.size)
                n_rows, n_preds, n_tables, _ = self._COMMIT.unpack(
                    fh.read(self._COMMIT.size)
                )
        else:
            n_rows = n_preds = n_tables = 0
        for name, want in (
            ("rows.bin", n_rows * 4 * ROW_FIELDS),
            ("preds.bin", n_preds * 4 * PRED_FIELDS),
        ):
            p = os.path.join(self.path, name)
            if os.path.exists(p) and os.path.getsize(p) > want:
                with open(p, "r+b") as fh:
                    fh.truncate(want)
        tp = os.path.join(self.path, "tables.jsonl")
        if os.path.exists(tp):
            keep = 0
            count = 0
            with open(tp, "rb") as fh:
                for line in fh:
                    if count >= n_tables or not line.endswith(b"\n"):
                        break
                    count += 1
                    keep += len(line)
            if os.path.getsize(tp) > keep:
                with open(tp, "r+b") as fh:
                    fh.truncate(keep)

    def commit_change(
        self,
        rows: np.ndarray,
        preds: np.ndarray,
        table_lines: List[str],
        flag: int,
    ) -> None:
        rows_fh, preds_fh, tables_fh, commits_fh = self._ensure_writable()
        if len(rows):
            rows_fh.write(np.ascontiguousarray(rows, np.int32).tobytes())
            rows_fh.flush()
            self._n_rows += len(rows)
        if len(preds):
            preds_fh.write(np.ascontiguousarray(preds, np.int32).tobytes())
            preds_fh.flush()
            self._n_preds += len(preds)
        for line in table_lines:
            tables_fh.write(line.encode("utf-8") + b"\n")
        if table_lines:
            tables_fh.flush()
            self._n_tables_written += len(table_lines)
        commits_fh.write(
            self._COMMIT.pack(
                self._n_rows, self._n_preds, self._n_tables_written, flag
            )
        )
        commits_fh.flush()

    def _count_table_lines(self) -> int:
        p = os.path.join(self.path, "tables.jsonl")
        if not os.path.exists(p):
            return 0
        with open(p, "rb") as fh:
            return sum(1 for _ in fh)

    def load(self):
        commits_raw = self._read(os.path.join(self.path, "commits.bin"))
        n_complete = len(commits_raw) // self._COMMIT.size
        commits = np.frombuffer(
            commits_raw[: n_complete * self._COMMIT.size], np.int32
        ).reshape(-1, COMMIT_FIELDS)
        n_rows = int(commits[-1, 0]) if n_complete else 0
        n_preds = int(commits[-1, 1]) if n_complete else 0
        n_tables = int(commits[-1, 2]) if n_complete else 0
        rows_raw = self._read(os.path.join(self.path, "rows.bin"))
        rows = np.frombuffer(
            rows_raw[: n_rows * 4 * ROW_FIELDS], np.int32
        ).reshape(-1, ROW_FIELDS)
        preds_raw = self._read(os.path.join(self.path, "preds.bin"))
        preds = np.frombuffer(
            preds_raw[: n_preds * 4 * PRED_FIELDS], np.int32
        ).reshape(-1, PRED_FIELDS)
        tables: List[str] = []
        tp = os.path.join(self.path, "tables.jsonl")
        if os.path.exists(tp) and n_tables:
            with open(tp, "rb") as fh:
                for line in fh:
                    tables.append(line.decode("utf-8").rstrip("\n"))
                    if len(tables) >= n_tables:
                        break
        return rows, preds, tables, commits

    @staticmethod
    def _read(path: str) -> bytes:
        if not os.path.exists(path):
            return b""
        with open(path, "rb") as fh:
            return fh.read()

    def reset(self) -> None:
        """Discard all cache contents (used when the sidecar disagrees
        with its feed — e.g. a restored/replaced feed file left the
        sidecar ahead of the block log)."""
        self.close()
        for name in ("rows.bin", "preds.bin", "tables.jsonl", "commits.bin"):
            p = os.path.join(self.path, name)
            if os.path.exists(p):
                os.remove(p)
        self._n_rows = self._n_preds = self._n_tables_written = None

    def destroy(self) -> None:
        """reset + remove the sidecar directory itself (doc destroy)."""
        self.reset()
        try:
            os.rmdir(self.path)
        except OSError:
            pass
        self._dir_ready = False

    def close(self) -> None:
        if self._fhs is not None:
            for fh in self._fhs:
                fh.close()
            self._fhs = None


_V2_HDR = struct.Struct("<IIIB")


_V3_MAGIC = b"HMc3"
_V3_HDR = struct.Struct("<IIII")  # n_rows, n_changes, n_preds, tables_len
_V3_DTYPES = (np.int8, np.int16, np.int32, np.uint8)


def _narrow_plane(col: np.ndarray) -> np.ndarray:
    """Minimal-dtype copy of one int32 column."""
    if len(col) == 0:
        return col.astype(np.int8)
    lo, hi = int(col.min()), int(col.max())
    if 0 <= lo and hi <= 255:
        return col.astype(np.uint8)
    if -128 <= lo and hi <= 127:
        return col.astype(np.int8)
    if -(2**15) <= lo and hi <= 2**15 - 1:
        return col.astype(np.int16)
    return np.ascontiguousarray(col, np.int32)


def planes_from_rows(rows: np.ndarray) -> Dict[str, np.ndarray]:
    return {
        name: _narrow_plane(rows[:, i])
        for i, name in enumerate(PLANE_NAMES)
    }


def rows_from_planes(planes: Dict[str, np.ndarray]) -> np.ndarray:
    n = len(planes["action"])
    rows = np.empty((n, ROW_FIELDS), np.int32)
    for i, name in enumerate(PLANE_NAMES):
        rows[:, i] = planes[name]
    return rows


def v3_body_bytes(
    planes: Dict[str, np.ndarray],
    preds: np.ndarray,
    row_ends: np.ndarray,
    flags: np.ndarray,
) -> bytes:
    """Everything between the v3 header and the tables blob — the
    doc-invariant middle the corpus writer renders once per template."""
    n_changes = len(row_ends)
    n_rows = int(row_ends[-1]) if n_changes else 0
    parts = []
    for name in PLANE_NAMES:
        p = planes[name]
        assert len(p) == n_rows, (name, len(p), n_rows)
        parts.append(bytes([_V3_DTYPES.index(p.dtype.type)]))
        parts.append(np.ascontiguousarray(p).tobytes())
    parts.append(np.ascontiguousarray(row_ends, np.int64).tobytes())
    parts.append(np.ascontiguousarray(flags, np.uint8).tobytes())
    parts.append(np.ascontiguousarray(preds, np.int32).tobytes())
    return b"".join(parts)


def v3_frame(
    body: bytes,
    n_rows: int,
    n_changes: int,
    n_preds: int,
    tables_bytes: bytes,
) -> bytes:
    return b"".join(
        (
            _V3_MAGIC,
            _V3_HDR.pack(n_rows, n_changes, n_preds, len(tables_bytes)),
            body,
            tables_bytes,
        )
    )


def pack_v3_checkpoint(
    planes: Dict[str, np.ndarray],
    preds: np.ndarray,
    row_ends: np.ndarray,
    flags: np.ndarray,
    tables_bytes: bytes,
) -> bytes:
    """The v3 checkpoint block: the whole committed prefix as contiguous
    column planes (minimal dtypes) + preds + per-change row ends/corrupt
    flags + the interner tables as one JSONL blob. Loading is a handful
    of np.frombuffer slices — no per-change parsing (the v2 record loop
    cost a 10k-feed cold open seconds of pure Python). v2 records append
    AFTER the checkpoint; `FileColumnStorageV2.load` replays that tail."""
    n_changes = len(row_ends)
    n_rows = int(row_ends[-1]) if n_changes else 0
    return v3_frame(
        v3_body_bytes(planes, preds, row_ends, flags),
        n_rows, n_changes, len(preds), tables_bytes,
    )


def parse_v3_checkpoint(raw: bytes):
    """(planes, preds, row_ends, flags, tables_lines, end_offset,
    plane_meta) or None when `raw` does not start with a complete v3
    block. plane_meta is the FeedColumns.plane_meta tuple (pointer table
    for the native bulk pack)."""
    if not raw.startswith(_V3_MAGIC):
        return None
    pos = len(_V3_MAGIC)
    if pos + _V3_HDR.size > len(raw):
        return None
    n_rows, n_changes, n_preds, t_len = _V3_HDR.unpack_from(raw, pos)
    pos += _V3_HDR.size
    planes: Dict[str, np.ndarray] = {}
    base = np.frombuffer(raw, np.uint8)
    base_addr = base.__array_interface__["data"][0]
    plane_offs = np.empty(len(PLANE_NAMES), np.int64)
    plane_dts = np.empty(len(PLANE_NAMES), np.uint8)
    for pi, name in enumerate(PLANE_NAMES):
        if pos + 1 > len(raw):
            return None
        code = raw[pos]
        pos += 1
        if code >= len(_V3_DTYPES):
            return None
        dt = np.dtype(_V3_DTYPES[code])
        nbytes = n_rows * dt.itemsize
        if pos + nbytes > len(raw):
            return None
        planes[name] = np.frombuffer(raw, dt, count=n_rows, offset=pos)
        plane_offs[pi] = pos
        plane_dts[pi] = code
        pos += nbytes
    plane_meta = (base_addr, plane_offs, plane_dts, base)
    need = n_changes * 8 + n_changes + n_preds * 4 * PRED_FIELDS + t_len
    if pos + need > len(raw):
        return None
    row_ends = np.frombuffer(raw, np.int64, count=n_changes, offset=pos)
    pos += n_changes * 8
    flags = np.frombuffer(raw, np.uint8, count=n_changes, offset=pos)
    pos += n_changes
    preds = np.frombuffer(
        raw, np.int32, count=n_preds * PRED_FIELDS, offset=pos
    ).reshape(-1, PRED_FIELDS)
    pos += n_preds * 4 * PRED_FIELDS
    tables = (
        raw[pos : pos + t_len].decode("utf-8").splitlines()
        if t_len
        else []
    )
    pos += t_len
    return planes, preds, row_ends, flags, tables, pos, plane_meta


def pack_v2_record(
    rows: np.ndarray, preds: np.ndarray, table_lines: List[str], flag: int
) -> bytes:
    """One framed v2 sidecar record (shared by the live writer and the
    corpus writer so both produce byte-identical files)."""
    tables_bytes = (
        ("\n".join(table_lines) + "\n").encode("utf-8")
        if table_lines
        else b""
    )
    return b"".join(
        (
            _V2_HDR.pack(len(rows), len(preds), len(tables_bytes), flag),
            np.ascontiguousarray(rows, np.int32).tobytes(),
            np.ascontiguousarray(preds, np.int32).tobytes(),
            tables_bytes,
        )
    )


class FileColumnStorageV2:
    """Single-file sidecar: optional v3 checkpoint + framed records.

    Record = <u32 n_rows, u32 n_preds, u32 tables_len, u8 flag>
             rows_bytes || preds_bytes || tables_bytes(jsonl)
    A record is valid iff the file holds all the bytes its header names;
    a torn tail (crash mid-append) simply fails that check and is
    overwritten by the next append. One open+read per cold load and one
    append write per change — the 4-file layout (FileColumnStorage,
    retained read-compatible for old repos) cost a bulk cold start four
    opens + seven stats PER FEED.

    A file may START with a v3 checkpoint block (pack_v3_checkpoint):
    the committed prefix as contiguous narrow column planes, loaded by
    `load_v3` with a handful of frombuffer slices instead of a per-
    change Python loop. Records after the checkpoint are the live tail;
    `write_checkpoint` (FeedColumnCache.compact) folds them in by
    atomically rewriting the file."""

    _HDR = struct.Struct("<IIIB")

    def __init__(self, path: str) -> None:
        self.path = path
        self._end: Optional[int] = None  # valid end offset
        self._counts = None  # (n_rows, n_preds, n_tables) totals

    def _parse_from(self, raw: bytes, start: int):
        """(records, valid_end): records are (n_rows, n_preds, tables
        slice, flag, rows slice, preds slice), parsed from `start`."""
        out = []
        pos = start
        end = len(raw)
        h = self._HDR
        while pos + h.size <= end:
            n_rows, n_preds, t_len, flag = h.unpack_from(raw, pos)
            body = n_rows * 4 * ROW_FIELDS + n_preds * 4 * PRED_FIELDS + t_len
            if pos + h.size + body > end:
                break  # torn tail
            p = pos + h.size
            out.append((n_rows, n_preds, t_len, flag, p))
            pos += h.size + body
        return out, pos

    def load_v3(self):
        """(base_planes|None, tail_rows, preds, tables, commits,
        n_tail_records, plane_meta|None): the checkpoint (when present)
        plus the v2 tail after it. Base commits synthesize
        [row_end, 0, 0, flag] rows — only columns 0 and 3 feed
        FeedColumns."""
        try:
            with open(self.path, "rb") as fh:
                raw = fh.read()
        except OSError:
            raw = b""
        return self._load_v3_bytes(raw)  # _load_v2 records the valid end

    def _load_v3_bytes(self, raw: bytes):
        ck = parse_v3_checkpoint(raw)
        if ck is None:
            rows, preds, tables, commits = self._load_v2(raw, 0)
            return None, rows, preds, tables, commits, len(commits), None
        planes, preds_ck, row_ends, flags, tables_ck, off, meta = ck
        t_rows, t_preds, t_tables, t_commits = self._load_v2(raw, off)
        n_base_rows = int(row_ends[-1]) if len(row_ends) else 0
        commits = np.zeros(
            (len(row_ends) + len(t_commits), COMMIT_FIELDS), np.int32
        )
        commits[: len(row_ends), 0] = row_ends
        commits[: len(row_ends), 3] = flags
        if len(t_commits):
            commits[len(row_ends) :] = t_commits
            commits[len(row_ends) :, 0] += n_base_rows
            commits[len(row_ends) :, 1] += len(preds_ck)
        preds = (
            np.concatenate([preds_ck, t_preds], axis=0)
            if len(t_preds)
            else preds_ck
        )
        self._counts = (
            n_base_rows + len(t_rows),
            len(preds),
            len(tables_ck) + len(t_tables),
        )
        return (
            planes, t_rows, preds, tables_ck + t_tables, commits,
            len(t_commits), meta,
        )

    def load(self):
        """Legacy whole-rows entry: delegates to load_v3 and widens any
        checkpoint planes into the dense row matrix."""
        planes, t_rows, preds, tables, commits, _, _meta = self.load_v3()
        if planes is None:
            return t_rows, preds, tables, commits
        base = rows_from_planes(planes)
        rows = (
            np.concatenate([base, t_rows], axis=0)
            if len(t_rows)
            else base
        )
        return rows, preds, tables, commits

    def _load_v2(self, raw: bytes, start: int):
        recs, valid_end = self._parse_from(raw, start)
        self._end = valid_end
        rows_parts = []
        pred_parts = []
        tables: List[str] = []
        commits = np.zeros((len(recs), COMMIT_FIELDS), np.int32)
        tr = tp = tt = 0
        for i, (n_rows, n_preds, t_len, flag, p) in enumerate(recs):
            rp = p + n_rows * 4 * ROW_FIELDS
            pp = rp + n_preds * 4 * PRED_FIELDS
            if n_rows:
                rows_parts.append(raw[p:rp])
            if n_preds:
                pred_parts.append(raw[rp:pp])
            if t_len:
                tables.extend(
                    raw[pp : pp + t_len].decode("utf-8").splitlines()
                )
            tr += n_rows
            tp += n_preds
            tt = len(tables)
            commits[i] = (tr, tp, tt, flag)
        rows = np.frombuffer(b"".join(rows_parts), np.int32).reshape(
            -1, ROW_FIELDS
        )
        preds = np.frombuffer(b"".join(pred_parts), np.int32).reshape(
            -1, PRED_FIELDS
        )
        self._counts = (tr, tp, tt)
        return rows, preds, tables, commits

    def _ensure_end(self) -> int:
        if self._end is None:
            self.load()
        return self._end

    def commit_change(
        self,
        rows: np.ndarray,
        preds: np.ndarray,
        table_lines: List[str],
        flag: int,
    ) -> None:
        end = self._ensure_end()
        rec = pack_v2_record(rows, preds, table_lines, flag)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        mode = "r+b" if os.path.exists(self.path) else "w+b"
        # a mid-write ENOSPC/EIO leaves a torn record past `end`;
        # self._end only advances on success, so the next commit seeks
        # back and overwrites it — and load() honors only records whose
        # bytes are all present either way
        with io_open(self.path, mode) as fh:
            fh.seek(end)  # overwrite any torn tail
            fh.write(rec)
            fh.truncate()
            fh.flush()
        self._end = end + len(rec)

    def write_checkpoint(
        self,
        planes: Dict[str, np.ndarray],
        preds: np.ndarray,
        row_ends: np.ndarray,
        flags: np.ndarray,
        tables_bytes: bytes,
    ) -> None:
        """Atomically replace the file with a checkpoint covering the
        whole committed state (tmp + rename: a crash leaves either the
        old file or the new one, never a hybrid)."""
        blob = pack_v3_checkpoint(
            planes, preds, row_ends, flags, tables_bytes
        )
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with io_open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            io_fsync(fh)
        io_replace(tmp, self.path)
        self._end = len(blob)

    def reset(self) -> None:
        if os.path.exists(self.path):
            io_remove(self.path)
        self._end = 0
        self._counts = None

    def destroy(self) -> None:
        self.reset()
        self._end = None

    def close(self) -> None:
        pass


class SlabColumnStorage(FileColumnStorageV2):
    """One feed's sidecar served from the corpus slab (storage/slab.py).

    Byte format per feed is identical to the `.cols2` single file —
    the slab just frames many of them in one file — so this subclass
    only redirects the byte source: loads slice the slab's mmap,
    commits append record segments, checkpoints append a fresh image.
    A legacy `.cols2` file migrates lazily on first read: its bytes
    become the feed's image segment and the file is deleted (sidecars
    are derived data — a crash between the two at worst rebuilds from
    blocks, the cache's normal recovery)."""

    def __init__(
        self, slab, name: str, legacy_v2: Optional[str] = None
    ) -> None:
        super().__init__(slab.path + "#" + name)  # diagnostic only
        self._slab = slab
        self._name = name
        self._legacy_v2 = legacy_v2

    def load_v3(self):
        from .slab import KIND_IMAGE

        raw = self._slab.image_bytes(self._name)
        if not raw and not self._slab.has(self._name):
            lp = self._legacy_v2
            if lp is not None and os.path.exists(lp):
                with open(lp, "rb") as fh:
                    raw = fh.read()
                self._slab.append(KIND_IMAGE, self._name, raw)
                try:
                    io_remove(lp)
                except OSError:
                    pass
        return self._load_v3_bytes(raw)

    def commit_change(self, rows, preds, table_lines, flag) -> None:
        from .slab import KIND_RECORD

        self._slab.append(
            KIND_RECORD,
            self._name,
            pack_v2_record(rows, preds, table_lines, flag),
        )

    def write_checkpoint(
        self, planes, preds, row_ends, flags, tables_bytes
    ) -> None:
        from .slab import KIND_IMAGE

        self._slab.append(
            KIND_IMAGE,
            self._name,
            pack_v3_checkpoint(planes, preds, row_ends, flags, tables_bytes),
        )

    def reset(self) -> None:
        from .slab import KIND_TOMBSTONE

        if self._slab.feed_live(self._name):
            self._slab.append(KIND_TOMBSTONE, self._name, b"")
        lp = self._legacy_v2
        if lp is not None and os.path.exists(lp):
            io_remove(lp)
        self._counts = None

    def destroy(self) -> None:
        self.reset()

    def close(self) -> None:  # the slab is owned/closed by the repo
        pass


def memory_column_storage_fn(_name: str) -> MemoryColumnStorage:
    return MemoryColumnStorage()


def file_column_storage_fn(root: str):
    """Sidecars live in the corpus slab (storage/slab.py): one file, one
    open, sequential reads for a whole cold start. Per-feed `.cols2`
    files written by older versions migrate into the slab lazily on
    first read; directories written by the oldest 4-file layout keep
    loading through their reader. HM_SLAB=0 restores the per-feed
    single-file layout. The returned fn carries the slab handle as
    `fn.slab` (the backend compacts + closes it on shutdown)."""
    use_slab = os.environ.get("HM_SLAB", "1") != "0"
    slab = None
    if use_slab:
        from .slab import CorpusSlab

        slab = CorpusSlab(os.path.join(root, "cols.slab"))

    def fn(name: str):
        legacy = os.path.join(root, name[:2], name + ".cols")
        v2 = os.path.join(root, name[:2], name + ".cols2")
        if slab is not None and slab.has(name):
            return SlabColumnStorage(slab, name, legacy_v2=v2)
        if os.path.isdir(legacy) and not os.path.exists(v2):
            return FileColumnStorage(legacy)
        if slab is None:
            return FileColumnStorageV2(v2)
        return SlabColumnStorage(slab, name, legacy_v2=v2)

    fn.slab = slab
    return fn


# ---------------------------------------------------------------------------
# the cache


class _Interner:
    def __init__(self) -> None:
        self.items: List[Any] = []
        self._index: Dict[Any, int] = {}

    def add(self, item: Any) -> int:
        idx = self._index.get(item)
        if idx is None:
            idx = len(self.items)
            self.items.append(item)
            self._index[item] = idx
        return idx

    def __contains__(self, item: Any) -> bool:
        return item in self._index


class FeedColumnCache:
    """Maintains the columnar encoding of one feed.

    Writers call `append_change` after every block append (Actor does
    this for both local writes and decoded remote blocks); bulk loaders
    call `columns()` — a cheap incremental concatenation after the first
    load. The encode mirrors ops/columnar.py `_pack_one` semantics:
    INC rides ref_* with no pred edges; ops are dropped at *pack* time
    (not here) when their obj/ref targets are absent from the packed
    window."""

    def __init__(self, storage, writer: str) -> None:
        self._storage = storage
        self._lock = make_rlock("store.colcache")
        self.writer = writer
        self._loaded = False  # storage read is deferred: a bulk cold
        # start creates thousands of caches serially but loads them in
        # parallel (RepoBackend._prefetch_columns)

    def _ensure_loaded(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        self._actors = _Interner()
        self._keys = _Interner()
        self._strings = _Interner()
        self._floats = _Interner()
        self._bigints = _Interner()
        self._pending_tables = []
        self._base_planes: Optional[Dict[str, np.ndarray]] = None
        self._base_meta = None
        n_tail = 0
        lv3 = getattr(self._storage, "load_v3", None)
        if lv3 is not None:
            (
                self._base_planes, rows, preds, tables, commits, n_tail,
                self._base_meta,
            ) = lv3()
        else:
            rows, preds, tables, commits = self._storage.load()
        self._apply_tables(tables)
        if self.writer not in self._actors:
            # fresh cache: actor 0 is the writer (the table line flushes
            # with the first commit)
            self._intern("a", self._actors, self.writer)
        self._base_rows = (
            len(self._base_planes["action"])
            if self._base_planes is not None
            else 0
        )
        self._row_chunks: List[np.ndarray] = [rows] if len(rows) else []
        self._pred_chunks: List[np.ndarray] = [preds] if len(preds) else []
        self._n_rows_total = self._base_rows + len(rows)
        self._n_preds_total = len(preds)
        self._commits_arr: np.ndarray = np.asarray(
            commits, np.int32
        ).reshape(-1, COMMIT_FIELDS)
        self._commits_new: List[Tuple[int, int, int, int]] = []
        self._cached: Optional[FeedColumns] = None
        # long v2 tails re-pay the per-record parse on every cold load:
        # fold them into the checkpoint now (atomic rewrite)
        if n_tail >= int(os.environ.get("HM_CKPT_TAIL", "64")):
            try:
                self.compact()
            except OSError:  # read-only media: served from memory fine
                pass

    # -- table interning ----------------------------------------------

    def _apply_tables(self, lines: List[str]) -> None:
        if not lines:
            return
        kinds = {
            "a": self._actors,
            "k": self._keys,
            "s": self._strings,
            "f": self._floats,
            "b": self._bigints,
        }
        # one C-level parse for the whole file beats a json.loads per line
        # (bulk cold opens read tens of thousands of these)
        for rec in json.loads("[" + ",".join(lines) + "]"):
            t = rec["t"]
            v = rec["v"]
            kinds[t].add(int(v) if t == "b" else v)

    def _intern(self, kind: str, interner: _Interner, v: Any) -> int:
        if v in interner:
            return interner.add(v)
        idx = interner.add(v)
        jv = str(v) if kind == "b" else v
        self._pending_tables.append(
            json.dumps({"t": kind, "v": jv}, separators=(",", ":"))
        )
        return idx

    # -- encode --------------------------------------------------------

    @property
    def n_changes(self) -> int:
        with self._lock:
            self._ensure_loaded()
            return len(self._commits_arr) + len(self._commits_new)

    def append_change(self, change: Optional[Change]) -> None:
        """Encode one change (None = corrupt block placeholder)."""
        with self._lock:
            self._ensure_loaded()
            if change is None:
                lines = self._take_pending()
                try:
                    self._storage.commit_change(
                        np.zeros((0, ROW_FIELDS), np.int32),
                        np.zeros((0, PRED_FIELDS), np.int32),
                        lines,
                        1,
                    )
                except BaseException:
                    self._pending_tables = lines + self._pending_tables
                    raise
                self._commits_new.append(
                    (self._total_rows(), self._total_preds(), 0, 1)
                )
                self._cached = None
                return
            rows, preds = self._encode(change)
            lines = self._take_pending()
            try:
                self._storage.commit_change(rows, preds, lines, 0)
            except BaseException:
                # ENOSPC/EIO mid-commit: the interners already hold the
                # new table entries, so the un-persisted lines MUST go
                # back on the pending queue — dropping them would make
                # every later commit reference table indices the file
                # never defines (silently wrong values after reload)
                self._pending_tables = lines + self._pending_tables
                raise
            if len(rows):
                self._row_chunks.append(rows)
                self._n_rows_total += len(rows)
            if len(preds):
                self._pred_chunks.append(preds)
                self._n_preds_total += len(preds)
            self._commits_new.append(
                (self._total_rows(), self._total_preds(), 0, 0)
            )
            self._cached = None

    def _take_pending(self) -> List[str]:
        lines = self._pending_tables
        self._pending_tables = []
        return lines

    def _total_rows(self) -> int:
        return self._n_rows_total

    def _total_preds(self) -> int:
        return self._n_preds_total

    def _encode(self, change: Change) -> Tuple[np.ndarray, np.ndarray]:
        base = self._total_rows()
        out_rows: List[List[int]] = []
        out_preds: List[Tuple[int, int, int]] = []
        # hoisted out of the closure: the guarded-attr rule checks the
        # _actors read at THIS (REQUIRES-covered) function depth
        actors = self._actors
        aid = lambda actor: self._intern("a", actors, actor)  # noqa: E731
        for i, op in enumerate(change.ops):
            ctr = change.start_op + i
            if op.obj == ROOT:
                obj_ctr, obj_a = 0, OBJ_ROOT
            else:
                obj_ctr, obj_a = op.obj.ctr, aid(op.obj.actor)
            if op.action == Action.INC:
                if not op.pred:
                    continue  # no target: dropped (matches _pack_one)
                tgt = op.pred[0]
                ref_ctr, ref_a = tgt.ctr, aid(tgt.actor)
            elif op.ref is None:
                ref_ctr, ref_a = 0, REF_NONE
            elif op.ref == HEAD:
                ref_ctr, ref_a = 0, REF_HEAD
            else:
                ref_ctr, ref_a = op.ref.ctr, aid(op.ref.actor)
            vkind, value = self._encode_value(op)
            key = (
                self._intern("k", self._keys, op.key)
                if op.key is not None
                else -1
            )
            dt = (
                1 if op.datatype == "counter"
                else 2 if op.datatype == "timestamp" else 0
            )
            row_idx = base + len(out_rows)
            if op.action != Action.INC:
                for p in op.pred:
                    out_preds.append((row_idx, p.ctr, aid(p.actor)))
            out_rows.append(
                [
                    int(op.action), ctr, change.seq, change.start_op,
                    obj_ctr, obj_a, key, ref_ctr, ref_a,
                    1 if op.insert else 0, vkind, value, dt, 0,
                ]
            )
        rows = np.asarray(out_rows, np.int32).reshape(-1, ROW_FIELDS)
        preds = np.asarray(out_preds, np.int32).reshape(-1, PRED_FIELDS)
        return rows, preds

    def _encode_value(self, op) -> Tuple[int, int]:
        # mirrors ops/columnar.py _encode_value
        v = op.value
        if op.action.makes_object or v is None:
            return VK_NONE, 0
        if isinstance(v, bool):
            return VK_BOOL, 1 if v else 0
        if isinstance(v, int):
            if _INT32_MIN <= v <= _INT32_MAX:
                return VK_INT, v
            return VK_BIGINT, self._intern("b", self._bigints, v)
        if isinstance(v, float):
            return VK_FLOAT, self._intern("f", self._floats, v)
        if isinstance(v, str):
            return VK_STR, self._intern("s", self._strings, v)
        return VK_STR, self._intern("s", self._strings, repr(v))

    # -- decode --------------------------------------------------------

    def reset(self) -> None:
        """Discard the cache and start over (storage included). Invoked
        by Actor when the sidecar claims more changes than the feed holds
        — blocks are the source of truth, so a cache that ran ahead (e.g.
        feed file replaced/truncated out-of-band) must rebuild."""
        with self._lock:
            self._loaded = True  # reset state IS the loaded-fresh state
            self._storage.reset()
            self._base_planes = None
            self._base_meta = None
            self._base_rows = 0
            self._actors = _Interner()
            self._keys = _Interner()
            self._strings = _Interner()
            self._floats = _Interner()
            self._bigints = _Interner()
            self._pending_tables = []
            self._intern("a", self._actors, self.writer)
            self._row_chunks = []
            self._pred_chunks = []
            self._n_rows_total = 0
            self._n_preds_total = 0
            self._commits_arr = np.zeros((0, COMMIT_FIELDS), np.int32)
            self._commits_new = []
            self._cached = None

    def columns(self) -> FeedColumns:
        with self._lock:
            self._ensure_loaded()
            if self._cached is not None:
                return self._cached
            planes = None
            meta = None
            if self._base_planes is not None:
                if not self._row_chunks:
                    planes = self._base_planes  # pure checkpoint load
                    meta = self._base_meta
                else:
                    # live appends landed after the checkpoint: fold the
                    # planes into dense rows once and continue row-wise
                    self._row_chunks.insert(
                        0, rows_from_planes(self._base_planes)
                    )
                    self._base_planes = None
                    self._base_meta = None
                    self._base_rows = 0
            rows = (
                self._row_chunks[0]
                if len(self._row_chunks) == 1  # no-copy: fresh load
                else np.concatenate(self._row_chunks, axis=0)
                if self._row_chunks
                else (
                    None
                    if planes is not None
                    else np.zeros((0, ROW_FIELDS), np.int32)
                )
            )
            preds = (
                self._pred_chunks[0]
                if len(self._pred_chunks) == 1
                else np.concatenate(self._pred_chunks, axis=0)
                if self._pred_chunks
                else np.zeros((0, PRED_FIELDS), np.int32)
            )
            self._row_chunks = (
                [rows] if rows is not None and len(rows) else []
            )
            self._pred_chunks = [preds] if len(preds) else []
            if self._commits_new:
                self._commits_arr = np.concatenate(
                    [
                        self._commits_arr,
                        np.asarray(self._commits_new, np.int32).reshape(
                            -1, COMMIT_FIELDS
                        ),
                    ],
                    axis=0,
                )
                self._commits_new = []
            commits = self._commits_arr
            n = len(commits)
            bad = np.nonzero(commits[:, 3] != 0)[0]
            ok_prefix = int(bad[0]) if len(bad) else n
            row_ends = np.zeros(n + 1, np.int64)
            if n:
                row_ends[1:] = commits[:, 0]
            self._cached = FeedColumns(
                rows=rows,
                preds=preds,
                actors=list(self._actors.items),
                keys=list(self._keys.items),
                strings=list(self._strings.items),
                floats=list(self._floats.items),
                bigints=list(self._bigints.items),
                n_changes=n,
                ok_prefix_len=ok_prefix,
                row_ends=row_ends,
                planes=planes,
                plane_meta=meta,
            )
            return self._cached

    def compact(self) -> None:
        """Fold the storage's whole committed state into one v3
        checkpoint (atomic rewrite). Cold loads of a compacted feed are
        a handful of frombuffer slices; v2 tails re-accumulate with
        live appends until the next compaction (auto at load when the
        tail exceeds HM_CKPT_TAIL records)."""
        with self._lock:
            self._ensure_loaded()
            wc = getattr(self._storage, "write_checkpoint", None)
            if wc is None:
                return
            fc = self.columns()
            if fc.planes is not None:
                planes = fc.planes
            else:
                planes = planes_from_rows(fc.ensure_rows())
            commits = self._commits_arr
            wc(
                planes,
                fc.preds,
                commits[:, 0].astype(np.int64),
                commits[:, 3].astype(np.uint8),
                self._tables_blob(),
            )

    def _tables_blob(self) -> bytes:
        lines = []
        for kind, interner in (
            ("a", self._actors), ("k", self._keys),
            ("s", self._strings), ("f", self._floats),
            ("b", self._bigints),
        ):
            for v in interner.items:
                jv = str(v) if kind == "b" else v
                lines.append(
                    json.dumps({"t": kind, "v": jv}, separators=(",", ":"))
                )
        return ("\n".join(lines) + "\n").encode("utf-8") if lines else b""

    def destroy(self) -> None:
        """Delete the cache's persisted state entirely (doc destroy)."""
        with self._lock:
            self.reset()
            if hasattr(self._storage, "destroy"):
                self._storage.destroy()

    def close(self) -> None:
        self._storage.close()
