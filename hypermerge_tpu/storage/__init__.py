"""Storage layer: append-only signed feeds, block codec, durable stores."""
