"""Group-commit write-ahead journal — O(1) fsyncs per commit window.

Before this module, durable tiers paid per-FEED fsyncs: tier 1's group
flusher fsynced every dirty block log each window (O(dirty feeds)), and
tier 2 fsynced the log inline on every append. The WAL makes a durable
commit window ONE sequential journal append + ONE fsync regardless of
how many feeds (or writer threads) are dirty:

  - every feed append at HM_FSYNC>=1 also writes an APPEND record
    (feed name, block index, block bytes) to the shared per-repo
    journal (<repo>/wal.log), a pure sequential write;
  - durability = fsync of the JOURNAL only. Tier 2 acks through
    `commit()` — a leader/follower group commit where concurrent
    committers (different docs, different threads, since the per-doc
    emission split) share one fsync. Tier 1 marks the WAL dirty with
    the DurabilityManager, whose debounced flusher calls `sync()`:
    one journal fsync per window, however many feeds changed;
  - the per-feed block logs are written (page cache) at append time
    but fsynced only at CHECKPOINT, off the ack path: when the
    journal exceeds HM_WAL_MAX_BYTES (or at close), every journaled
    storage gets its one `sync()`, then the journal resets to its
    session dirty-name ledger via an atomic tmp+rename rotation — a
    crash at any point mid-checkpoint leaves either the old journal
    (replay is idempotent) or the new one (the logs are already
    durable);
  - recovery (storage/scrub.py) replays the journal prefix into the
    block logs before the per-feed scrub: a power cut that dropped
    unfsynced log pages loses nothing acked, because the acked bytes
    are in the fsynced journal. A torn journal tail (crash mid-record)
    parses as end-of-journal — torn records were never acked.

The journal doubles as the **generation stamp** bounding recovery: its
header carries a per-session id (also written into the `repo.dirty`
marker), and a DIRTY record names every feed touched this session —
checkpoint rotation preserves the name ledger. Recovery after a crash
whose marker matches the journal header therefore scrubs ONLY the
session-dirty feeds instead of scanning every sidecar in the repo
(100k-feed repos recover in O(dirty), satellite: "generation stamp
honored"). A mismatched or unreadable journal (older layout, HM_WAL=0
session, tier-0 header) falls back to the full scan — bounding is an
optimization that must never skip real damage.

Every byte goes through the storage/faults.py io seam, so the crash
matrix (tests/test_crash.py) replays journal writes, fsyncs, fsync
LIES, and the checkpoint rename with the same kill -9 / power-cut
fidelity as the block logs.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Set, Tuple

from ..analysis.lockdep import make_condition, make_lock
from ..utils.debug import log
from .faults import io_fsync, io_open, io_remove, io_replace
from .. import telemetry

JOURNAL_NAME = "wal.log"
_MAGIC = b"HMWAL1 "

_REC = struct.Struct("<IIBH")  # payload_len, crc32, kind, name_len
_IDX = struct.Struct("<Q")  # block index (APPEND payload prefix)
K_DIRTY = 1
K_APPEND = 2

# journal telemetry (process registry): the [wal] group tools/top.py
# renders — append/fsync/checkpoint rates and journal byte flow
_M_APPENDS = telemetry.counter("storage.wal.appends")
_M_BYTES = telemetry.counter("storage.wal.bytes")
_M_FSYNCS = telemetry.counter("storage.wal.fsyncs")
_M_CKPTS = telemetry.counter("storage.wal.checkpoints")
_M_PACED = telemetry.counter("storage.wal.paced_commits")
_M_REPLAYED = telemetry.counter("storage.wal.replayed")


def wal_enabled() -> bool:
    return os.environ.get("HM_WAL", "1") != "0"


def _max_bytes() -> int:
    try:
        return int(os.environ.get("HM_WAL_MAX_BYTES", "67108864"))
    except ValueError:
        return 67108864


def _commit_window_s() -> float:
    try:
        return float(os.environ.get("HM_WAL_MS", "0")) / 1e3
    except ValueError:
        return 0.0


def _encode(kind: int, name: str, payload: bytes) -> bytes:
    nb = name.encode("utf-8")
    crc = zlib.crc32(bytes([kind]) + nb + payload) & 0xFFFFFFFF
    return _REC.pack(len(payload), crc, kind, len(nb)) + nb + payload


class WriteAheadLog:
    """The shared per-repo journal. One instance per file-backed
    RepoBackend session, created AFTER recovery consumed the previous
    session's journal; `session` is the generation stamp the repo
    writes into its crash marker."""

    def __init__(self, path: str, tier: int) -> None:
        self.path = path
        self.session = os.urandom(8).hex()
        self.tier = tier
        self._max_bytes = _max_bytes()
        self._window_s = _commit_window_s()
        self._lock = make_lock("store.wal")
        self._cv = make_condition("store.wal", self._lock)
        header = _MAGIC + json.dumps(
            {"session": self.session, "tier": tier}
        ).encode("utf-8") + b"\n"
        self._fh = io_open(path, "wb")
        self._fh.write(header)
        self._fh.flush()
        # the header (the stamp recovery matches against the crash
        # marker) must be durable at every tier — one fsync per
        # session open, the same cost class as the marker itself
        io_fsync(self._fh)
        self._fh.close()
        self._fh = io_open(path, "ab")
        self._file_bytes = len(header)
        # virtual append offset: MONOTONE across checkpoint rotations
        # (commit tokens survive the file shrinking), in bytes
        self._end = 0
        self._synced = 0
        self._syncing = False
        self._ckpt_running = False
        self._dirty_names: Set[str] = set()
        self._ckpt_pending: Dict[str, object] = {}
        self._closed = False
        # service-plane backpressure hook (set once at wiring, before
        # writers exist): zero-arg callable returning extra seconds to
        # add to the group-commit gather window while the overload
        # controller is in SHED — acks pace down, writes are never
        # dropped once acked (serve/overload.py)
        self.ack_pacer = None

    # ------------------------------------------------------------------
    # append + group commit

    def _write_locked(self, rec: bytes) -> bool:
        """Append one encoded record to the journal; heals its own
        torn tail on a failed write (truncate back to the last good
        end) so later records stay parseable. False = journal broken
        (caller falls back to legacy per-feed durability)."""
        try:
            self._fh.write(rec)
            self._fh.flush()
        except OSError as e:
            log("storage:wal", f"journal write failed: {e}")
            try:
                self._fh.truncate(self._file_bytes)
            except OSError:
                # cannot even truncate: stop journaling, the fsynced
                # prefix stays replayable
                self._closed = True
            return False
        self._file_bytes += len(rec)
        self._end += len(rec)
        return True

    def _append_dirty_locked(self, name: str, storage) -> bool:
        if name not in self._dirty_names:
            if not self._write_locked(_encode(K_DIRTY, name, b"")):
                return False
            self._dirty_names.add(name)
        if storage is not None:
            self._ckpt_pending[name] = storage
        return True

    def note_dirty(self, name: str, storage=None) -> None:
        """Ledger-only entry (tier 0): records that `name` was touched
        this session so recovery can bound its scan, without
        journaling payload bytes."""
        with self._cv:
            if self._closed:
                return
            self._append_dirty_locked(name, storage)

    def append(
        self, name: str, index: int, data: bytes, storage=None
    ) -> Optional[int]:
        """Journal one feed block; returns the commit token to pass to
        `commit()` (tier 2) or None when the journal cannot accept it
        (caller falls back to the legacy per-feed path)."""
        rec = _encode(K_APPEND, name, _IDX.pack(index) + bytes(data))
        ckpt = False
        with self._cv:
            if self._closed:
                return None
            if not self._append_dirty_locked(name, storage):
                return None
            if not self._write_locked(rec):
                return None
            end = self._end
            if (
                self._file_bytes > self._max_bytes
                and not self._ckpt_running
            ):
                self._ckpt_running = True
                ckpt = True
        _M_APPENDS.add(1)
        _M_BYTES.add(len(rec))
        if ckpt:
            threading.Thread(
                target=self._checkpoint_bg, daemon=True, name="hm-wal-ckpt"
            ).start()
        return end

    def fsync_debt(self) -> int:
        """Bytes appended but not yet covered by a journal fsync —
        the service plane's WAL pressure signal (serve/overload.py
        normalizes it against HM_WAL_MAX_BYTES)."""
        with self._cv:
            return max(0, self._end - self._synced)

    def commit(self, end: int) -> None:
        """Block until the journal is durable through `end` — the
        group-commit handshake: the first committer in becomes the
        leader and fsyncs for everyone queued behind it."""
        while True:
            with self._cv:
                if self._synced >= end:
                    return
                if self._closed:
                    # woken by closure WITHOUT a covering fsync (a
                    # failed close/broken journal): the append is NOT
                    # durable — raising makes the caller's ack fail
                    # instead of granting a durable ack for bytes
                    # that never reached the platter
                    raise OSError(
                        "journal closed before commit was durable"
                    )
                if not self._syncing:
                    self._syncing = True
                    leader = True
                else:
                    leader = False
                    self._cv.wait(1.0)
            if not leader:
                continue
            pacer = self.ack_pacer
            extra = float(pacer()) if pacer is not None else 0.0
            if extra > 0:
                _M_PACED.add(1)
            gather = self._window_s + extra
            if gather > 0:
                time.sleep(gather)  # gather followers (+ backpressure)
            with self._cv:
                fh = self._fh
                target = self._end
            err: Optional[OSError] = None
            rotated = False
            try:
                io_fsync(fh)
                _M_FSYNCS.add(1)
            except OSError as e:
                err = e
            except ValueError:
                # a checkpoint rotation closed this handle mid-fsync;
                # the rotation itself marked everything durable — loop
                # and re-read _synced instead of failing the commit
                rotated = True
            with self._cv:
                self._syncing = False
                if err is None and not rotated:
                    self._synced = max(self._synced, target)
                self._cv.notify_all()
            if err is not None:
                raise err

    def sync(self) -> None:
        """Make everything journaled so far durable (the tier-1 group
        flusher target and the pre-sqlite barrier): ONE fsync per
        window however many feeds are dirty."""
        with self._cv:
            end = self._end
        self.commit(end)

    # ------------------------------------------------------------------
    # checkpoint (off the ack path)

    def _checkpoint_bg(self) -> None:
        try:
            self.checkpoint()
        except Exception as e:  # pragma: no cover - defensive
            log("storage:wal", f"background checkpoint failed: {e}")
        finally:
            with self._cv:
                self._ckpt_running = False

    def checkpoint(self) -> Dict[str, int]:
        """Drain the journal into the per-feed files: fsync every
        journaled storage (their bytes are already written — this is
        the deferred durability), then reset the journal to its
        session dirty-name ledger with an atomic tmp+rename. Records
        appended DURING the checkpoint are carried over verbatim.
        Crash-safe at every prefix: the old journal replays
        idempotently; the new one only lands after the logs are
        durable."""
        out = {"synced_feeds": 0, "carried_bytes": 0}
        with self._cv:
            if self._closed:
                return out
            pending = self._ckpt_pending
            self._ckpt_pending = {}
            file_mark = self._file_bytes
        items = sorted(pending.items())
        for i, (name, storage) in enumerate(items):
            try:
                storage.sync()
                out["synced_feeds"] += 1
            except (OSError, ValueError) as e:
                log("storage:wal", f"checkpoint sync {name[:8]}: {e}")
                # abort: the journal stays authoritative for this feed
                # AND every not-yet-synced one behind it — dropping
                # them would let a later rotation discard K_APPEND
                # records whose logs never reached the platter
                with self._cv:
                    for n, s in items[i:]:
                        self._ckpt_pending.setdefault(n, s)
                return out
        with self._cv:
            if self._closed:
                return out
            # rotate: header + dirty ledger + any records appended
            # while the syncs ran (their logs are NOT yet durable)
            tail = b""
            if self._file_bytes > file_mark:
                try:
                    with open(self.path, "rb") as rfh:
                        rfh.seek(file_mark)
                        tail = rfh.read()
                except OSError as e:
                    log("storage:wal", f"checkpoint tail read: {e}")
                    return out
            header = _MAGIC + json.dumps(
                {"session": self.session, "tier": self.tier}
            ).encode("utf-8") + b"\n"
            body = b"".join(
                _encode(K_DIRTY, n, b"")
                for n in sorted(self._dirty_names)
            )
            tmp = self.path + ".tmp"
            try:
                with io_open(tmp, "wb") as tfh:
                    tfh.write(header + body + tail)
                    tfh.flush()
                    io_fsync(tfh)
                self._fh.close()
                io_replace(tmp, self.path)
                self._fh = io_open(self.path, "ab")
            except OSError as e:
                log("storage:wal", f"checkpoint rotate failed: {e}")
                try:  # keep appending to the (intact) old journal
                    self._fh = io_open(self.path, "ab")
                except OSError:
                    self._closed = True
                return out
            self._file_bytes = len(header) + len(body) + len(tail)
            out["carried_bytes"] = len(tail)
            # everything journaled before the rotation is durable now:
            # checkpointed records live in fsynced logs, and the
            # carried tail rode the fsynced tmp image
            self._synced = max(self._synced, self._end)
        _M_CKPTS.add(1)
        return out

    # ------------------------------------------------------------------

    def file_bytes(self) -> int:
        with self._cv:
            return self._file_bytes

    def dirty_names(self) -> Set[str]:
        with self._cv:
            return set(self._dirty_names)

    def close(self) -> bool:
        """Final checkpoint + journal reset. True when everything
        reached the platter (the repo only marks itself clean then)."""
        try:
            self.sync()
        except OSError:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            return False
        ok = True
        with self._cv:
            pending = dict(self._ckpt_pending)
            self._ckpt_pending = {}
        for _name, storage in sorted(pending.items()):
            try:
                storage.sync()
            except OSError as e:
                log("storage:wal", f"close sync failed: {e}")
                ok = False
        with self._cv:
            self._closed = True
            fh = self._fh
            self._cv.notify_all()
        try:
            fh.close()
        except OSError:
            pass
        if ok:
            # logs are durable: the journal has served its purpose.
            # Truncate to the bare header so a later crash's recovery
            # (marker left by a FAILED close elsewhere) sees an empty
            # ledger consistent with reality.
            try:
                header = _MAGIC + json.dumps(
                    {"session": self.session, "tier": self.tier}
                ).encode("utf-8") + b"\n"
                with io_open(self.path, "wb") as nfh:
                    nfh.write(header)
                    nfh.flush()
                    io_fsync(nfh)
            except OSError as e:
                log("storage:wal", f"close reset failed: {e}")
                ok = False
        return ok


# ---------------------------------------------------------------------------
# recovery-side reading + replay


def read_journal(path: str):
    """Parse a journal file. Returns (header | None, dirty_names,
    records, torn_bytes) where records is [(name, index, bytes), ...]
    in append order. A torn tail (crash mid-record) terminates the
    parse cleanly — torn records were never acknowledged."""
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError:
        return None, set(), [], 0
    if not raw.startswith(_MAGIC):
        return None, set(), [], len(raw)
    nl = raw.find(b"\n")
    if nl < 0:
        return None, set(), [], len(raw)
    try:
        header = json.loads(raw[len(_MAGIC):nl].decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None, set(), [], len(raw)
    pos = nl + 1
    dirty: Set[str] = set()
    records: List[Tuple[str, int, bytes]] = []
    end = len(raw)
    while pos + _REC.size <= end:
        plen, crc, kind, nlen = _REC.unpack_from(raw, pos)
        body_end = pos + _REC.size + nlen + plen
        if body_end > end:
            break  # torn tail
        nb = raw[pos + _REC.size: pos + _REC.size + nlen]
        payload = raw[pos + _REC.size + nlen: body_end]
        if zlib.crc32(bytes([kind]) + nb + payload) & 0xFFFFFFFF != crc:
            break  # torn/corrupt record: stop here
        try:
            name = nb.decode("utf-8")
        except UnicodeDecodeError:
            break
        dirty.add(name)
        if kind == K_APPEND and plen >= _IDX.size:
            (index,) = _IDX.unpack_from(payload, 0)
            records.append((name, index, payload[_IDX.size:]))
        pos = body_end
    return header, dirty, records, len(raw) - pos


def recover(back, repair: bool = True) -> Dict:
    """Replay the crashed session's journal into the block logs —
    called by storage/scrub.py BEFORE the per-feed scrub, so torn-tail
    repair and sig-chain reconciliation see the replayed blocks.
    Returns the `wal` section of the scrub report; `bounded`+`dirty`
    tell the scrub which feeds the session could have damaged (the
    generation stamp honored)."""
    path = os.path.join(back.path, JOURNAL_NAME)
    report: Dict = {
        "present": 0, "session_match": 0, "tier": None, "records": 0,
        "dirty_feeds": 0, "replayed": 0, "skipped": 0, "torn_bytes": 0,
        "bounded": 0,
    }
    if not os.path.exists(path):
        return report
    header, dirty, records, torn = read_journal(path)
    report["present"] = 1
    report["torn_bytes"] = torn
    if header is None:
        return report
    report["tier"] = header.get("tier")
    report["records"] = len(records)
    report["dirty_feeds"] = len(dirty)
    report["dirty"] = sorted(dirty)
    marker = b""
    try:
        with open(os.path.join(back.path, "repo.dirty"), "rb") as fh:
            marker = fh.read()
    except OSError:
        pass
    session = str(header.get("session") or "")
    match = bool(session) and marker.decode("utf-8", "replace") == session
    report["session_match"] = 1 if match else 0
    # bounding is only sound when the journal provably belongs to the
    # crashed session AND that session ran a durable tier (tier 0
    # never fsyncs the ledger, so a power cut may have eaten it)
    report["bounded"] = 1 if (match and (header.get("tier") or 0) >= 1) else 0
    if not repair:
        # mirror the real replay's sequential `index == have` walk per
        # feed (a journal with a GAP must preview exactly what repair
        # will append — `index >= have` would overcount past the gap)
        would = 0
        have_sim: Dict[str, int] = {}
        for name, index, _data in records:
            if name not in have_sim:
                storage = back.feeds._storage_fn(name)
                try:
                    have_sim[name] = len(storage)
                finally:
                    storage.close()
            if index == have_sim[name]:
                would += 1
                have_sim[name] += 1
        report["replay_would"] = would
        return report
    # -- replay: append every journaled block the log lost -------------
    by_feed: Dict[str, List[Tuple[int, bytes]]] = {}
    for name, index, data in records:
        by_feed.setdefault(name, []).append((index, data))
    replayed_feeds: Set[str] = set()
    replay_durable = True
    suspend = getattr(back.durability, "suspended", None)
    import contextlib

    ctx = suspend() if suspend is not None else contextlib.nullcontext()
    with ctx:
        for name in sorted(by_feed):
            storage = back.feeds._storage_fn(name)
            try:
                touched = False
                for index, data in sorted(by_feed[name]):
                    have = len(storage)
                    if index == have:
                        storage.append(data)
                        touched = True
                        report["replayed"] += 1
                        replayed_feeds.add(name)
                    else:
                        report["skipped"] += 1
                if touched:
                    # replayed bytes must be durable BEFORE the journal
                    # is reset below (this IS the recovery checkpoint)
                    try:
                        storage.sync()
                    except OSError as e:
                        log("storage:wal", f"replay sync {name[:8]}: {e}")
                        replay_durable = False
            finally:
                storage.close()
    _M_REPLAYED.add(report["replayed"])
    report["replayed_feeds"] = sorted(replayed_feeds)
    if replay_durable:
        try:
            io_remove(path)  # consumed: a fresh session writes its own
        except OSError:
            pass
    else:
        # a replayed block reached only the page cache: the journal
        # stays — another power cut can still replay it. The session
        # must then run journal-less (RepoBackend checks this flag;
        # creating a fresh WriteAheadLog here would truncate the one
        # copy of the un-durable records).
        report["replay_sync_failed"] = 1
    return report
