"""Machine-checked concurrency invariants (ISSUES 10 + 13).

Two halves over one rule set:

- the manifests: `hierarchy.py` — THE lock-hierarchy (ranks, leaves,
  no-block emission locks) plus the blocking-call and engine-entry
  tables; `guards.py` — THE shared-state guard map (which lock guards
  which field, GUARDED_BY-style, with declared escape classes);
  `envvars.py` — the HM_* env-var registry.
- the checkers: `linter.py` — the static AST pass (`python
  tools/lint.py`, run in tier-1 by tests/test_analysis.py);
  `lockdep.py` — the runtime detectors: `HM_LOCKDEP=1` lock-order/
  blocking instrumentation through the `make_lock`/`make_rlock`/
  `make_condition` factories, and `HM_RACEDEP=1` Eraser-style lockset
  race detection over the guard manifest's attributes.

`suppressions.py` holds the (justified) exceptions.
"""

from .lockdep import (  # noqa: F401
    blocking,
    enable as enable_lockdep,
    enabled as lockdep_enabled,
    install_racedep,
    make_condition,
    make_lock,
    make_rlock,
    maybe_install_racedep,
    racedep_enabled,
    uninstall_racedep,
)

__all__ = [
    "blocking",
    "enable_lockdep",
    "lockdep_enabled",
    "install_racedep",
    "make_condition",
    "make_lock",
    "make_rlock",
    "maybe_install_racedep",
    "racedep_enabled",
    "uninstall_racedep",
]
