"""Machine-checked concurrency invariants (ISSUE 10).

Two halves over one rule set:

- `hierarchy.py` — THE lock-hierarchy manifest (ranks, leaves,
  no-block emission locks) plus the blocking-call and engine-entry
  tables; `envvars.py` — the HM_* env-var registry.
- `linter.py` — the static AST pass (`python tools/lint.py`, run in
  tier-1 by tests/test_analysis.py); `lockdep.py` — the runtime
  detector behind `HM_LOCKDEP=1` and the `make_lock`/`make_rlock`/
  `make_condition` factories every package lock is created through.

`suppressions.py` holds the (justified) exceptions.
"""

from .lockdep import (  # noqa: F401
    blocking,
    enable as enable_lockdep,
    enabled as lockdep_enabled,
    make_condition,
    make_lock,
    make_rlock,
)

__all__ = [
    "blocking",
    "enable_lockdep",
    "lockdep_enabled",
    "make_condition",
    "make_lock",
    "make_rlock",
]
