"""Central lint suppressions — each entry MUST carry a justification
(the linter rejects empty ones, and flags entries that match nothing).

Prefer the inline form next to the code it excuses:

    ...  # lint: allow(<rule>) — <why this specific site is safe>

and use this file only for exceptions that span several sites or
cannot carry a comment (generated code). Every entry is a reviewed,
documented decision — "the linter was noisy" is not a justification.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple


class Suppression(NamedTuple):
    rule: str  # one of linter.RULES
    path_glob: str  # repo-relative, fnmatch style
    contains: str  # substring the violating source line must contain
    justification: str


SUPPRESSIONS: Tuple[Suppression, ...] = ()
