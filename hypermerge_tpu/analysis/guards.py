"""THE shared-state guard manifest — which lock guards which field.

PR 9 (`hierarchy.py`) made the lock ORDER machine-checked; this module
does the same for the DATA half of the concurrency story: every shared
attribute of the hot concurrent classes is mapped to the lock class
(from `analysis/hierarchy.py`) that guards it, in the tradition of
Clang's `GUARDED_BY` thread-safety annotations. The scattered
"guarded by the engine lock" comments those classes used to carry are
now rows here, consumed by two checkers:

- the static `guarded-attr` lint rule (`analysis/linter.py`, run by
  `tools/lint.py` and tier-1): every `self.<attr>` read/write of a
  declared attribute inside its class must sit lexically inside a
  `with` of the declared guard (or inside a method listed in
  `REQUIRES` below). Writes are hard errors; reads may be excused by
  the `atomic_read_ok` escape.
- the runtime lockset detector (`analysis/lockdep.py`,
  `HM_RACEDEP=1`): the declared attributes are wrapped in descriptor
  instrumentation that intersects per-(object, attribute) candidate
  locksets Eraser-style against the per-thread held stacks lockdep
  maintains — a guard violation is reported from the access pattern
  alone, without the race ever firing, and regardless of which
  receiver expression reached the field (the static rule only sees
  `self.X`).

Escape classes — every shared field has a DECLARED story, including
the fields that are deliberately not lock-guarded:

- (no escape)      reads AND writes require the guard.
- `atomic_read_ok` writes require the guard; a lone read is a
  GIL-atomic snapshot (dict.get / bool flag / int) taken on a hot
  path on purpose. The runtime detector still tracks writes.
- `init_only`      written only in `__init__` (before the object is
  shared); reads need no lock. A write anywhere else is a violation.
- `unguarded`      deliberately lock-free shared state; the `doc`
  string IS the story (single-writer protocol, monotonic latch,
  snapshot idiom). Not instrumented at runtime.

Granularity matches GUARDED_BY: the FIELD (the reference) is guarded,
not the object graph behind it — mutating a dict obtained from a
guarded read is visible to the checkers only at the `self.X` access.
`__init__` bodies are exempt everywhere (the object is not yet
shared). Accesses through receivers other than `self` are invisible
to the static rule but fully visible to the runtime detector.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

from .hierarchy import BY_NAME as LOCK_BY_NAME

ESCAPES = ("", "atomic_read_ok", "init_only", "unguarded")


class GuardedClass(NamedTuple):
    cls: str      # class name (unique across the package)
    module: str   # dotted import path (runtime instrumentation)
    guard: str    # hierarchy lock class guarding the fields below
    guarded: Tuple[str, ...] = ()         # reads + writes under guard
    atomic_read_ok: Tuple[str, ...] = ()  # writes under guard only
    init_only: Tuple[str, ...] = ()       # written in __init__ only
    unguarded: Tuple[str, ...] = ()       # declared lock-free (doc!)
    doc: str = ""


GUARDS: Tuple[GuardedClass, ...] = (
    GuardedClass(
        "LiveApplyEngine", "hypermerge_tpu.backend.live", "live.engine",
        guarded=(
            "_refused", "_adopting", "_demoted_ids",
            "_use_clock",
        ),
        atomic_read_ok=("_docs",),
        init_only=("_back", "_m", "_ticker"),
        doc="Tick/dirty-set coordination only since the write-plane "
            "split: the doc table and refusal/adoption/demotion sets "
            "mutate under the engine lock, but `_docs` LOOKUPS are "
            "GIL-atomic dict.get snapshots — the tick and the "
            "emission paths resolve a doc with NO engine lock held "
            "and recheck identity under the doc's emission domain. "
            "Adoption BUILDS run lock-free and install under the "
            "engine lock with a recheck (the PR-4 idiom). Per-doc "
            "live state lives on `_LiveDoc` under `doc.emit`.",
    ),
    GuardedClass(
        "_LiveDoc", "hypermerge_tpu.backend.live", "doc.emit",
        guarded=(
            "state", "clock", "max_op", "history_len", "pending",
            "queued", "undecoded",
        ),
        atomic_read_ok=("tick_rows",),
        init_only=("doc", "cols"),
        doc="One doc's live write-plane state — decoded state, "
            "admission clock/pending set, queued tick changes, and "
            "the appended-but-undecoded marker — all under ITS OWN "
            "emission domain (backend/emission.py), never the engine "
            "lock: this is the relocated half of the old engine-lock "
            "guard rows. `cols` is rebound only at construction; its "
            "in-place appends happen under the domain. `tick_rows` — "
            "the phase-3 install-and-recheck token — is written under "
            "the domain (_tick_doc_locked); the tick loop's bucket "
            "grouping reads it as a GIL-atomic int snapshot with no "
            "domain held, and phase 3 rechecks it under the domain "
            "before installing.",
    ),
    GuardedClass(
        "_LiveDoc(engine)", "hypermerge_tpu.backend.live",
        "live.engine",
        guarded=("last_use", "demotable_at"),
        doc="The LRU bookkeeping the ENGINE owns about a live doc "
            "(use-clock stamp, demotability memo): read and written "
            "by the coordination passes under the engine lock.",
    ),
    GuardedClass(
        "EmissionDomain", "hypermerge_tpu.backend.emission", "doc.emit",
        init_only=("doc_id",),
        doc="The per-doc emission domain handle itself: one "
            "re-entrant doc.emit lock plus its identity. All real "
            "state it orders lives on the doc/_LiveDoc.",
    ),
    GuardedClass(
        "DocBackend", "hypermerge_tpu.backend.doc_backend", "doc",
        guarded=(
            "_lazy_loader", "_lazy_clock", "_lazy_len", "_snapshot_fn",
            "_snapshot_cache", "_replay_cache", "minimum_clock",
            "_live_adopted",
        ),
        atomic_read_ok=("opset", "_announced", "actor_id"),
        init_only=("id", "_notify", "_live", "ready", "local_q",
                   "remote_q", "emission"),
        doc="Per-doc CRDT/lazy state under the doc lock. `opset` and "
            "`_announced` transition once (None->OpSet, "
            "False->True) and are snapshot-read on the hot dispatch "
            "paths before taking any lock; `actor_id` is snapshot-read "
            "by Ready emissions (engine/emit lock held, doc lock not).",
    ),
    GuardedClass(
        "RepoBackend", "hypermerge_tpu.backend.repo_backend", "repo",
        guarded=("_bulk_deferred_syncs", "_bulk_feed_rows",
                 "_writer_actors", "_pending_ready"),
        atomic_read_ok=("docs", "actors"),
        init_only=(
            "path", "memory", "durability", "db", "clocks", "cursors",
            "key_store", "feed_info", "feeds", "id", "meta",
            "to_frontend", "recovery_report", "_dirty_marker",
            "_col_slab", "_query_handlers", "_gossip",
            "_syncs", "_cache_syncs", "_stores", "_store_debounce",
            "_gossip_fresh", "live", "serve",
        ),
        unguarded=("network", "file_store", "_file_server", "_closed",
                   "_actor_keys"),
        doc="docs/actors mutate under the repo lock; lookups are "
            "GIL-atomic dict.get snapshots on the receive/query hot "
            "paths. `network`/`file_store`/`_file_server` are "
            "set-once wiring installed before traffic flows; "
            "`_closed` is a monotonic shutdown latch; `_actor_keys` "
            "mirrors the sqlite keys table (insert-once per actor, "
            "GIL-atomic dict ops, sqlite is the durable truth).",
    ),
    GuardedClass(
        "RepoBackend(bulk)", "hypermerge_tpu.backend.repo_backend",
        "repo.bulk",
        guarded=("_pending_memo", "_bulk_t0", "_fetch_ctx",
                 "_summary_memo_bytes"),
        atomic_read_ok=("_summary_memo",),
        unguarded=(
            "_pending_summaries", "_rr_cached", "_rr_value",
            "_mesh_cached", "_mesh_value",
        ),
        doc="Bulk-load accumulators: one load at a time under "
            "repo.bulk (the barrier, fetch_bulk_summaries, takes it "
            "too). `_summary_memo` is read lock-free by pipeline "
            "classify and serve installs (GIL-atomic dict.get); "
            "`_pending_summaries` is appended by pipeline stage "
            "threads (GIL-atomic) and swapped whole under repo.bulk "
            "after the stage barrier joined them; the scheduler/mesh "
            "caches build once, idempotently, on first use.",
    ),
    GuardedClass(
        "RepoBackend(stats)", "hypermerge_tpu.backend.repo_backend",
        "repo.stats",
        atomic_read_ok=("last_bulk_stats",),
        doc="Stage timings accumulate from pipeline worker threads "
            "under repo.stats (_stat_add); bench/tools read the dict "
            "lock-free after the load settled.",
    ),
    GuardedClass(
        "ReadBatcher", "hypermerge_tpu.serve.batcher", "serve.batch",
        guarded=("_seq", "_closed"),
        atomic_read_ok=("_depth",),
        init_only=("_flush", "_cap", "_deb"),
        doc="Admission accounting under serve.batch; `depth` is a "
            "monitoring snapshot read.",
    ),
    GuardedClass(
        "OverloadController", "hypermerge_tpu.serve.overload",
        "serve.overload",
        guarded=("_tenants", "_last", "_pressure", "_thread",
                 "_closed"),
        atomic_read_ok=("_state",),
        init_only=("_signals", "_now", "_slo_s", "_tick_s", "_retry_s",
                   "_stretch_s", "_rate", "_burst", "_ladder", "_force",
                   "_m"),
        doc="The service plane's shared state: the tenant "
            "token-bucket table, the last signal sample, and the "
            "ticker lifecycle mutate under serve.overload (tick, "
            "admit_read, report). `_state` — the one question every "
            "hot path asks (am I shedding?) — is written under the "
            "lock by tick() and read as a GIL-atomic int snapshot by "
            "admit_read/defer_install/ack_extra_s. `_ladder` is a "
            "construction-time reference whose internals mutate only "
            "inside tick()'s critical section.",
    ),
    GuardedClass(
        "ResidencyCache", "hypermerge_tpu.serve.resident", "serve.cache",
        guarded=("_entries", "_evicted", "_use"),
        atomic_read_ok=("_bytes",),
        doc="The residency table mutates under serve.cache only "
            "(builds/uploads run outside it); `resident_bytes` is a "
            "monitoring snapshot read.",
    ),
    GuardedClass(
        "SessionSupervisor", "hypermerge_tpu.net.resilience", "net.sup",
        guarded=("_sessions",),
        atomic_read_ok=("_stopped",),
        init_only=("_dial", "_deliver", "_banned", "_m", "_connector"),
        unguarded=("_on_status",),
        doc="The outbound session table mutates under net.sup; "
            "`_stopped` is polled lock-free by every session thread's "
            "redial loop (and checked by the async-mode callback "
            "chain). `_on_status` is a set-once hook registered "
            "before sessions start; `_connector` is construction-time "
            "wiring selecting the async (event-loop) dial mode.",
    ),
    GuardedClass(
        "TcpSwarm", "hypermerge_tpu.net.tcp", "net.tcp.accept",
        guarded=("_accept_q", "_accept_idle", "_accept_workers"),
        init_only=("_async", "_loop"),
        doc="The bounded inbound-handshake pool of the legacy "
            "(thread-per-connection) stack: the accepted-socket queue "
            "and the idle/spawned worker counters mutate under "
            "net.tcp.accept (listener thread enqueues, pool workers "
            "dequeue, destroy() drains). `_async`/`_loop` are the "
            "construction-time transport-twin selection "
            "(HM_NET_ASYNC).",
    ),
    GuardedClass(
        "AioLoop", "hypermerge_tpu.net.aio", "net.aio",
        guarded=("_ready", "_timers"),
        init_only=("_sel", "_timer_seq", "_wake_r", "_wake_w",
                   "_worker_cap", "_thread"),
        doc="The shared event loop's submission state: the ready-"
            "callback deque and the timer heap mutate under net.aio "
            "(any thread submits, the loop thread drains). The "
            "selector itself is mutated ONLY on the loop thread "
            "(callers go through call_soon), so it needs no lock; "
            "the self-pipe write is a lock-free wakeup.",
    ),
    GuardedClass(
        "AioLoop(dispatch)", "hypermerge_tpu.net.aio",
        "net.aio.dispatch",
        guarded=("_dispatch_q", "_dispatch_idle", "_workers"),
        doc="The bounded dispatch pool (user-facing callbacks run "
            "here, never on the loop thread): the work queue and the "
            "idle/spawned counters mutate under net.aio.dispatch "
            "(offload() demand-spawns up to HM_AIO_DISPATCH workers).",
    ),
    GuardedClass(
        "AioDuplex", "hypermerge_tpu.net.aio", "net.aio.conn",
        guarded=("_outbox", "_out_inflight", "_tx_scheduled",
                 "_rx_pending", "_rx_scheduled", "_close_cbs",
                 "_ready_fired"),
        atomic_read_ok=("_out_bytes", "closed"),
        init_only=("_loop", "_sock", "_identity", "_on_ready",
                   "_out_cap", "_stall_s", "_drained", "_inbox",
                   "_session"),
        unguarded=("_shed", "_rx_eof", "_last_rx", "_last_progress",
                   "_rbuf", "_wbuf", "_registered", "_events",
                   "_counted", "_hs_timer", "_ka_timer", "_ka_misses",
                   "_ka_probe", "_hs_phase", "_hs_offer"),
        doc="One multiplexed connection: the plaintext outbox, the tx "
            "kick latch, the ordered inbound-dispatch deque and its "
            "exactly-one-drainer latch, the close-listener list, and "
            "the ready-once latch mutate under net.aio.conn (sender "
            "threads vs the loop thread vs dispatch workers). "
            "`_out_bytes`/`closed` are written under the lock and "
            "snapshot-read on the lock-free fast paths (shed check, "
            "early-outs). The unguarded block is LOOP-CONFINED state "
            "— read/write buffers, selector registration, the "
            "handshake machine, keepalive bookkeeping — touched only "
            "by loop callbacks after construction, plus the monotonic "
            "`_shed`/`_rx_eof` latches and the stall/liveness "
            "clocks, whose racing writers all move them the same "
            "direction.",
    ),
    GuardedClass(
        "NetworkPeer", "hypermerge_tpu.net.peer", "net.peer",
        guarded=("_pending",),
        init_only=("self_id", "id", "_on_active", "_on_inactive"),
        unguarded=("connection",),
        doc="`_pending` mutates under net.peer (accept/supervisor "
            "threads vs close-driven prunes). `connection` is the "
            "DOCUMENTED snapshot idiom: it can flip to None under "
            "churn, so every consumer snapshots it once "
            "(NetworkPeer.try_send) instead of check-then-use.",
    ),
    GuardedClass(
        "RoutingTable", "hypermerge_tpu.net.discovery.dht", "net.dht",
        guarded=("_buckets", "_replacements", "_probing"),
        init_only=("self_id", "k"),
        doc="The k-bucket array and per-bucket replacement caches "
            "mutate under net.dht only (observe/refresh/evict/closest "
            "from the UDP reader thread, lookup walkers, and timeout "
            "timers); liveness probes run outside it.",
    ),
    GuardedClass(
        "RecordStore", "hypermerge_tpu.net.discovery.dht",
        "net.dht.store",
        guarded=("_records",),
        doc="The signed announce-record table (reader thread stores, "
            "lookup walkers and lazy expiry read) mutates under "
            "net.dht.store; signature verification runs before the "
            "lock.",
    ),
    GuardedClass(
        "DhtNode", "hypermerge_tpu.net.discovery.dht", "net.dht.rpc",
        guarded=("_pending",),
        init_only=("table", "records", "_rpc_ids", "bootstrap",
                   "public_key", "id"),
        unguarded=("_closed", "_announce_seed", "_seed",
                   "_sign_cache", "_seed_hook", "_seeded"),
        doc="The pending-RPC correlation table mutates under "
            "net.dht.rpc (reader thread resolves, timers expire, "
            "senders register). `_closed` is a monotonic shutdown "
            "latch polled by the reader; `_announce_seed` is set-once "
            "wiring installed by set_identity before any join "
            "traffic; `_seed` is the construction-time node key. "
            "`_sign_cache` is driven only by the swarm maintenance "
            "thread (announce is its single caller; the boot-time "
            "set_announce_seed reset precedes any join traffic); "
            "`_seed_hook` is set-once wiring installed before "
            "traffic; `_seeded` dedup membership mutates only on the "
            "UDP reader thread.",
    ),
    GuardedClass(
        "DhtSwarm", "hypermerge_tpu.net.discovery.swarm",
        "net.dht.swarm",
        guarded=("_joined", "_targets", "_pass_waiters"),
        init_only=("tcp", "node", "_rng", "_kick", "_stop", "_thread"),
        unguarded=("_need",),
        doc="The joined-id table and the sampled active-view targets "
            "mutate under net.dht.swarm (join/leave callers vs the "
            "maintenance thread); dials and DHT walks run outside "
            "it. `_need` is set-once wiring (Network.set_swarm "
            "installs the demand hook before any join traffic).",
    ),
    GuardedClass(
        "GossipSampler", "hypermerge_tpu.net.discovery.gossip",
        "net.gossip",
        guarded=("_samples",),
        init_only=("fanout", "reshuffle_s", "_rng"),
        unguarded=("overload_ctl",),
        doc="The per-key sample table mutates under net.gossip; the "
            "hot broadcast paths hold it for dict bookkeeping only. "
            "`_rng` is only ever driven under the lock. "
            "`overload_ctl` is a set-once service-plane hook "
            "installed by Network wiring before traffic flows; the "
            "sample path snapshots the reference (GIL-atomic).",
    ),
    GuardedClass(
        "_FrontendHub", "hypermerge_tpu.net.ipc", "net.ipc.hub",
        guarded=("_conns", "_interest", "_next_key"),
        init_only=("_back", "_writers"),
        doc="The multi-frontend daemon's connection + doc-interest "
            "tables (accept/reader threads register and retire "
            "entries, the to_frontend router snapshots its targets) "
            "mutate under net.ipc.hub; socket sends run OUTSIDE it "
            "so a slow frontend cannot stall accepts or routing.",
    ),
    GuardedClass(
        "_ShardRouter", "hypermerge_tpu.net.ipc", "net.ipc.router",
        guarded=("_workers", "_pending", "_respawns", "_gen",
                 "_tele", "_next_tele"),
        init_only=("_repo_path", "_sock_base", "_n"),
        unguarded=("_closed", "_dispatch", "_interest"),
        doc="Worker slots, outage buffers, and in-flight telemetry "
            "fan-outs mutate under net.ipc.router (route threads vs "
            "the respawn supervisor vs worker reader threads); "
            "socket sends run OUTSIDE it. `_closed` is a monotonic "
            "shutdown latch; `_dispatch`/`_interest` are set-once "
            "hub wiring installed by start() before any worker "
            "spawns (traffic cannot precede them).",
    ),
    GuardedClass(
        "SlabPipeline", "hypermerge_tpu.backend.pipeline",
        "pipeline.pack_pool",
        guarded=("_pack_turn", "_pack_eof_claimed"),
        init_only=("docs", "prefetch", "classify", "pack", "dispatch",
                   "fetch", "slab", "fetch_workers", "pack_workers",
                   "pack_q", "disp_q", "fetch_q", "_q_gauges",
                   "abort"),
        unguarded=("total_slabs", "pack_busy", "pack_t0", "pack_t1",
                   "memo_hits", "fallbacks"),
        doc="The pack pool's ordered-emit state: the turn counter and "
            "the EOF claim mutate under pipeline.pack_pool (N workers "
            "race the pack queue, emit in slab order). `total_slabs` "
            "is a write-once latch the io thread publishes BEFORE the "
            "EOF token (the queue put/get is the happens-before edge "
            "to the one reader, the EOF-claiming worker). "
            "`pack_busy`/`pack_t0`/`pack_t1` are per-worker slots — "
            "single-writer by construction (worker w owns index w) — "
            "read only after the workers joined. "
            "`memo_hits`/`fallbacks` are appended by the single io "
            "thread and read after it joined.",
    ),
    GuardedClass(
        "SlabPipeline(err)", "hypermerge_tpu.backend.pipeline",
        "pipeline.err",
        atomic_read_ok=("error", "error_stage"),
        doc="First-error capture: _fail writes the winning (error, "
            "stage) pair under pipeline.err; the driver reads them "
            "lock-free after every stage joined.",
    ),
    GuardedClass(
        "FeedColumnCache", "hypermerge_tpu.storage.colcache",
        "store.colcache",
        guarded=(
            "_loaded", "_actors", "_keys", "_strings", "_floats",
            "_bigints", "_pending_tables", "_base_planes",
            "_base_meta", "_base_rows", "_row_chunks", "_pred_chunks",
            "_n_rows_total", "_n_preds_total", "_commits_arr",
            "_commits_new", "_cached",
        ),
        init_only=("_storage", "writer"),
        doc="The pack path's shared-memo audit row (HM_PACK_WORKERS "
            ">1): every interner table, chunk list, and the cached "
            "FeedColumns snapshot mutate under the feed's rlock only. "
            "Concurrent pack workers never reach these fields — "
            "columns() hands them an immutable snapshot whose table "
            "lists are COPIES taken under the lock.",
    ),
    GuardedClass(
        "FeedColumns", "hypermerge_tpu.storage.colcache",
        "store.colcache",
        unguarded=("rows",),
        doc="The shared snapshot pack workers read CONCURRENTLY. "
            "`rows` is a lazy idempotent latch: ensure_rows() derives "
            "the row matrix from the immutable planes and rebinds "
            "once (GIL-atomic); racing workers at worst duplicate the "
            "compute, never observe a torn value. The "
            "`_prefix_single_ok` bool ops/columnar caches on the "
            "object is the same idiom (set through a foreign "
            "receiver, so only this story covers it — the checkers "
            "cannot see it). Every other field is written by the "
            "cache build under store.colcache before the object "
            "escapes.",
    ),
    GuardedClass(
        "FileFeedStorage", "hypermerge_tpu.storage.feed",
        "store.feed_io",
        guarded=("_wfh", "_len_fh", "_fh_gen"),
        doc="The cached write handles (block log + .len sidecar) and "
            "the fault-harness generation they were opened under: "
            "shared between the appender (under its doc's emission "
            "domain + feed lock) and the WAL checkpoint thread's "
            "storage.sync() — every use, fsync, and drop serializes "
            "under store.feed_io, or interleaved seek/write could "
            "tear the sidecar and a drop could close an fd mid-fsync.",
    ),
    GuardedClass(
        "CursorStore", "hypermerge_tpu.storage.stores", "store.cursors",
        guarded=("_mem", "_by_actor", "_del_gen"),
        atomic_read_ok=("_hydrated",),
        init_only=("db",),
        doc="The write-through cursor mirror mutates under "
            "store.cursors; `_hydrated` membership is the documented "
            "GIL-atomic fast path of _ensure_hydrated (writes merge "
            "under the lock).",
    ),
    GuardedClass(
        "DurabilityManager", "hypermerge_tpu.storage.durability",
        "store.durability",
        guarded=("_dirty", "_closed"),
        atomic_read_ok=("_flusher", "wal"),
        unguarded=("_wal_suspended", "journalless_write_cb"),
        doc="The tier-1 dirty set and shutdown latch mutate under "
            "store.durability; flush_now snapshots the flusher handle "
            "lock-free (it is installed once and cleared at close). "
            "`wal` is attached once at repo open (before traffic) and "
            "snapshot-read on every journal_append. `_wal_suspended` "
            "is toggled only inside the single-threaded recovery "
            "replay window (scrub runs before any doc opens). "
            "`journalless_write_cb` is a fire-once latch set at repo "
            "open; a racing double-clear at worst double-fires the "
            "idempotent stamp invalidation.",
    ),
    GuardedClass(
        "WriteAheadLog", "hypermerge_tpu.storage.wal", "store.wal",
        guarded=(
            "_fh", "_end", "_file_bytes", "_synced", "_syncing",
            "_dirty_names", "_ckpt_pending", "_ckpt_running",
            "_closed",
        ),
        init_only=("path", "session", "tier", "_max_bytes",
                   "_window_s"),
        unguarded=("ack_pacer",),
        doc="The shared journal: file handle (rebound at checkpoint "
            "rotation), append end offset, the group-commit "
            "synced/syncing handshake, the session dirty-name ledger "
            "and the checkpoint-pending storage set all mutate under "
            "store.wal. The commit fsync snapshots the handle under "
            "the lock and syncs OUTSIDE it. `ack_pacer` is a "
            "set-once service-plane hook installed at backend wiring "
            "before any writer exists; the commit leader snapshots "
            "the reference once per window (GIL-atomic).",
    ),
)

# Methods whose WHOLE BODY runs with the named lock held — the Clang
# `REQUIRES` annotation as manifest data. Every caller acquires the
# lock; the static rule treats the body as a held region. (The runtime
# detector needs no such hint: it sees the actual held stack.)
REQUIRES: Dict[Tuple[str, str], str] = {
    ("LiveApplyEngine", "_bump_use"): "live.engine",
    ("LiveApplyEngine", "_tick_doc_locked"): "doc.emit",
    ("LiveApplyEngine", "_catch_up_locked"): "doc.emit",
    ("LiveApplyEngine", "_demote_candidates_locked"): "live.engine",
    ("LiveApplyEngine", "_demote_locked"): "live.engine",
    ("WriteAheadLog", "_append_dirty_locked"): "store.wal",
    ("WriteAheadLog", "_write_locked"): "store.wal",
    ("FileFeedStorage", "_append_io_locked"): "store.feed_io",
    ("FileFeedStorage", "_check_gen"): "store.feed_io",
    ("FileFeedStorage", "_write_handle"): "store.feed_io",
    ("FileFeedStorage", "_drop_write_handles"): "store.feed_io",
    ("FileFeedStorage", "_write_len"): "store.feed_io",
    ("DocBackend", "_minimum_satisfied"): "doc",
    ("RepoBackend", "_load_documents_bulk_locked"): "repo.bulk",
    ("RepoBackend", "_load_slabs_serial"): "repo.bulk",
    ("RepoBackend", "_load_slabs_pipelined"): "repo.bulk",
    ("RepoBackend", "_memoize_summaries"): "repo.bulk",
    ("ResidencyCache", "_note_evicted"): "serve.cache",
    ("FeedColumnCache", "_ensure_loaded"): "store.colcache",
    ("FeedColumnCache", "_apply_tables"): "store.colcache",
    ("FeedColumnCache", "_intern"): "store.colcache",
    ("FeedColumnCache", "_take_pending"): "store.colcache",
    ("FeedColumnCache", "_total_rows"): "store.colcache",
    ("FeedColumnCache", "_total_preds"): "store.colcache",
    ("FeedColumnCache", "_encode"): "store.colcache",
    ("FeedColumnCache", "_encode_value"): "store.colcache",
    ("FeedColumnCache", "_tables_blob"): "store.colcache",
    ("CursorStore", "_repo"): "store.cursors",
    ("CursorStore", "_absorb"): "store.cursors",
    ("OverloadController", "_tenant_row"): "serve.overload",
}


class AttrGuard(NamedTuple):
    cls: str
    module: str
    guard: str
    attr: str
    escape: str  # "", "atomic_read_ok", "init_only", "unguarded"


def _flatten() -> Dict[Tuple[str, str], AttrGuard]:
    out: Dict[Tuple[str, str], AttrGuard] = {}
    for gc in GUARDS:
        # "RepoBackend(bulk)" style rows split ONE class's fields
        # across guards; the real class name precedes the "("
        cls = gc.cls.split("(", 1)[0]
        for escape, attrs in (
            ("", gc.guarded),
            ("atomic_read_ok", gc.atomic_read_ok),
            ("init_only", gc.init_only),
            ("unguarded", gc.unguarded),
        ):
            for attr in attrs:
                key = (cls, attr)
                if key in out:
                    raise ValueError(
                        f"duplicate guard entry for {cls}.{attr}"
                    )
                out[key] = AttrGuard(cls, gc.module, gc.guard, attr,
                                     escape)
    return out


BY_CLS_ATTR: Dict[Tuple[str, str], AttrGuard] = _flatten()
CLASSES: Tuple[str, ...] = tuple(
    sorted({cls for cls, _attr in BY_CLS_ATTR})
)


def guard_for(cls: str, attr: str) -> Optional[AttrGuard]:
    """The declared guard entry for (class, attribute), or None."""
    return BY_CLS_ATTR.get((cls, attr))


def validate() -> None:
    """Manifest self-check (run by tests): guards declared in the
    lock hierarchy, REQUIRES targets sane, no duplicate fields."""
    for gc in GUARDS:
        if gc.guard not in LOCK_BY_NAME:
            raise ValueError(
                f"{gc.cls}: guard {gc.guard!r} is not a lock class "
                f"declared in analysis/hierarchy.py"
            )
        if not gc.module.startswith("hypermerge_tpu."):
            raise ValueError(f"{gc.cls}: module {gc.module!r} outside "
                             f"the package")
        if gc.unguarded and not gc.doc.strip():
            raise ValueError(
                f"{gc.cls}: unguarded fields need the story in doc"
            )
    _flatten()  # raises on duplicates
    for (cls, _method), lock in REQUIRES.items():
        if lock not in LOCK_BY_NAME:
            raise ValueError(
                f"REQUIRES[{cls}]: unknown lock class {lock!r}"
            )
        if not any(c.split("(", 1)[0] == cls for c in
                   (g.cls for g in GUARDS)):
            raise ValueError(
                f"REQUIRES names class {cls!r} absent from GUARDS"
            )


def markdown_table() -> str:
    """The README guard-map table (tools/lint.py --guards-table)."""
    lines = [
        "| Class | Guard | Escape | Fields |",
        "| --- | --- | --- | --- |",
    ]
    for gc in GUARDS:
        cls = gc.cls.split("(", 1)[0]
        for escape, attrs in (
            ("—", gc.guarded),
            ("atomic_read_ok", gc.atomic_read_ok),
            ("init_only", gc.init_only),
            ("unguarded", gc.unguarded),
        ):
            if not attrs:
                continue
            fields = ", ".join(f"`{a}`" for a in attrs)
            lines.append(
                f"| `{cls}` | `{gc.guard}` | {escape} | {fields} |"
            )
    return "\n".join(lines)
