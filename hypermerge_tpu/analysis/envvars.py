"""The HM_* environment-variable registry.

Every `os.environ` read of an `HM_`-prefixed name anywhere in the
package (plus tools/, scripts/, bench.py, __graft_entry__.py) must be
declared here exactly once — the `env-registry` lint rule
(analysis/linter.py) fails tier-1 on an undeclared read, on a registry
entry nothing reads (stale), and on a registry entry missing from the
README's env-var table. This is the one place a knob's default and
meaning live; the README table is generated from the same data
(`python tools/lint.py --env-table`).

`default` is the literal fallback the reading site uses (None for
presence-style flags where unset means off). Registering here is
documentation, not parsing — call sites keep reading os.environ
directly so hot paths stay allocation-free.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple


class EnvVar(NamedTuple):
    name: str
    default: Optional[str]  # None: presence-style flag, unset = off
    doc: str


REGISTRY: Tuple[EnvVar, ...] = (
    # -- live apply engine ---------------------------------------------
    EnvVar("HM_LIVE", "1", "Live apply engine on the incremental path "
           "(0 = host OpSet twin)."),
    EnvVar("HM_LIVE_TICK_MS", "2", "Debounce window of the live tick "
           "(leading-edge pad of a burst)."),
    EnvVar("HM_LIVE_TICK_MAX_MS", "25", "Adaptive ceiling of the live "
           "tick window under sustained load."),
    EnvVar("HM_LIVE_INC_BUDGET", "2000000", "Max cells (rows x lanes) a "
           "small tick applies host-side before a catch-up dispatch."),
    EnvVar("HM_LIVE_MAX_BYTES", "0", "Resident-bytes cap across adopted "
           "docs' live columns; LRU demotes back to lazy (0 = unbounded)."),
    EnvVar("HM_DEVICE_MIN_CELLS", "131072", "Below this many cells a "
           "materialize runs host-side instead of a device dispatch."),
    # -- bulk cold open / pipeline -------------------------------------
    EnvVar("HM_BULK_SLAB", "4096", "Docs per bulk-load slab (the "
           "streaming pipeline's unit of IO/pack/dispatch)."),
    EnvVar("HM_PIPELINE", None, "Force the streaming pipeline on (1) or "
           "off (0); unset = auto (on when the native pack drops the "
           "GIL)."),
    EnvVar("HM_PIPELINE_DEPTH", "2", "Bounded depth of each pipeline "
           "stage queue."),
    EnvVar("HM_FETCH_WORKERS", "4", "Summary-fetch workers (sized to "
           "device count by the bulk loader)."),
    EnvVar("HM_PACK_WORKERS", "0", "Pack-pool threads for the bulk "
           "pipeline (slab-granular, order-preserving); 0 = auto: "
           "min(4, cores) when the native pack is concurrency-safe, "
           "else 1."),
    EnvVar("HM_DEVICE_PACK", "0", "Run the fast-path pack as a jitted "
           "device kernel (ops/pack_kernels.py); falls back native -> "
           "numpy, bit-identical."),
    EnvVar("HM_LOAD_THREADS", "8", "Parallel sidecar prefetch threads "
           "for bulk document loads."),
    EnvVar("HM_FAST_OPEN", "1", "Serve single-doc opens from the "
           "columnar sidecar when possible (0 = full feed replay)."),
    EnvVar("HM_SUMMARY_MEMO_MB", "256", "Byte-bounded LRU of per-doc "
           "summary rows; clean docs skip pack+dispatch+fetch "
           "(0 = disabled)."),
    EnvVar("HM_ASYNC_SUMMARY_COPY", "1", "Overlap the device->host "
           "summary copy with the next slab's dispatch."),
    # -- mesh / multi-chip ---------------------------------------------
    EnvVar("HM_MESH", "1", "Multi-device mesh programs (0 = single "
           "device)."),
    EnvVar("HM_SLAB_RR", "1", "Round-robin whole slabs across devices "
           "(0 = sharded_full lockstep)."),
    EnvVar("HM_RR_DEPTH", "2", "Per-device in-flight slab bound of the "
           "round-robin scheduler."),
    EnvVar("HM_RR_LEAST_LOADED", "0", "Shortest-queue-first slab "
           "placement instead of strict round-robin."),
    EnvVar("HM_ICI_PALLAS", "1", "Pallas async remote-copy ring for "
           "collective gathers on real ICI (0 = lax.all_gather twin)."),
    EnvVar("HM_COMPILE_CACHE", None, "Persistent XLA compile-cache "
           "directory override (default ~/.cache/hypermerge_tpu/xla; "
           "empty disables)."),
    EnvVar("HM_COMPILE_CACHE_FORCE", "0", "Force-enable the persistent "
           "XLA compile cache even on CPU."),
    # -- storage --------------------------------------------------------
    EnvVar("HM_SLAB", "1", "Columnar sidecars in one mmap'd corpus slab "
           "file (0 = per-feed .cols2 files)."),
    EnvVar("HM_SLAB_SLACK", "0.25", "Dead-byte fraction that triggers "
           "slab compaction."),
    EnvVar("HM_CKPT_TAIL", "64", "Sidecar tail length that triggers a "
           "fresh column image (checkpoint) instead of a delta append."),
    EnvVar("HM_BLOCK_CODEC", None, "Block codec override (zlib); unset "
           "= raw."),
    EnvVar("HM_FSYNC", "0", "Durability tier: 0 none, 1 group-fsync "
           "window, 2 fsync per append."),
    EnvVar("HM_FSYNC_MS", "25", "Group-fsync window for HM_FSYNC=1."),
    EnvVar("HM_WAL", "1", "Shared per-repo write-ahead journal "
           "(storage/wal.py): a durable commit window is ONE "
           "sequential append + ONE fsync regardless of dirty feed "
           "count (0 = legacy per-feed fsyncs)."),
    EnvVar("HM_WAL_MS", "0", "Group-commit gather window of the WAL "
           "leader fsync (tier-2 acks and HM_ACK_DURABLE tier-1 acks; "
           "0 = sync immediately; concurrent committers still share "
           "one fsync)."),
    EnvVar("HM_ACK_DURABLE", "0", "=1 makes a local edit's ack "
           "DURABLE at HM_FSYNC=1: the LocalPatch echo waits for the "
           "WAL group commit covering its append (N writers share "
           "one fsync per HM_WAL_MS window)."),
    EnvVar("HM_WAL_MAX_BYTES", "67108864", "Journal size that "
           "triggers a checkpoint (per-feed logs fsynced off the ack "
           "path, journal reset to its dirty-name ledger)."),
    EnvVar("HM_RECOVER", "1", "Whole-repo recovery-on-open after a "
           "crash marker (0 = skip; tools/scrub.py --dry-run sets it)."),
    EnvVar("HM_SIGN_INTERVAL", "1024", "Appends between persisted "
           "merkle signature records (lazy signing)."),
    EnvVar("HM_ALLOW_UNSIGNED_FEEDS", None, "=1 serves feeds with no "
           "signature chain (tests/migration only)."),
    EnvVar("HM_SPARSE_CAP", "1024", "Bound of the out-of-order "
           "verified-block side buffer per feed."),
    EnvVar("HM_SPARSE_WANTED_CAP", "8192", "Bound of the outstanding "
           "sparse range-request set per feed (furthest-out shed "
           "first)."),
    EnvVar("HM_STORE_DEBOUNCE", "1", "Debounced clock/cursor sqlite "
           "flusher (0 = write-through)."),
    EnvVar("HM_STORE_FLUSH_MS", "5", "Window of the clock/cursor store "
           "flusher."),
    EnvVar("HM_CACHE_FLUSH_MS", "5", "Window of the deferred columnar "
           "sidecar sync."),
    EnvVar("HM_SYNC_FLUSH_MS", "2", "Window of the inbound-sync "
           "application debouncer."),
    EnvVar("HM_CLOCK_MIRROR", "1", "Device-resident clock mirror for "
           "bulk union/dominated queries."),
    # -- read-serving tier ---------------------------------------------
    EnvVar("HM_SERVE", "1", "HBM-resident read-serving tier: reads "
           "answer from batched device query kernels over resident "
           "summary columns (0 = per-request host materialization "
           "twin)."),
    EnvVar("HM_SERVE_MAX_BYTES", "268435456", "Resident-bytes budget "
           "of the serving tier (LRU eviction), applied to the device "
           "residency cache and the host fallback memo each."),
    EnvVar("HM_SERVE_BATCH_MS", "1", "Debounce window of the read "
           "batcher: concurrent reads inside it coalesce into one "
           "batched kernel dispatch."),
    EnvVar("HM_SERVE_QUEUE", "4096", "Bound of the read admission "
           "queue; overflow is a dedicated service-plane signal "
           "(serve.overload_shed) answered via the host path or a "
           "typed refusal, never an unbounded queue."),
    # -- service plane (overload control) -------------------------------
    EnvVar("HM_SERVICE", "1", "Overload controller (serve/overload.py "
           "brownout ladder): signal-driven admission control at the "
           "read front door plus WAL ack pacing (0 = no controller)."),
    EnvVar("HM_SERVICE_TICK_MS", "50", "Period of the controller's "
           "signal-sampling tick."),
    EnvVar("HM_SERVICE_P99_SLO_MS", "50", "Serve-read p99 SLO the "
           "pressure signal normalizes against (pressure 1.0 = p99 "
           "at SLO)."),
    EnvVar("HM_SERVICE_RETRY_AFTER_MS", "100", "Floor of the "
           "retry-after a typed Overload refusal carries."),
    EnvVar("HM_SERVICE_ACK_STRETCH_MS", "25", "Extra group-commit "
           "gather window while SHED — durable-write backpressure "
           "(acks pace down; nothing acked is dropped)."),
    EnvVar("HM_SERVICE_FORCE", None, "Pin the ladder state "
           "(healthy|brownout|shed) — deterministic tests and drills; "
           "unset = signal-driven."),
    EnvVar("HM_BROWNOUT_HI", "1.0", "Pressure watermark at/above "
           "which consecutive ticks escalate the ladder one rung."),
    EnvVar("HM_BROWNOUT_LO", "0.5", "Pressure watermark at/below "
           "which consecutive ticks de-escalate one rung (the dead "
           "band between LO and HI holds the rung: no flapping)."),
    EnvVar("HM_BROWNOUT_UP_TICKS", "3", "Consecutive over-HI ticks "
           "required to escalate."),
    EnvVar("HM_BROWNOUT_DOWN_TICKS", "10", "Consecutive under-LO "
           "ticks required to de-escalate (slower down than up: "
           "recovery must be proven, not hoped)."),
    EnvVar("HM_QUOTA_READS_S", "512", "Per-tenant token-bucket refill "
           "rate enforced at the front door while SHED (reads/s)."),
    EnvVar("HM_QUOTA_BURST", "64", "Per-tenant token-bucket burst "
           "capacity."),
    # -- write plane (hub daemon) ---------------------------------------
    EnvVar("HM_NATIVE_CODEC", "1", "Binary change frames (native "
           "GIL-free encode when built, bit-identical Python twin "
           "otherwise) for small change blocks; 0 = write JSON blocks "
           "(readers always handle both)."),
    EnvVar("HM_HUB_WRITERS", "1", "Hub daemon many-writer plane: tag "
           "Create/Open/NeedsActorId with the connection key so each "
           "writing connection gets its OWN per-doc actor; 0 = legacy "
           "one-writer-per-doc protocol."),
    EnvVar("HM_WORKERS", "0", "Hub daemon worker processes: >0 shards "
           "docs across N per-doc-range net.ipc worker subprocesses "
           "(own repo shard, engine, and WAL each) behind the hub; "
           "0 = single in-process backend."),
    EnvVar("HM_WORKER_RESPAWN_MS", "200", "Supervision backoff before "
           "a dead worker process is reaped and respawned on its "
           "shard (journal-prefix recovery replays acked edits)."),
    # -- network --------------------------------------------------------
    EnvVar("HM_DHT_BOOTSTRAP", None, "Comma-separated host:port DHT "
           "bootstrap nodes (net/discovery/) for DhtSwarm/DhtNode."),
    EnvVar("HM_DHT_K", "16", "Kademlia k: contacts per routing bucket "
           "and width of lookup frontiers/replica sets."),
    EnvVar("HM_DHT_ALPHA", "3", "Concurrent probes per iterative "
           "lookup round."),
    EnvVar("HM_DHT_RPC_TIMEOUT_S", "1", "UDP DHT RPC timeout (an "
           "unanswered liveness ping evicts the bucket LRU)."),
    EnvVar("HM_DHT_TTL_S", "120", "Announce record time-to-live; a "
           "crashed peer's stale address evaporates within one TTL."),
    EnvVar("HM_DHT_ANNOUNCE_S", "30", "Re-announce period for joined "
           "ids with announce posture (keep well under HM_DHT_TTL_S)."),
    EnvVar("HM_DHT_LOOKUP_S", "10", "Lookup refresh period for joined "
           "ids with lookup posture (resamples the active view)."),
    EnvVar("HM_DHT_TARGETS", "4", "Bounded active view: max supervised "
           "dials per joined id out of the announcers a lookup found "
           "(0 = dial every announcer)."),
    EnvVar("HM_GOSSIP_FANOUT", "8", "Per-doc active replication/gossip "
           "fanout cap (random peer subset; 0 = broadcast to every "
           "peer). Anti-entropy sweeps stay unsampled."),
    EnvVar("HM_GOSSIP_RESHUFFLE_S", "5", "How long a gossip sample "
           "stays fixed before reshuffling to a fresh peer subset."),
    EnvVar("HM_GOSSIP_FLUSH_MS", "10", "Window of the cursor/clock "
           "gossip broadcast debouncer."),
    EnvVar("HM_GOSSIP_FRESH", "1", "Overlay pending store rows onto "
           "gossip so it never advertises stale cursors."),
    EnvVar("HM_REPL_CHUNK", "1024", "Blocks per replication data "
           "frame."),
    EnvVar("HM_REPL_CHUNK_BYTES", "8388608", "Byte bound per "
           "replication data frame."),
    EnvVar("HM_REPL_FLUSH_MS", "2", "Window of the replication live-"
           "tail debouncer."),
    EnvVar("HM_REPL_FLUSH_MAX_MS", "25", "Adaptive ceiling of the "
           "replication flush window."),
    EnvVar("HM_ANTIENTROPY_S", "30", "Period of the FeedLength "
           "re-announce sweep (bounds staleness under frame loss; "
           "0 = off)."),
    EnvVar("HM_TCP_OUTBOX_MB", "64", "Per-connection outbound buffer "
           "cap; exceeding it sheds the connection."),
    EnvVar("HM_TCP_STALL_S", "10", "Writer-thread no-progress bound "
           "before a connection is shed."),
    EnvVar("HM_TCP_PLAINTEXT", None, "=1 disables the encrypted "
           "session (tests only)."),
    EnvVar("HM_NET_AUTH", "1", "Require peer identity proof at "
           "accept/dial."),
    EnvVar("HM_NET_PING_S", "15", "Keepalive probe period (0 = off)."),
    EnvVar("HM_NET_PING_MISSES", "3", "Unanswered probes before a "
           "half-open connection is shed."),
    EnvVar("HM_DIAL_TIMEOUT_S", "10", "Bound on one dial+handshake "
           "attempt."),
    EnvVar("HM_REDIAL_BASE_MS", "250", "Base of the supervised-redial "
           "full-jitter backoff."),
    EnvVar("HM_REDIAL_MAX_S", "30", "Cap of the supervised-redial "
           "backoff."),
    EnvVar("HM_REDIAL_RESET_S", "1", "Connection must survive this "
           "long before the backoff resets."),
    EnvVar("HM_INFO_TIMEOUT_S", "20", "Reap connections whose Info "
           "exchange never completes."),
    EnvVar("HM_FAULT", None, "Deterministic network fault spec "
           "(seed:events...) auto-applied to every swarm."),
    EnvVar("HM_NET_ASYNC", "0", "=1 multiplexes every TCP connection "
           "onto the process's selector event loop (net/aio.py): "
           "non-blocking sockets, loop-driven handshakes and dials, "
           "keepalives on one timer wheel — O(1) threads per daemon "
           "instead of ~4 per peer. =0 keeps the wire-compatible "
           "thread-per-connection twin."),
    EnvVar("HM_AIO_DISPATCH", "8", "Bounded worker pool that runs "
           "user-facing callbacks off the event loop thread "
           "(HM_NET_ASYNC=1)."),
    EnvVar("HM_TCP_ACCEPT_POOL", "8", "Bounded inbound-handshake "
           "workers of the thread-per-connection stack; an accept "
           "storm queues instead of spawning unbounded threads."),
    EnvVar("HM_CURSOR_DELTA", "1", "Delta cursor gossip: steady-state "
           "frames carry only actors whose clock advanced since the "
           "last frame on that connection (full frame on "
           "(re)connect; repair paths always full). =0 sends full "
           "maps every frame."),
    EnvVar("HM_DHT_PUSH_SEED", "0", "=1 push-seeds announced docs to "
           "the DHT's k-closest nodes at announce time (they open "
           "the doc and serve the cold-join first wave)."),
    EnvVar("HM_FILE_FETCH_TIMEOUT_S", "15", "Hyperfile range-fetch "
           "timeout."),
    # -- telemetry / analysis ------------------------------------------
    EnvVar("HM_TRACE", None, "Span-trace output path (Chrome trace "
           "JSON, written at exit)."),
    EnvVar("HM_TRACE_RING", "65536", "Span ring capacity."),
    EnvVar("HM_LOCKDEP", "0", "=1 instruments every factory-made lock: "
           "records acquisition order, reports potential deadlock "
           "cycles + held-across-blocking-call violations "
           "(analysis/lockdep.py)."),
    EnvVar("HM_RACEDEP", "0", "=1 wraps the guard manifest's declared "
           "attributes (analysis/guards.py) in Eraser-style lockset "
           "descriptors: a shared field no lock consistently guards "
           "is reported without the race firing (implies "
           "HM_LOCKDEP)."),
    EnvVar("HM_RACEDEP_SAMPLE", "1", "Track every Nth "
           "(object, attribute) under HM_RACEDEP=1 (1 = all; raise "
           "to bound overhead on huge corpora)."),
    # -- native / tools -------------------------------------------------
    EnvVar("HM_NATIVE_PACK", "1", "Native C++ pack kernel (0 = numpy "
           "twin)."),
    EnvVar("HM_NO_NATIVE", None, "Presence disables loading/building "
           "the native library entirely."),
    EnvVar("HM_DRYRUN_DOCS", "2048", "Docs for the graft-entry dryrun "
           "corpus."),
    EnvVar("HM_DRYRUN_OPS", "512", "Ops per doc for the graft-entry "
           "dryrun corpus."),
)

BY_NAME: Dict[str, EnvVar] = {v.name: v for v in REGISTRY}


def validate() -> None:
    """Registry self-check: unique names, every entry documented."""
    if len(BY_NAME) != len(REGISTRY):
        raise ValueError("duplicate HM_* names in the env registry")
    for v in REGISTRY:
        if not v.name.startswith("HM_"):
            raise ValueError(f"{v.name}: registry is for HM_* names")
        if not v.doc.strip():
            raise ValueError(f"{v.name}: missing description")


def markdown_table() -> str:
    """The README env-var table (tools/lint.py --env-table emits it)."""
    lines = [
        "| Variable | Default | Meaning |",
        "| --- | --- | --- |",
    ]
    for v in REGISTRY:
        default = "(unset)" if v.default is None else f"`{v.default}`"
        lines.append(f"| `{v.name}` | {default} | {v.doc} |")
    return "\n".join(lines)
