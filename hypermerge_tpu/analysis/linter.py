"""Static invariant linter: machine-checks the concurrency rules this
repo used to enforce by comment.

One AST pass over the whole tree (the package, tools/, scripts/,
bench.py, __graft_entry__.py), driven by the declared rule data in
`analysis/hierarchy.py` and `analysis/envvars.py`:

- **lock-order** — nested `with` acquisitions must follow the declared
  rank order (doc.emit -> engine -> doc -> repo -> actor -> store.*;
  leaves nest nothing), and no ENGINE_ENTRYPOINTS call may run under a
  lock ranked below the engine (the repo->engine inversion that made
  the open()/Ready deadlock).
- **no-block** — no blocking primitive (fsync / socket send / sqlite
  commit / join / sleep / first-wait) lexically inside a `with` region
  holding a no-block class (the emission locks). The runtime half
  (`lockdep.blocking`) catches the interprocedural cases this lexical
  rule cannot see.
- **churn-send** — no direct `X.connection.send(...)` /
  `X.connection.open_channel(...)` outside net/peer.py:
  `NetworkPeer.try_send` is THE churn-safe send idiom (`connection`
  can flip to None between a check and the send).
- **env-registry** — every `os.environ` read of an `HM_*` name must be
  declared in `analysis/envvars.py`, with the call-site default
  matching the registered one; registry entries nothing reads, and
  entries missing from the README table, are violations too.
- **telemetry-name** — registry series created with a literal name
  must match the `subsystem.metric` dotted convention
  (`live.ticks`, `net.tcp.frames_tx`); the runtime half asserts the
  same at registry-creation time under HM_LOCKDEP=1.
- **raw-lock** — every `threading.Lock()/RLock()/Condition()` creation
  in the package must go through `analysis.lockdep.make_lock /
  make_rlock / make_condition` (with a class declared in the
  manifest), so runtime lockdep sees every lock. Bare test/analysis
  code is exempt.
- **guarded-attr** — every `self.<attr>` read/write of an attribute
  declared in the guard manifest (`analysis/guards.py`, the
  GUARDED_BY map) must sit lexically inside a `with` of its declared
  guard or inside a `guards.REQUIRES` method. Writes are hard errors;
  reads may satisfy the `atomic_read_ok` escape; `init_only` fields
  flag any write outside `__init__`. Tree runs also flag stale
  manifest entries and classes missing from the README guard table.
  The runtime half (`HM_RACEDEP=1` lockset descriptors) covers the
  non-`self` receivers and interprocedural flows this lexical rule
  cannot see.

Suppression requires a justification, either inline —

    ...  # lint: allow(no-block) — <why this one is safe>

— or as an entry in `analysis/suppressions.py`. A suppression with an
empty justification, or a file entry matching nothing, is itself a
violation: the suppressions file cannot silently rot.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from . import guards as guardsmod
from . import suppressions as suppmod
from .envvars import BY_NAME as ENV_BY_NAME, REGISTRY as ENV_REGISTRY
from .hierarchy import (
    BLOCKING_CALLS,
    BY_NAME as LOCK_BY_NAME,
    ENGINE_ENTRYPOINTS,
    LEAVES,
    NO_BLOCK,
    RANKED,
    TELEMETRY_NAME_RE,
)

RULES = (
    "lock-order",
    "no-block",
    "churn-send",
    "env-registry",
    "telemetry-name",
    "raw-lock",
    "guarded-attr",
    "suppression",
)

# method names that MUTATE the container a guarded field holds — for
# the guarded-attr rule, `self._docs.pop(...)` is a WRITE to the
# field's state, not a read (field-level granularity would otherwise
# let `atomic_read_ok` excuse a lock-free mutation)
_MUTATORS = frozenset(
    {
        "append", "appendleft", "add", "pop", "popitem", "clear",
        "remove", "discard", "insert", "extend", "setdefault",
        "move_to_end",
    }
)

_NAME_RE = TELEMETRY_NAME_RE
_PREFIX_RE = re.compile(r"^[a-z][a-z0-9_]*\.")
_ALLOW_RE = re.compile(
    r"#\s*lint:\s*allow\(([a-z-]+)\)\s*(?:[-—–:]+\s*(.*))?$"
)

# receivers we trust to be the metrics registry (telemetry-name rule)
_REGISTRY_RECEIVERS = {"telemetry", "reg", "registry", "REGISTRY"}
_ENGINE_RANK = RANKED["live.engine"]


class Violation(NamedTuple):
    rule: str
    path: str  # repo-relative
    line: int
    msg: str
    suppressed: bool
    justification: str = ""

    def format(self) -> str:
        mark = " [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.msg}{mark}"


# ---------------------------------------------------------------------------
# scope


def repo_root() -> str:
    """The tree the linter covers (parent of the package dir)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_files(root: Optional[str] = None) -> List[str]:
    root = root or repo_root()
    out: List[str] = []
    pkg = os.path.join(root, "hypermerge_tpu")
    for base in (pkg, os.path.join(root, "tools"),
                 os.path.join(root, "scripts")):
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for fn in ("bench.py", "__graft_entry__.py"):
        p = os.path.join(root, fn)
        if os.path.exists(p):
            out.append(p)
    return out


def _rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(path, root)
    except ValueError:  # pragma: no cover - windows drives
        return path


def _in_package(rel: str) -> bool:
    return rel.replace(os.sep, "/").startswith("hypermerge_tpu/")


# ---------------------------------------------------------------------------
# lock-expression resolution


class _LockTable:
    """Maps lock-holding expressions to manifest classes, derived from
    the factory call sites themselves (`self._x = make_rlock("cls")`):
    the code is the single source of truth, the linter just reads it.

    Resolution for `with` items:
      - `self.<attr>`     -> exact (module class, attr) binding
      - `<name>.<attr>`   -> by attr, when the attr is unique tree-wide
      - `<name>`          -> module-level binding
      - `<x>.emission`    -> doc.emit (the per-doc EmissionDomain)
    """

    def __init__(self) -> None:
        self.by_class_attr: Dict[Tuple[str, str], str] = {}
        self.by_attr: Dict[str, Set[str]] = {}
        self.module_names: Dict[Tuple[str, str], str] = {}

    def learn(self, rel: str, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for sub in ast.walk(node):
                cls = self._factory_class(sub)
                if cls is None:
                    continue
                for tgt in sub.targets:  # type: ignore[attr-defined]
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        self.by_class_attr[(node.name, tgt.attr)] = cls
                        self.by_attr.setdefault(tgt.attr, set()).add(cls)
        for node in ast.walk(tree):
            cls = self._factory_class(node)
            if cls is None:
                continue
            for tgt in node.targets:  # type: ignore[attr-defined]
                if isinstance(tgt, ast.Name):
                    self.module_names[(rel, tgt.id)] = cls
                    self.by_attr.setdefault(tgt.id, set()).add(cls)

    @staticmethod
    def _factory_class(node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Assign):
            return None
        call = node.value
        if not isinstance(call, ast.Call) or not call.args:
            return None
        fn = call.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in ("make_lock", "make_rlock", "make_condition"):
            return None
        arg = call.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        return None

    def resolve(
        self, expr: ast.AST, rel: str, cls_name: Optional[str]
    ) -> Optional[str]:
        if isinstance(expr, ast.Call):
            return None
        if isinstance(expr, ast.Attribute):
            if expr.attr == "emission":
                return "doc.emit"
            if (
                isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and cls_name is not None
            ):
                hit = self.by_class_attr.get((cls_name, expr.attr))
                if hit is not None:
                    return hit
            owners = self.by_attr.get(expr.attr, set())
            if len(owners) == 1:
                return next(iter(owners))
            return None
        if isinstance(expr, ast.Name):
            return self.module_names.get((rel, expr.id))
        return None


# ---------------------------------------------------------------------------
# helpers


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted rendering of an attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _env_name(node: ast.Call) -> Optional[Tuple[str, Optional[str]]]:
    """(HM_* name, literal default or None) for an os.environ read.
    Matches `<any>.environ.get`, `<any>.getenv` (import aliases like
    `_os` included) and bare `environ.get`/`getenv`."""
    dotted = _dotted(node.func)
    leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
    is_get = dotted.endswith("environ.get") or dotted == "environ.get"
    if not (is_get or leaf == "getenv"):
        return None
    if node.args and isinstance(node.args[0], ast.Constant):
        name = node.args[0].value
        if isinstance(name, str) and name.startswith("HM_"):
            default: Optional[str] = None
            if len(node.args) > 1 and isinstance(
                node.args[1], ast.Constant
            ):
                d = node.args[1].value
                default = d if isinstance(d, str) else None
            return name, default
    return None


def _env_subscript(node: ast.Subscript) -> Optional[str]:
    """HM_* name for an `os.environ["HM_X"]` READ (Load context)."""
    if not isinstance(node.ctx, ast.Load):
        return None
    if not (
        isinstance(node.value, ast.Attribute)
        and node.value.attr == "environ"
    ):
        return None
    sl = node.slice
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str) and (
        sl.value.startswith("HM_")
    ):
        return sl.value
    return None


def _literal_prefix(node: ast.AST) -> Optional[str]:
    """The leading literal text of a metric-name expression: full
    string for a Constant, left side of a `"lit" + x` BinOp, leading
    literal of an f-string. None when nothing literal leads."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _literal_prefix(node.left)
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


# ---------------------------------------------------------------------------
# the per-file rule pass


class _FileLinter(ast.NodeVisitor):
    def __init__(
        self,
        rel: str,
        src: str,
        table: _LockTable,
        out: List[Violation],
        env_reads: Dict[str, List[Tuple[str, int, Optional[str]]]],
        guard_seen: Optional[Set[Tuple[str, str]]] = None,
    ) -> None:
        self.rel = rel
        self.relu = rel.replace(os.sep, "/")
        self.lines = src.splitlines()
        self.table = table
        self.out = out
        self.env_reads = env_reads
        self.guard_seen = guard_seen if guard_seen is not None else set()
        self.cls_stack: List[str] = []
        # (class name or None, line) per enclosing `with` item that
        # resolved to a tracked lock
        self.with_stack: List[Tuple[Optional[str], int]] = []
        self.fn_depth_at_with: List[int] = []
        self.fn_depth = 0
        self.fn_stack: List[str] = []
        # guarded-attr: self.<attr> nodes already classified as writes
        # (assignment targets, mutator receivers) — visit_Attribute
        # must not re-classify them as reads
        self._guard_done: Set[int] = set()
        self.in_pkg = _in_package(rel)
        self.is_peer = self.relu.endswith("net/peer.py")
        self.is_analysis = "/analysis/" in "/" + self.relu

    # -- emit ----------------------------------------------------------

    def hit(self, rule: str, line: int, msg: str) -> None:
        self.out.append(
            Violation(rule, self.rel, line, msg, False)
        )

    # -- structure tracking --------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()

    def _visit_fn(self, node) -> None:
        name = getattr(node, "name", "<lambda>")
        self.fn_stack.append(name)
        self.fn_depth += 1
        # a method listed in guards.REQUIRES runs its WHOLE body with
        # the named lock held (every caller acquires it — the Clang
        # REQUIRES annotation as manifest data); nested defs still
        # start from an empty held set (they may run on any thread)
        req = (
            guardsmod.REQUIRES.get((self.cls_stack[-1], name))
            if self.cls_stack
            else None
        )
        if req is not None:
            self.with_stack.append((req, node.lineno))
            self.fn_depth_at_with.append(self.fn_depth)
        self.generic_visit(node)
        if req is not None:
            self.with_stack.pop()
            self.fn_depth_at_with.pop()
        self.fn_depth -= 1
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn
    visit_Lambda = _visit_fn

    def _held(self) -> List[Tuple[Optional[str], int]]:
        """With-items lexically held at the current node — excluding
        regions opened in an OUTER function scope (a closure body does
        not run under the with that surrounds its definition)."""
        return [
            w
            for w, d in zip(self.with_stack, self.fn_depth_at_with)
            if d == self.fn_depth
        ]

    def visit_With(self, node: ast.With) -> None:
        resolved: List[Tuple[Optional[str], int]] = []
        cls_name = self.cls_stack[-1] if self.cls_stack else None
        for item in node.items:
            lock_cls = self.table.resolve(
                item.context_expr, self.rel, cls_name
            )
            if lock_cls is not None:
                resolved.append((lock_cls, item.context_expr.lineno))
        if resolved and self.in_pkg:
            self._check_order(resolved)
        for r in resolved:
            self.with_stack.append(r)
            self.fn_depth_at_with.append(self.fn_depth)
        self.generic_visit(node)
        for _ in resolved:
            self.with_stack.pop()
            self.fn_depth_at_with.pop()

    def _check_order(
        self, acquiring: List[Tuple[Optional[str], int]]
    ) -> None:
        held = [h for h in self._held() if h[0] is not None]
        for cls, line in acquiring:
            my_rank = RANKED.get(cls)
            for hcls, hline in held:
                if hcls == cls:
                    continue  # re-entrant same-class (RLock) regions
                if hcls in LEAVES and cls in RANKED and cls not in LEAVES:
                    self.hit(
                        "lock-order", line,
                        f"acquires {cls!r} inside leaf lock {hcls!r} "
                        f"(held since line {hline})",
                    )
                    continue
                hr = RANKED.get(hcls)
                if my_rank is not None and hr is not None and hr >= my_rank:
                    self.hit(
                        "lock-order", line,
                        f"acquires {cls!r} (rank {my_rank}) while "
                        f"holding {hcls!r} (rank {hr}) — inverts the "
                        f"declared hierarchy "
                        f"(analysis/hierarchy.py)",
                    )

    # -- guarded-attr (analysis/guards.py) -----------------------------

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[ast.Attribute]:
        """The `self.<attr>` Attribute node under zero or more
        subscripts (`self.x`, `self.x[k]`, `self.x[k][j]`)."""
        while isinstance(node, ast.Subscript):
            node = node.value
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node
        return None

    def _collect_target_attrs(
        self, tgt: ast.AST, out: List[ast.Attribute]
    ) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._collect_target_attrs(el, out)
            return
        if isinstance(tgt, ast.Starred):
            self._collect_target_attrs(tgt.value, out)
            return
        a = self._self_attr(tgt)
        if a is not None:
            out.append(a)

    def _guard_access(self, attr_node: ast.Attribute, write: bool) -> None:
        """Check one `self.<attr>` access against the guard manifest
        (the `guarded-attr` rule). Writes are hard errors outside the
        declared guard; reads may be excused by `atomic_read_ok`."""
        self._guard_done.add(id(attr_node))
        if not self.in_pkg or not self.cls_stack:
            return
        cls = self.cls_stack[-1]
        entry = guardsmod.guard_for(cls, attr_node.attr)
        if entry is None:
            return
        self.guard_seen.add((cls, attr_node.attr))
        if "__init__" in self.fn_stack:
            return  # not shared yet: constructor writes are exempt
        if entry.escape == "unguarded":
            return
        line = attr_node.lineno
        if entry.escape == "init_only":
            if write:
                self.hit(
                    "guarded-attr", line,
                    f"writes init-only field {cls}.{attr_node.attr} "
                    f"outside __init__ (analysis/guards.py)",
                )
            return
        held = {h for h, _ln in self._held() if h is not None}
        if entry.guard in held:
            return
        if write:
            self.hit(
                "guarded-attr", line,
                f"writes {cls}.{attr_node.attr} outside a `with` of "
                f"its declared guard {entry.guard!r} "
                f"(analysis/guards.py)",
            )
        elif entry.escape != "atomic_read_ok":
            self.hit(
                "guarded-attr", line,
                f"reads {cls}.{attr_node.attr} outside a `with` of "
                f"its declared guard {entry.guard!r} — take the lock, "
                f"or declare the read atomic_read_ok in "
                f"analysis/guards.py",
            )

    def visit_Assign(self, node: ast.Assign) -> None:
        attrs: List[ast.Attribute] = []
        for tgt in node.targets:
            self._collect_target_attrs(tgt, attrs)
        for a in attrs:
            self._guard_access(a, write=True)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        a = self._self_attr(node.target)
        if a is not None:
            self._guard_access(a, write=True)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        a = self._self_attr(node.target)
        if a is not None and node.value is not None:
            self._guard_access(a, write=True)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            a = self._self_attr(tgt)
            if a is not None:
                self._guard_access(a, write=True)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if id(node) not in self._guard_done:
            a = self._self_attr(node)
            if a is node:
                self._guard_access(node, write=not isinstance(
                    node.ctx, ast.Load
                ))
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _MUTATORS
            and isinstance(fn.value, ast.Attribute)
        ):
            # mutating the container a guarded field holds IS a write
            # to the guarded state. Direct receivers only: an element
            # access (`self._m[k].add(1)`) reaches a DIFFERENT object
            # (field-level granularity), and init_only/unguarded
            # fields hold service objects whose API may collide with
            # container-mutator names — their story is rebinding, not
            # content.
            a = self._self_attr(fn.value)
            if a is not None:
                entry = (
                    guardsmod.guard_for(self.cls_stack[-1], a.attr)
                    if self.cls_stack
                    else None
                )
                if entry is not None and entry.escape in (
                    "", "atomic_read_ok"
                ):
                    self._guard_access(a, write=True)
        if self.in_pkg:
            self._rule_raw_lock(node, name)
            self._rule_churn_send(node, name)
            self._rule_under_lock_calls(node, name)
        self._rule_env(node)
        self._rule_telemetry(node, name)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        name = _env_subscript(node)
        if name is not None:
            self.env_reads.setdefault(name, []).append(
                (self.rel, node.lineno, None)
            )
            if name not in ENV_BY_NAME:
                self.hit(
                    "env-registry", node.lineno,
                    f"reads undeclared env var {name!r} — declare it "
                    f"in analysis/envvars.py (name, default, one-line "
                    f"doc)",
                )
        self.generic_visit(node)

    def _rule_raw_lock(self, node: ast.Call, name: Optional[str]) -> None:
        if self.is_analysis:
            return
        fn = node.func
        is_threading = (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "threading"
        )
        if not is_threading:
            return
        if name in ("Lock", "RLock"):
            self.hit(
                "raw-lock", node.lineno,
                f"raw threading.{name}() — create locks via "
                f"analysis.lockdep.make_{'lock' if name == 'Lock' else 'rlock'}"
                f"(<class>) with a class declared in "
                f"analysis/hierarchy.py so runtime lockdep can see it",
            )
        elif name == "Condition" and not node.args:
            self.hit(
                "raw-lock", node.lineno,
                "bare threading.Condition() hides its lock from "
                "lockdep — use analysis.lockdep.make_condition(<class>)",
            )

    def _rule_churn_send(self, node: ast.Call, name: Optional[str]) -> None:
        if self.is_peer or name not in ("send", "open_channel"):
            return
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Attribute)
            and fn.value.attr == "connection"
        ):
            self.hit(
                "churn-send", node.lineno,
                f"direct {_dotted(fn)}(...) — `peer.connection` can "
                f"flip to None between a check and the send; "
                f"NetworkPeer.try_send is THE churn-safe idiom",
            )

    def _rule_under_lock_calls(
        self, node: ast.Call, name: Optional[str]
    ) -> None:
        held = [h for h in self._held() if h[0] is not None]
        if not held:
            return
        # engine entrypoints under a below-engine lock: the repo->engine
        # inversion (open()/Ready deadlock shape)
        if name in ENGINE_ENTRYPOINTS:
            for hcls, hline in held:
                hr = RANKED.get(hcls)
                if hr is not None and hr > _ENGINE_RANK:
                    self.hit(
                        "lock-order", node.lineno,
                        f"calls {name}() (acquires 'live.engine', rank "
                        f"{_ENGINE_RANK}) while holding {hcls!r} (rank "
                        f"{hr}, held since line {hline}) — the engine "
                        f"lock must be outermost",
                    )
        # blocking primitives under a no-block (emission) lock
        if name in BLOCKING_CALLS and any(h in NO_BLOCK for h, _ in held):
            if name == "join" and self._is_str_join(node):
                return
            holder = next(h for h, _ in held if h in NO_BLOCK)
            self.hit(
                "no-block", node.lineno,
                f"blocking call {name}() inside the {holder!r} "
                f"emission lock — a stalled emission stalls every "
                f"doc's {{compute patch -> push}} pairs",
            )

    @staticmethod
    def _is_str_join(node: ast.Call) -> bool:
        fn = node.func
        return (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Constant)
            and isinstance(fn.value.value, str)
        )

    def _rule_env(self, node: ast.Call) -> None:
        hit = _env_name(node)
        if hit is None:
            return
        name, default = hit
        self.env_reads.setdefault(name, []).append(
            (self.rel, node.lineno, default)
        )
        reg = ENV_BY_NAME.get(name)
        if reg is None:
            self.hit(
                "env-registry", node.lineno,
                f"reads undeclared env var {name!r} — declare it in "
                f"analysis/envvars.py (name, default, one-line doc)",
            )
        elif default is not None and reg.default is not None and (
            default != reg.default
        ):
            self.hit(
                "env-registry", node.lineno,
                f"{name} default {default!r} drifts from the "
                f"registered default {reg.default!r} "
                f"(analysis/envvars.py)",
            )

    def _rule_telemetry(self, node: ast.Call, name: Optional[str]) -> None:
        if name not in ("counter", "gauge", "histogram") or not node.args:
            return
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        recv = fn.value
        recv_name = (
            recv.id if isinstance(recv, ast.Name) else
            recv.attr if isinstance(recv, ast.Attribute) else None
        )
        if recv_name not in _REGISTRY_RECEIVERS:
            return
        prefix = _literal_prefix(node.args[0])
        if prefix is None:
            return  # dynamic name: the runtime assert covers it
        full_literal = isinstance(node.args[0], ast.Constant)
        ok = (
            bool(_NAME_RE.match(prefix)) if full_literal
            else bool(_PREFIX_RE.match(prefix))
        )
        if not ok:
            self.hit(
                "telemetry-name", node.lineno,
                f"series name {prefix!r} breaks the dotted "
                f"`subsystem.metric` convention (telemetry/__init__.py)"
                f" — tools/top.py groups rates by the prefix",
            )


# ---------------------------------------------------------------------------
# suppression matching


def _apply_suppressions(
    viols: List[Violation], sources: Dict[str, List[str]]
) -> List[Violation]:
    used_file_entries: Set[int] = set()
    out: List[Violation] = []
    for v in viols:
        lines = sources.get(v.path, [])
        just = _inline_allow(lines, v.line, v.rule)
        if just is not None:
            if not just.strip():
                out.append(v._replace(suppressed=False))
                out.append(
                    Violation(
                        "suppression", v.path, v.line,
                        f"inline allow({v.rule}) has no justification "
                        f"— write `# lint: allow({v.rule}) — <why>`",
                        False,
                    )
                )
                continue
            out.append(v._replace(suppressed=True, justification=just))
            continue
        matched = False
        for i, s in enumerate(suppmod.SUPPRESSIONS):
            if s.rule != v.rule:
                continue
            if not fnmatch.fnmatch(v.path.replace(os.sep, "/"), s.path_glob):
                continue
            line_txt = lines[v.line - 1] if 0 < v.line <= len(lines) else ""
            if s.contains and s.contains not in line_txt:
                continue
            used_file_entries.add(i)
            if not s.justification.strip():
                out.append(v)
                out.append(
                    Violation(
                        "suppression", "hypermerge_tpu/analysis/"
                        "suppressions.py", 1,
                        f"suppression #{i} ({s.rule} in {s.path_glob}) "
                        f"has no justification",
                        False,
                    )
                )
                matched = True
                break
            out.append(v._replace(suppressed=True,
                                  justification=s.justification))
            matched = True
            break
        if not matched:
            out.append(v)
    for i, s in enumerate(suppmod.SUPPRESSIONS):
        if i not in used_file_entries:
            out.append(
                Violation(
                    "suppression",
                    "hypermerge_tpu/analysis/suppressions.py", 1,
                    f"stale suppression #{i} ({s.rule} in "
                    f"{s.path_glob}): matches no current violation — "
                    f"delete it",
                    False,
                )
            )
    return out


def _inline_allow(
    lines: List[str], line: int, rule: str
) -> Optional[str]:
    """Justification text when line (or the line above) carries a
    matching `# lint: allow(rule)` comment; None when absent."""
    for ln in (line, line - 1):
        if 0 < ln <= len(lines):
            m = _ALLOW_RE.search(lines[ln - 1])
            if m and m.group(1) == rule:
                return m.group(2) or ""
    return None


# ---------------------------------------------------------------------------
# entry points


def lint_files(
    paths: List[str], root: Optional[str] = None
) -> List[Violation]:
    root = root or repo_root()
    # tree-wide checks (stale registry entries, README coverage) only
    # make sense when the read-scan covered the whole default file
    # set — a scoped `tools/lint.py some/file.py` run must not flag
    # every HM_* var that one file happens not to read
    whole_tree = {os.path.abspath(p) for p in paths} >= {
        os.path.abspath(p) for p in default_files(root)
    }
    table = _LockTable()
    parsed: List[Tuple[str, ast.AST, str]] = []
    out: List[Violation] = []
    sources: Dict[str, List[str]] = {}
    for p in paths:
        rel = _rel(p, root)
        try:
            with open(p, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=p)
        except (OSError, SyntaxError) as e:
            out.append(
                Violation("lock-order", rel, getattr(e, "lineno", 0) or 0,
                          f"unparseable: {e}", False)
            )
            continue
        sources[rel] = src.splitlines()
        if _in_package(rel):
            table.learn(rel, tree)
        parsed.append((rel, tree, src))
    env_reads: Dict[str, List[Tuple[str, int, Optional[str]]]] = {}
    guard_seen: Set[Tuple[str, str]] = set()
    for rel, tree, src in parsed:
        _FileLinter(rel, src, table, out, env_reads, guard_seen).visit(
            tree
        )
    if whole_tree:
        _check_env_registry(out, env_reads, root)
        _check_guards_registry(out, guard_seen, root)
    return _apply_suppressions(out, sources)


def lint_source(
    src: str, path: str = "hypermerge_tpu/_fixture.py"
) -> List[Violation]:
    """Lint one in-memory snippet (test fixtures). The path decides
    scope rules (package-only rules need a hypermerge_tpu/ path)."""
    table = _LockTable()
    tree = ast.parse(src)
    if _in_package(path):
        table.learn(path, tree)
    out: List[Violation] = []
    env_reads: Dict[str, List[Tuple[str, int, Optional[str]]]] = {}
    _FileLinter(path, src, table, out, env_reads).visit(tree)
    return _apply_suppressions(out, {path: src.splitlines()})


def lint_repo(root: Optional[str] = None) -> List[Violation]:
    root = root or repo_root()
    return lint_files(default_files(root), root)


def unsuppressed(viols: List[Violation]) -> List[Violation]:
    return [v for v in viols if not v.suppressed]


def _check_env_registry(
    out: List[Violation],
    env_reads: Dict[str, List[Tuple[str, int, Optional[str]]]],
    root: str,
) -> None:
    readme = ""
    try:
        with open(os.path.join(root, "README.md"), encoding="utf-8") as fh:
            readme = fh.read()
    except OSError:
        pass
    for var in ENV_REGISTRY:
        if var.name not in env_reads:
            out.append(
                Violation(
                    "env-registry",
                    "hypermerge_tpu/analysis/envvars.py", 1,
                    f"stale registry entry {var.name}: nothing in the "
                    f"tree reads it — delete it or wire it up",
                    False,
                )
            )
        # backticked form: the generated table renders `HM_X`, and a
        # plain substring match would let a name that prefixes another
        # (HM_FSYNC vs HM_FSYNC_MS) pass on the longer row alone
        if readme and f"`{var.name}`" not in readme:
            out.append(
                Violation(
                    "env-registry",
                    "hypermerge_tpu/analysis/envvars.py", 1,
                    f"{var.name} is registered but missing from the "
                    f"README env-var table (regenerate with "
                    f"`python tools/lint.py --env-table`)",
                    False,
                )
            )


def _check_guards_registry(
    out: List[Violation], guard_seen: Set[Tuple[str, str]], root: str
) -> None:
    """Tree-wide guard-manifest hygiene (whole-tree runs only): an
    entry no `self.<attr>` access matches is stale (renamed/deleted
    field rots silently otherwise), and every row of the generated
    guard-map table must appear verbatim in the README (the
    --guards-table mirror of the env-table drift rule; a row check —
    not a class-name check — so moving a field between escape
    classes without regenerating is also drift)."""
    for (cls, attr) in sorted(guardsmod.BY_CLS_ATTR):
        if (cls, attr) not in guard_seen:
            out.append(
                Violation(
                    "guarded-attr",
                    "hypermerge_tpu/analysis/guards.py", 1,
                    f"stale guard entry {cls}.{attr}: no such "
                    f"attribute access in the tree — delete it or fix "
                    f"the name",
                    False,
                )
            )
    readme = ""
    try:
        with open(os.path.join(root, "README.md"), encoding="utf-8") as fh:
            readme = fh.read()
    except OSError:
        pass
    if readme:
        for row in guardsmod.markdown_table().splitlines()[2:]:
            if row not in readme:
                out.append(
                    Violation(
                        "guarded-attr",
                        "hypermerge_tpu/analysis/guards.py", 1,
                        f"README guard-map table is missing the row "
                        f"{row!r} (regenerate with "
                        f"`python tools/lint.py --guards-table`)",
                        False,
                    )
                )
