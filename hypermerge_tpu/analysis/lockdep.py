"""Runtime lockdep: opt-in instrumented locks + potential-deadlock
detection (HM_LOCKDEP=1).

Every lock in the package is created through `make_lock` /
`make_rlock` / `make_condition` with a lock-class name declared in
`analysis/hierarchy.py`. With lockdep OFF (the default) the factories
return plain `threading` primitives — zero overhead, nothing imported
beyond stdlib. With lockdep ON they return `DepLock` wrappers that
record, per thread, the acquisition order of every tracked lock and
maintain one process-global CLASS-level lock-order graph — the Linux
lockdep idea: a single observed A-held-while-acquiring-B edge is
enough to prove the order, so an inverted B->A acquisition on ANY
later run (or the other branch of a race) is reported as a potential
deadlock *without the deadlock ever firing*.

Checks, all reported through `report()` / `assert_clean()`:

- **cycles**: the class graph gains edge (A, B) whenever B is acquired
  with A held; a path B -> ... -> A at insertion time is a potential
  deadlock cycle (two threads interleaving the two chains can wedge).
- **order**: acquiring a RANKED class while holding an equal-or-lower
  ranked one inverts the declared hierarchy (hierarchy.RANKED).
- **leaf**: acquiring ANY tracked lock while holding a leaf class.
- **blocking**: `blocking(kind)` is called from the package's blocking
  seams (io_fsync, sqlite commit, socket sendall, thread joins, queue
  first-waits); reaching one with a no-block class held (the emission
  locks) is a held-across-blocking-call violation.
- **self-deadlock**: re-acquiring a held non-reentrant Lock.
- **unknown-class**: a factory call naming a class missing from the
  manifest (keeps hierarchy.py in sync with the code).

The fault harnesses double as race drivers: tests/test_chaos.py and
tests/test_live.py run their suites with lockdep enabled and assert a
clean graph at teardown (see `tests/test_analysis.py` for the
detector's own fixtures).
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from .hierarchy import ALLOWED_EDGES, BY_NAME, LEAVES, NO_BLOCK, RANKED

_MAX_REPORTS = 200  # bound memory on a pathological run

_enabled = os.environ.get("HM_LOCKDEP", "0") == "1"
# HM_RACEDEP=1: Eraser-style lockset race detection over the guard
# manifest (analysis/guards.py) — see the "racedep" section below.
# Implies lockdep (the per-thread held stacks ARE the lockset input).
_race_enabled = os.environ.get("HM_RACEDEP", "0") == "1"


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Flip instrumentation for locks created AFTER this call (tests:
    enable before constructing the repos under test). Existing plain
    locks stay untracked; existing DepLocks stay tracked."""
    global _enabled
    _enabled = on


class _State:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        # class -> set of classes observed acquired while it was held
        self.graph: Dict[str, set] = {}
        self.edge_sites: Dict[Tuple[str, str], str] = {}
        self.cycles: List[Dict[str, Any]] = []
        self.violations: List[Dict[str, Any]] = []
        self._seen_cycles: set = set()
        self._seen_viol: set = set()


_state = _State()
_tls = threading.local()


def _held() -> List[list]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _site(skip: int = 3) -> str:
    """Short code-site witness: innermost non-lockdep frames."""
    frames = traceback.extract_stack()[:-skip]
    tail = frames[-3:]
    return " <- ".join(
        f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}"
        for f in reversed(tail)
    )


def _record_violation(kind: str, key: tuple, msg: str) -> None:
    with _state.lock:
        if key in _state._seen_viol:
            return
        _state._seen_viol.add(key)
        if len(_state.violations) < _MAX_REPORTS:
            _state.violations.append(
                {"kind": kind, "msg": msg, "site": _site(skip=4)}
            )


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> ... -> dst in the class graph (caller holds
    _state.lock)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _state.graph.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _add_edge(holder: str, acquired: str) -> None:
    with _state.lock:
        succ = _state.graph.setdefault(holder, set())
        if acquired in succ:
            return
        # cycle check BEFORE inserting: a path acquired -> ... -> holder
        # plus this new edge closes a loop
        path = _find_path(acquired, holder)
        succ.add(acquired)
        site = _site(skip=4)
        _state.edge_sites.setdefault((holder, acquired), site)
        if path is not None:
            key = frozenset(path)
            if key not in _state._seen_cycles:
                _state._seen_cycles.add(key)
                if len(_state.cycles) < _MAX_REPORTS:
                    _state.cycles.append(
                        {
                            "cycle": path + [acquired],
                            "edge": (holder, acquired),
                            "site": site,
                            "prior_sites": [
                                _state.edge_sites.get((a, b), "?")
                                for a, b in zip(path, path[1:])
                            ],
                        }
                    )


class DepLock:
    """Instrumented Lock/RLock with per-thread order tracking. Quacks
    like the wrapped primitive, including the private Condition
    protocol (`_is_owned`/`_release_save`/`_acquire_restore`) so
    `threading.Condition(DepLock(...))` works."""

    __slots__ = ("name", "_inner", "_reentrant")

    def __init__(self, name: str, reentrant: bool) -> None:
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        if name not in BY_NAME:
            _record_violation(
                "unknown-class",
                ("unknown", name),
                f"lock class {name!r} is not declared in "
                f"analysis/hierarchy.py",
            )

    # -- bookkeeping ---------------------------------------------------

    def _pre_acquire(self, held: List[list]) -> None:
        name = self.name
        my_rank = RANKED.get(name)
        for hname, hinst, _cnt in held:
            if hinst is self:
                continue
            if (hname, name) in ALLOWED_EDGES:
                continue
            if (
                hname in LEAVES
                and name in RANKED
                and name not in LEAVES
            ):
                # scoped to the ranked world: terminal unranked
                # latches (native load-once, fault recorders) are
                # pure sinks a leaf may touch — cycle detection still
                # covers them
                _record_violation(
                    "leaf",
                    ("leaf", hname, name),
                    f"acquiring {name!r} while holding leaf lock "
                    f"{hname!r}",
                )
            hr = RANKED.get(hname)
            if my_rank is not None and hr is not None and hr >= my_rank:
                _record_violation(
                    "order",
                    ("order", hname, name),
                    f"acquiring {name!r} (rank {my_rank}) while "
                    f"holding {hname!r} (rank {hr}) — inverts the "
                    f"declared hierarchy",
                )
            _add_edge(hname, name)

    # -- lock protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held()
        entry = None
        for e in held:
            if e[1] is self:
                entry = e
                break
        if entry is None:
            self._pre_acquire(held)
        elif not self._reentrant:
            _record_violation(
                "self-deadlock",
                ("self", self.name),
                f"re-acquiring held non-reentrant lock {self.name!r} "
                f"on the same thread",
            )
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if entry is not None and self._reentrant:
                entry[2] += 1
            else:
                held.append([self.name, self, 1])
        return ok

    def release(self) -> None:
        self._inner.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                held[i][2] -= 1
                if held[i][2] == 0:
                    del held[i]
                return

    def locked(self) -> bool:
        inner = getattr(self._inner, "locked", None)
        return bool(inner()) if inner is not None else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DepLock {self.name!r} {self._inner!r}>"

    # -- Condition protocol --------------------------------------------

    def _is_owned(self):
        inner = getattr(self._inner, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        """Condition.wait: fully release (all recursion levels) and pop
        our held entry — while waiting, the thread does NOT hold this
        lock and must not contribute edges with it."""
        count = 0
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                count = held[i][2]
                del held[i]
                break
        inner = getattr(self._inner, "_release_save", None)
        if inner is not None:
            st = inner()
        else:
            self._inner.release()
            st = None
        return (st, count)

    def _acquire_restore(self, saved) -> None:
        st, count = saved
        self._pre_acquire(_held())
        inner = getattr(self._inner, "_acquire_restore", None)
        if inner is not None:
            inner(st)
        else:
            self._inner.acquire()
        _held().append([self.name, self, max(count, 1)])


# ---------------------------------------------------------------------------
# factories — the ONE way the package creates locks (linter rule
# raw-lock enforces this)


def make_lock(name: str):
    """A non-reentrant lock of the given manifest class."""
    return DepLock(name, False) if _enabled else threading.Lock()


def make_rlock(name: str):
    """A re-entrant lock of the given manifest class."""
    return DepLock(name, True) if _enabled else threading.RLock()


def make_condition(name: str, lock=None):
    """A Condition whose underlying lock is tracked under `name` (or
    the caller's already-tracked `lock`)."""
    if lock is None:
        lock = make_rlock(name)
    return threading.Condition(lock)


# ---------------------------------------------------------------------------
# blocking seams


class _NoopSeam:
    """Shared do-nothing seam (lockdep off / nothing held)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass


_NOOP_SEAM = _NoopSeam()

# per-lock-class blocking-debt counters (`lock.held_blocking_ms.<cls>`
# with the class dots flattened): the time the package spent inside a
# blocking primitive while HOLDING each lock class. This is the
# ROADMAP write-plane gate as a NUMBER — the feed-append/clock-commit
# debt under `live.engine` must read zero before the per-doc emission
# split lands. Lazy telemetry import: registry.py imports this module.
_blk_handles: Dict[str, Any] = {}


def _blk_counter(cls_name: str):
    h = _blk_handles.get(cls_name)
    if h is None:
        from .. import telemetry

        h = _blk_handles[cls_name] = telemetry.counter(
            "lock.held_blocking_ms." + cls_name.replace(".", "_")
        )
    return h


class _BlockingSeam:
    """Times one blocking primitive and charges the wall to every lock
    class the calling thread held at entry."""

    __slots__ = ("classes", "t0")

    def __init__(self, classes: Tuple[str, ...]) -> None:
        self.classes = classes
        self.t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        dt_ms = (time.perf_counter() - self.t0) * 1e3
        for c in self.classes:
            _blk_counter(c).add(dt_ms)


def blocking(kind: str, detail: str = ""):
    """Called from the package's blocking primitives (fsync, sqlite
    commit, socket sendall, joins, first-waits). With lockdep on,
    reaching one while holding a no-block class (the emission locks)
    is recorded as a held-across-blocking-call violation.

    Returns a context manager: seams that wrap the blocking operation
    in `with blocking(...)` additionally accumulate its wall time into
    the per-held-lock-class `lock.held_blocking_ms.*` counters (the
    write-plane blocking-debt series bench/top/BASELINE track). A bare
    call keeps the violation check only."""
    if not _enabled:
        return _NOOP_SEAM
    held = getattr(_tls, "held", None)
    if not held:
        return _NOOP_SEAM
    noblock = False
    for hname, _inst, _cnt in held:
        if hname in NO_BLOCK:
            noblock = True
            _record_violation(
                "blocking",
                ("blocking", hname, kind),
                f"blocking call {kind!r}{f' ({detail})' if detail else ''}"
                f" while holding no-block lock {hname!r}",
            )
    if noblock:
        from .. import telemetry

        telemetry.instant("lock.held_blocking", cat="lock")
    return _BlockingSeam(
        tuple(dict.fromkeys(h[0] for h in held))
    )


def held_classes() -> List[str]:
    """Lock classes the CURRENT thread holds (debug aid)."""
    return [e[0] for e in getattr(_tls, "held", ())]


# ---------------------------------------------------------------------------
# racedep (HM_RACEDEP=1): Eraser lockset detection over the guard
# manifest. Every non-`unguarded` attribute declared in
# analysis/guards.py is wrapped in a data descriptor; each access
# intersects the per-(object, attribute) candidate lockset with the
# accessing thread's held stack. The Eraser state machine: an
# attribute starts EXCLUSIVE to its creating thread (no refinement —
# init writes hold nothing, by design); the first access from a
# SECOND thread starts the candidate set at that thread's held locks;
# every later access intersects. An empty candidate set once the
# attribute is written-while-shared means NO lock consistently guards
# it — reported with the first shared-access site AND the violating
# site, without the race ever firing. `atomic_read_ok` attributes
# track writes only (their lone reads are declared GIL-atomic);
# `init_only` attributes report any write once a second thread has
# touched the object.


class _AttrTrack:
    __slots__ = ("owner", "state", "lockset", "first_site", "reported")

    EXCL, SHARED, SHARED_MOD = 0, 1, 2

    def __init__(self, owner: int) -> None:
        self.owner = owner
        self.state = _AttrTrack.EXCL
        self.lockset: Optional[set] = None
        self.first_site = ""
        self.reported = False


_race_lock = threading.Lock()  # guards every _AttrTrack transition;
# inner order is _race_lock -> _state.lock (never reversed)
_race_n = 0
_race_sample_n = 1
_race_installed: List[Tuple[type, str]] = []
_SKIP = object()  # sampled-out marker


def _race_sample() -> int:
    try:
        return max(1, int(os.environ.get("HM_RACEDEP_SAMPLE", "1")))
    except ValueError:
        return 1


class _RaceAttr:
    """Data descriptor wrapping one declared guarded attribute. The
    value itself still lives in the instance `__dict__` (the
    descriptor shadows it for lookups), so instrumented objects keep
    their exact state and uninstalling restores plain access."""

    __slots__ = ("cls", "attr", "guard", "escape", "skey")

    def __init__(self, cls: str, attr: str, guard: str,
                 escape: str) -> None:
        self.cls = cls
        self.attr = attr
        self.guard = guard
        self.escape = escape
        self.skey = "_racedep__" + attr

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            val = obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(
                f"{type(obj).__name__!r} object has no attribute "
                f"{self.attr!r}"
            ) from None
        # declared-atomic reads and init-only reads are free; guarded
        # reads participate in the lockset
        if _race_enabled and self.escape == "":
            _race_access(self, obj, write=False)
        return val

    def __set__(self, obj, value) -> None:
        obj.__dict__[self.attr] = value
        if _race_enabled:
            _race_access(self, obj, write=True)

    def __delete__(self, obj) -> None:
        try:
            del obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(self.attr) from None
        if _race_enabled:
            _race_access(self, obj, write=True)


def _race_access(desc: _RaceAttr, obj, write: bool) -> None:
    ident = threading.get_ident()
    held = frozenset(e[0] for e in getattr(_tls, "held", ()))
    hit = False
    with _race_lock:
        tr = obj.__dict__.get(desc.skey)
        if tr is _SKIP:
            return
        if tr is None:
            global _race_n
            _race_n += 1
            if _race_sample_n > 1 and (_race_n % _race_sample_n):
                obj.__dict__[desc.skey] = _SKIP
                return
            obj.__dict__[desc.skey] = _AttrTrack(ident)
            return
        if tr.reported:
            return
        if tr.state == _AttrTrack.EXCL:
            if tr.owner == ident:
                return
            # second thread: refinement begins (Eraser's
            # exclusive->shared transition)
            if desc.escape == "init_only":
                if write:
                    hit = _race_report(desc, tr, held, write)
                return
            tr.lockset = set(held)
            tr.state = (
                _AttrTrack.SHARED_MOD if write else _AttrTrack.SHARED
            )
            # drop _site/_race_access/__get__|__set__ so the witness
            # is the accessing code line
            tr.first_site = _site(skip=3)
        else:
            if desc.escape == "init_only":
                if write:
                    hit = _race_report(desc, tr, held, write)
                return
            tr.lockset &= held
            if write:
                tr.state = _AttrTrack.SHARED_MOD
        if tr.state == _AttrTrack.SHARED_MOD and not tr.lockset:
            hit = _race_report(desc, tr, held, write)
    if hit:
        from .. import telemetry

        telemetry.counter("lock.racedep_violations").add(1)
        telemetry.instant("lock.racedep_violation", cat="lock")


def _race_report(
    desc: _RaceAttr, tr: _AttrTrack, held: frozenset, write: bool
) -> bool:
    """Record one lockset violation (caller holds _race_lock). True
    when it was newly recorded (kind+class+attr dedup)."""
    tr.reported = True
    if desc.escape == "init_only":
        kind, why = "lockset", (
            f"init-only field {desc.cls}.{desc.attr} written after "
            f"the object was shared across threads"
        )
    else:
        kind, why = "lockset", (
            f"{desc.cls}.{desc.attr} (declared guard {desc.guard!r}): "
            f"candidate lockset is EMPTY — no lock consistently "
            f"guards it. This {'write' if write else 'read'} holds "
            f"{sorted(held) or 'no locks'}; first shared access at "
            f"{tr.first_site or '<exclusive phase>'}"
        )
    key = ("lockset", desc.cls, desc.attr)
    before = len(_state.violations)
    _record_violation(kind, key, why)
    return len(_state.violations) != before


def racedep_enabled() -> bool:
    return _race_enabled


def install_racedep() -> int:
    """Instrument every non-`unguarded` attribute of the guard
    manifest's classes (analysis/guards.py) with lockset descriptors.
    Idempotent; returns the number of attributes wrapped. Enables
    lockdep too — the per-thread held stacks are the lockset input,
    so only factory-made locks created AFTER this call participate
    (enable before constructing the repos under test, exactly like
    lockdep)."""
    global _race_enabled, _race_sample_n
    import importlib

    from . import guards

    enable(True)
    _race_enabled = True
    _race_sample_n = _race_sample()
    wrapped = {(c, a) for c, a in _race_installed}
    n = 0
    for (cls_name, attr), entry in sorted(guards.BY_CLS_ATTR.items()):
        if entry.escape == "unguarded":
            continue
        mod = importlib.import_module(entry.module)
        cls = getattr(mod, cls_name)
        if (cls, attr) in wrapped:
            continue
        cur = cls.__dict__.get(attr)
        if isinstance(cur, _RaceAttr):
            continue
        if cur is not None:
            raise ValueError(
                f"guard manifest names {cls_name}.{attr} but the class "
                f"defines it at class level (property/default) — "
                f"racedep can only wrap instance attributes"
            )
        setattr(
            cls, attr, _RaceAttr(cls_name, attr, entry.guard,
                                 entry.escape)
        )
        _race_installed.append((cls, attr))
        n += 1
    return n


def uninstall_racedep() -> None:
    """Remove the descriptors (test teardown): instance values were
    always stored in `__dict__`, so plain attribute access resumes."""
    global _race_enabled
    _race_enabled = False
    for cls, attr in _race_installed:
        try:
            delattr(cls, attr)
        except AttributeError:
            pass
    _race_installed.clear()


def maybe_install_racedep() -> None:
    """HM_RACEDEP=1 activation hook (called from RepoBackend
    construction — a daemon or bench run needs no test fixture)."""
    if os.environ.get("HM_RACEDEP", "0") == "1" and not _race_installed:
        install_racedep()


# ---------------------------------------------------------------------------
# reporting


def report() -> Dict[str, Any]:
    """The global observation so far: every class-order edge with its
    first witness site, potential cycles, and violations."""
    with _state.lock:
        pairs = sorted(
            (a, b) for a, succ in _state.graph.items() for b in succ
        )
        edges = [
            {"from": a, "to": b, "site": _state.edge_sites.get((a, b), "?")}
            for a, b in pairs
        ]
        return {
            "enabled": _enabled,
            "edges": edges,
            "cycles": [dict(c) for c in _state.cycles],
            "violations": [dict(v) for v in _state.violations],
        }


def reset() -> None:
    """Drop every observation (test isolation). Held-lock state of
    live threads is intentionally kept — resetting mid-acquisition
    would corrupt release bookkeeping."""
    with _state.lock:
        _state.graph.clear()
        _state.edge_sites.clear()
        _state.cycles.clear()
        _state.violations.clear()
        _state._seen_cycles.clear()
        _state._seen_viol.clear()


def assert_clean(
    allow_kinds: Tuple[str, ...] = (), msg: str = ""
) -> None:
    """Raise AssertionError when any potential cycle or violation was
    observed (tests call this at teardown). `allow_kinds` filters
    violation kinds a specific suite tolerates."""
    rep = report()
    viol = [v for v in rep["violations"] if v["kind"] not in allow_kinds]
    if rep["cycles"] or viol:
        lines = [msg or "lockdep observations:"]
        for c in rep["cycles"]:
            lines.append(
                f"  potential deadlock cycle: {' -> '.join(c['cycle'])}"
                f"\n    closing edge at {c['site']}"
            )
        for v in viol:
            lines.append(f"  {v['kind']}: {v['msg']}\n    at {v['site']}")
        raise AssertionError("\n".join(lines))
